// Native reader pool: async segment reads with batch completion.
//
// C++ equivalent of the reference's AIOHandler (libaio wrapper with a
// completion thread, reference src/CommUtils/AIOHandler.cc:80-235) and
// of the per-disk thread-pool reader in the orphaned AsyncIO/ directory
// (reference src/AsyncIO/AsyncReaderManager.cc:16-50, AsyncReaderThread.cc
// :36-86 — compiled but never wired; here the capability IS wired, into
// uda_tpu.mofserver.data_engine).
//
// Two backends behind ONE submit/get_events ABI (PARITY C15):
//
//  - io_uring (backend 1): compiled in when the build host carries the
//    uapi header (<linux/io_uring.h>), selected at pool creation only
//    when the RUNNING kernel accepts io_uring_setup — a 4.4-class host
//    gets ENOSYS and silently takes the worker pool. One ring doorbell
//    submits a whole batch of reads (the RDMAbox batched-submission
//    lesson, arXiv:2104.12197); a reaper thread drains CQEs into the
//    same completion queue uda_pool_get_events serves.
//  - worker pool (backend 0): plain pread worker threads + a completion
//    queue (the io_getevents analogue, same min_nr/timeout shape as
//    AIOHandler.cc:152-235). uda_pool_submit_batch still amortizes the
//    lock round and wakes workers once per batch.

#include <chrono>
#include <cstdint>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include <errno.h>
#include <unistd.h>

#if defined(__linux__) && defined(__has_include)
#if __has_include(<linux/io_uring.h>)
#define UDA_HAVE_IOURING 1
#include <linux/io_uring.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <sys/uio.h>
#ifndef __NR_io_uring_setup
#define __NR_io_uring_setup 425
#endif
#ifndef __NR_io_uring_enter
#define __NR_io_uring_enter 426
#endif
#endif
#endif

namespace {

struct Job {
  int fd;
  int64_t offset;
  int64_t len;
  uint8_t* dst;
  uint64_t tag;
};

struct Event {
  uint64_t tag;
  int64_t result;  // bytes read, or -errno
};

#ifdef UDA_HAVE_IOURING

int sys_io_uring_setup(unsigned entries, struct io_uring_params* p) {
  return static_cast<int>(syscall(__NR_io_uring_setup, entries, p));
}

int sys_io_uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                       unsigned flags) {
  return static_cast<int>(
      syscall(__NR_io_uring_enter, fd, to_submit, min_complete, flags,
              nullptr, 0));
}

// Minimal raw-syscall ring (no liburing dependency — the image bakes in
// no extra libraries). SQ/CQ mmaps + release/acquire on the shared
// head/tail indices, IORING_OP_READV SQEs (5.1+, the widest-supported
// read op) with one heap iovec per in-flight job.
struct Ring {
  int fd = -1;
  unsigned entries = 0;
  void* sq_ptr = nullptr;
  size_t sq_len = 0;
  void* cq_ptr = nullptr;
  size_t cq_len = 0;
  struct io_uring_sqe* sqes = nullptr;
  size_t sqes_len = 0;
  unsigned* sq_head = nullptr;
  unsigned* sq_tail = nullptr;
  unsigned* sq_mask = nullptr;
  unsigned* sq_array = nullptr;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  unsigned* cq_mask = nullptr;
  struct io_uring_cqe* cqes = nullptr;

  bool init(unsigned want_entries) {
    struct io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    fd = sys_io_uring_setup(want_entries, &p);
    if (fd < 0) return false;  // ENOSYS/EPERM: the worker pool serves
    entries = p.sq_entries;
    sq_len = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cq_len = p.cq_off.cqes + p.cq_entries * sizeof(struct io_uring_cqe);
    bool single_mmap = (p.features & IORING_FEAT_SINGLE_MMAP) != 0;
    if (single_mmap && cq_len > sq_len) sq_len = cq_len;
    sq_ptr = mmap(nullptr, sq_len, PROT_READ | PROT_WRITE,
                  MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING);
    if (sq_ptr == MAP_FAILED) { sq_ptr = nullptr; return false; }
    if (single_mmap) {
      cq_ptr = sq_ptr;
      cq_len = 0;  // owned by the sq mapping
    } else {
      cq_ptr = mmap(nullptr, cq_len, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_CQ_RING);
      if (cq_ptr == MAP_FAILED) { cq_ptr = nullptr; return false; }
    }
    sqes_len = p.sq_entries * sizeof(struct io_uring_sqe);
    sqes = static_cast<struct io_uring_sqe*>(
        mmap(nullptr, sqes_len, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQES));
    if (sqes == MAP_FAILED) { sqes = nullptr; return false; }
    char* sq = static_cast<char*>(sq_ptr);
    sq_head = reinterpret_cast<unsigned*>(sq + p.sq_off.head);
    sq_tail = reinterpret_cast<unsigned*>(sq + p.sq_off.tail);
    sq_mask = reinterpret_cast<unsigned*>(sq + p.sq_off.ring_mask);
    sq_array = reinterpret_cast<unsigned*>(sq + p.sq_off.array);
    char* cq = static_cast<char*>(cq_ptr);
    cq_head = reinterpret_cast<unsigned*>(cq + p.cq_off.head);
    cq_tail = reinterpret_cast<unsigned*>(cq + p.cq_off.tail);
    cq_mask = reinterpret_cast<unsigned*>(cq + p.cq_off.ring_mask);
    cqes = reinterpret_cast<struct io_uring_cqe*>(cq + p.cq_off.cqes);
    return true;
  }

  void destroy() {
    if (sqes) munmap(sqes, sqes_len);
    if (cq_ptr && cq_ptr != sq_ptr && cq_len) munmap(cq_ptr, cq_len);
    if (sq_ptr) munmap(sq_ptr, sq_len);
    if (fd >= 0) close(fd);
    fd = -1;
  }

  // caller holds the pool mutex; returns false when the SQ is full
  bool push_sqe(uint8_t opcode, int job_fd, const void* addr,
                unsigned len, int64_t off, uint64_t user_data) {
    unsigned head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
    unsigned tail = *sq_tail;
    if (tail - head >= entries) return false;
    unsigned idx = tail & *sq_mask;
    struct io_uring_sqe* sqe = &sqes[idx];
    std::memset(sqe, 0, sizeof(*sqe));
    sqe->opcode = opcode;
    sqe->fd = job_fd;
    sqe->addr = reinterpret_cast<uint64_t>(addr);
    sqe->len = len;
    sqe->off = static_cast<uint64_t>(off);
    sqe->user_data = user_data;
    sq_array[idx] = idx;
    __atomic_store_n(sq_tail, tail + 1, __ATOMIC_RELEASE);
    return true;
  }
};

#endif  // UDA_HAVE_IOURING

struct Pool {
  std::vector<std::thread> workers;
  std::deque<Job> jobs;
  std::deque<Event> events;
  std::mutex mu;
  std::condition_variable job_cv;
  std::condition_variable event_cv;
  bool stopping = false;
  int backend = 0;  // 0 = worker pool, 1 = io_uring

#ifdef UDA_HAVE_IOURING
  Ring ring;
  std::thread reaper;
  // tag -> iovec kept alive until its CQE lands (READV semantics)
  std::unordered_map<uint64_t, struct iovec*> iovs;
  static constexpr uint64_t kStopTag = ~0ull;

  // caller holds mu; falls back to a synchronous pread when the SQ is
  // full (bounded by the server's batch cap, so effectively never).
  // Returns whether an SQE was actually pushed — the caller must ring
  // the doorbell for exactly the pushed count (io_uring_enter consumes
  // only real SQEs; over-asking would spin forever on r == 0).
  bool ring_submit_locked(const Job& job) {
    struct iovec* iov = new struct iovec;
    iov->iov_base = job.dst;
    iov->iov_len = static_cast<size_t>(job.len);
    if (!ring.push_sqe(IORING_OP_READV, job.fd, iov, 1, job.offset,
                       job.tag)) {
      delete iov;
      sync_read_locked(job);
      return false;
    }
    iovs[job.tag] = iov;
    return true;
  }

  void ring_doorbell(unsigned n) {
    // outside mu: the kernel copies SQEs on enter, the reaper owns CQs
    while (n > 0) {
      int r = sys_io_uring_enter(ring.fd, n, 0, 0);
      if (r < 0) {
        if (errno == EINTR) continue;
        // the ring died under us (should not happen post-init): fail
        // every in-flight tag so no waiter hangs
        std::lock_guard<std::mutex> lk(mu);
        for (auto& kv : iovs) {
          delete kv.second;
          events.push_back(Event{kv.first, -EIO});
        }
        iovs.clear();
        event_cv.notify_all();
        return;
      }
      n -= static_cast<unsigned>(r);
    }
  }

  void reap() {
    for (;;) {
      int r = sys_io_uring_enter(ring.fd, 0, 1, IORING_ENTER_GETEVENTS);
      if (r < 0 && errno != EINTR) return;
      bool saw_stop = false;
      {
        std::lock_guard<std::mutex> lk(mu);
        unsigned head = *ring.cq_head;
        unsigned tail = __atomic_load_n(ring.cq_tail, __ATOMIC_ACQUIRE);
        while (head != tail) {
          struct io_uring_cqe* cqe = &ring.cqes[head & *ring.cq_mask];
          if (cqe->user_data == kStopTag) {
            saw_stop = true;
          } else {
            auto it = iovs.find(cqe->user_data);
            if (it != iovs.end()) {
              delete it->second;
              iovs.erase(it);
              events.push_back(Event{cqe->user_data,
                                     static_cast<int64_t>(cqe->res)});
            }
            // unknown tag: already failed by the doorbell error path
            // (synthetic -EIO) — a late real CQE must not produce a
            // DUPLICATE event for a tag the consumer settled
          }
          ++head;
        }
        __atomic_store_n(ring.cq_head, head, __ATOMIC_RELEASE);
      }
      event_cv.notify_all();
      if (saw_stop) return;
    }
  }
#endif  // UDA_HAVE_IOURING

  // caller holds mu: a read executed inline (SQ overflow spill), its
  // completion pushed directly
  void sync_read_locked(const Job& job) {
    int64_t done = 0;
    int64_t result = 0;
    while (done < job.len) {
      ssize_t r = pread(job.fd, job.dst + done,
                        static_cast<size_t>(job.len - done),
                        static_cast<off_t>(job.offset + done));
      if (r < 0) {
        if (errno == EINTR) continue;
        result = -static_cast<int64_t>(errno);
        break;
      }
      if (r == 0) break;  // EOF
      done += r;
    }
    if (result == 0) result = done;
    events.push_back(Event{job.tag, result});
    event_cv.notify_all();
  }

  void worker() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu);
        job_cv.wait(lk, [&] { return stopping || !jobs.empty(); });
        if (stopping && jobs.empty()) return;
        job = jobs.front();
        jobs.pop_front();
      }
      int64_t done = 0;
      int64_t result = 0;
      while (done < job.len) {
        ssize_t r = pread(job.fd, job.dst + done,
                          static_cast<size_t>(job.len - done),
                          static_cast<off_t>(job.offset + done));
        if (r < 0) {
          if (errno == EINTR) continue;
          result = -static_cast<int64_t>(errno);
          break;
        }
        if (r == 0) break;  // EOF
        done += r;
      }
      if (result == 0) result = done;
      {
        std::lock_guard<std::mutex> lk(mu);
        events.push_back(Event{job.tag, result});
      }
      event_cv.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* uda_pool_create(int threads) {
  if (threads < 1) threads = 1;
  Pool* p = new Pool();
#ifdef UDA_HAVE_IOURING
  // the io_uring rung: taken only when the RUNNING kernel accepts the
  // setup syscall (compiled-in != available; 4.4-class hosts land in
  // the worker pool below)
  if (p->ring.init(1024)) {
    p->backend = 1;
    p->reaper = std::thread([p] { p->reap(); });
    return p;
  }
  p->ring.destroy();
#endif
  for (int i = 0; i < threads; ++i) {
    p->workers.emplace_back([p] { p->worker(); });
  }
  return p;
}

void uda_pool_destroy(void* pool) {
  Pool* p = static_cast<Pool*>(pool);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stopping = true;
  }
#ifdef UDA_HAVE_IOURING
  if (p->backend == 1) {
    // wake the reaper blocked in GETEVENTS with a NOP completion; a
    // full SQ drains as in-flight reads complete, so retry-until-push
    // terminates
    for (;;) {
      {
        std::lock_guard<std::mutex> lk(p->mu);
        if (p->ring.push_sqe(IORING_OP_NOP, -1, nullptr, 0, 0,
                             Pool::kStopTag)) {
          break;
        }
      }
      usleep(1000);
    }
    p->ring_doorbell(1);
    if (p->reaper.joinable()) p->reaper.join();
    for (auto& kv : p->iovs) delete kv.second;
    p->iovs.clear();
    p->ring.destroy();
    delete p;
    return;
  }
#endif
  p->job_cv.notify_all();
  for (auto& t : p->workers) t.join();
  delete p;
}

// 0 = pread worker pool, 1 = io_uring — which rung of the PARITY C15
// ladder this pool actually runs (the Python side records it as the
// io.backend metric label).
int uda_pool_backend(void* pool) {
  return static_cast<Pool*>(pool)->backend;
}

int uda_pool_submit(void* pool, int fd, int64_t offset, int64_t len,
                    uint8_t* dst, uint64_t tag) {
  Pool* p = static_cast<Pool*>(pool);
#ifdef UDA_HAVE_IOURING
  unsigned pushed = 0;
#endif
  {
    std::lock_guard<std::mutex> lk(p->mu);
    if (p->stopping) return -1;
#ifdef UDA_HAVE_IOURING
    if (p->backend == 1) {
      if (p->ring_submit_locked(Job{fd, offset, len, dst, tag})) {
        pushed = 1;
      }
    } else {
      p->jobs.push_back(Job{fd, offset, len, dst, tag});
    }
#else
    p->jobs.push_back(Job{fd, offset, len, dst, tag});
#endif
  }
#ifdef UDA_HAVE_IOURING
  if (p->backend == 1) {
    if (pushed) p->ring_doorbell(pushed);
    return 0;
  }
#endif
  p->job_cv.notify_one();
  return 0;
}

// Batched submission (the C15 submit_batch half): N reads enter under
// ONE lock round and ONE doorbell/notify — io_uring submits the whole
// SQE span with a single io_uring_enter, the worker pool enqueues all
// jobs then wakes every worker once. Per-job isolation is the event
// contract: each tag completes (or fails) independently.
int uda_pool_submit_batch(void* pool, int n, const int32_t* fds,
                          const int64_t* offsets, const int64_t* lens,
                          uint8_t* const* dsts, const uint64_t* tags) {
  Pool* p = static_cast<Pool*>(pool);
  if (n <= 0) return 0;
#ifdef UDA_HAVE_IOURING
  unsigned pushed = 0;
#endif
  {
    std::lock_guard<std::mutex> lk(p->mu);
    if (p->stopping) return -1;
    for (int i = 0; i < n; ++i) {
      Job job{fds[i], offsets[i], lens[i], dsts[i], tags[i]};
#ifdef UDA_HAVE_IOURING
      if (p->backend == 1) {
        if (p->ring_submit_locked(job)) ++pushed;
        continue;
      }
#endif
      p->jobs.push_back(job);
    }
  }
#ifdef UDA_HAVE_IOURING
  if (p->backend == 1) {
    // the doorbell rings for the SQEs actually pushed — spilled jobs
    // already completed synchronously under the lock
    if (pushed) p->ring_doorbell(pushed);
    return 0;
  }
#endif
  p->job_cv.notify_all();
  return 0;
}

// Drain completions: blocks until >= min_events are available or the
// timeout (seconds) elapses; returns the number written to out_*.
int uda_pool_get_events(void* pool, uint64_t* out_tags, int64_t* out_results,
                        int max_events, int min_events, double timeout_s) {
  Pool* p = static_cast<Pool*>(pool);
  std::unique_lock<std::mutex> lk(p->mu);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                      std::chrono::duration<double>(timeout_s));
  p->event_cv.wait_until(lk, deadline, [&] {
    return static_cast<int>(p->events.size()) >= min_events || p->stopping;
  });
  int n = 0;
  while (n < max_events && !p->events.empty()) {
    out_tags[n] = p->events.front().tag;
    out_results[n] = p->events.front().result;
    p->events.pop_front();
    ++n;
  }
  return n;
}

}  // extern "C"
