// Native reader pool: async segment reads with batch completion.
//
// C++ equivalent of the reference's AIOHandler (libaio wrapper with a
// completion thread, reference src/CommUtils/AIOHandler.cc:80-235) and
// of the per-disk thread-pool reader in the orphaned AsyncIO/ directory
// (reference src/AsyncIO/AsyncReaderManager.cc:16-50, AsyncReaderThread.cc
// :36-86 — compiled but never wired; here the capability IS wired, into
// uda_tpu.mofserver.data_engine). Plain pread worker threads + a
// completion queue drained by uda_pool_get_events (the io_getevents
// analogue, same min_nr/timeout shape as AIOHandler.cc:152-235).

#include <chrono>
#include <cstdint>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

#include <errno.h>
#include <unistd.h>

namespace {

struct Job {
  int fd;
  int64_t offset;
  int64_t len;
  uint8_t* dst;
  uint64_t tag;
};

struct Event {
  uint64_t tag;
  int64_t result;  // bytes read, or -errno
};

struct Pool {
  std::vector<std::thread> workers;
  std::deque<Job> jobs;
  std::deque<Event> events;
  std::mutex mu;
  std::condition_variable job_cv;
  std::condition_variable event_cv;
  bool stopping = false;

  void worker() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu);
        job_cv.wait(lk, [&] { return stopping || !jobs.empty(); });
        if (stopping && jobs.empty()) return;
        job = jobs.front();
        jobs.pop_front();
      }
      int64_t done = 0;
      int64_t result = 0;
      while (done < job.len) {
        ssize_t r = pread(job.fd, job.dst + done,
                          static_cast<size_t>(job.len - done),
                          static_cast<off_t>(job.offset + done));
        if (r < 0) {
          if (errno == EINTR) continue;
          result = -static_cast<int64_t>(errno);
          break;
        }
        if (r == 0) break;  // EOF
        done += r;
      }
      if (result == 0) result = done;
      {
        std::lock_guard<std::mutex> lk(mu);
        events.push_back(Event{job.tag, result});
      }
      event_cv.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* uda_pool_create(int threads) {
  if (threads < 1) threads = 1;
  Pool* p = new Pool();
  for (int i = 0; i < threads; ++i) {
    p->workers.emplace_back([p] { p->worker(); });
  }
  return p;
}

void uda_pool_destroy(void* pool) {
  Pool* p = static_cast<Pool*>(pool);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stopping = true;
  }
  p->job_cv.notify_all();
  for (auto& t : p->workers) t.join();
  delete p;
}

int uda_pool_submit(void* pool, int fd, int64_t offset, int64_t len,
                    uint8_t* dst, uint64_t tag) {
  Pool* p = static_cast<Pool*>(pool);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    if (p->stopping) return -1;
    p->jobs.push_back(Job{fd, offset, len, dst, tag});
  }
  p->job_cv.notify_one();
  return 0;
}

// Drain completions: blocks until >= min_events are available or the
// timeout (seconds) elapses; returns the number written to out_*.
int uda_pool_get_events(void* pool, uint64_t* out_tags, int64_t* out_results,
                        int max_events, int min_events, double timeout_s) {
  Pool* p = static_cast<Pool*>(pool);
  std::unique_lock<std::mutex> lk(p->mu);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                      std::chrono::duration<double>(timeout_s));
  p->event_cv.wait_until(lk, deadline, [&] {
    return static_cast<int>(p->events.size()) >= min_events || p->stopping;
  });
  int n = 0;
  while (n < max_events && !p->events.empty()) {
    out_tags[n] = p->events.front().tag;
    out_results[n] = p->events.front().result;
    p->events.pop_front();
    ++n;
  }
  return n;
}

}  // extern "C"
