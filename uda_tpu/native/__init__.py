"""ctypes bindings for the native runtime (libuda_tpu_native.so).

Gracefully degrades: when the shared library hasn't been built (``make
-C uda_tpu/native``) or ``uda.tpu.use.native`` is off, callers fall back
to the pure-Python implementations in uda_tpu.utils.ifile. The Python
and native codecs are parity-tested against each other
(tests/test_native.py) — the Python side is the semantic reference, the
C++ side is the hot path (the reference's equivalent split: Java plugin
logic vs libuda.so, SURVEY §1 L4/L5).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from uda_tpu.utils.errors import StorageError
from uda_tpu.utils.ifile import RecordBatch
from uda_tpu.utils.logging import get_logger

__all__ = ["available", "build", "crack_native", "crack_partial_native",
           "decode_vlongs_native", "write_records_native", "frame_batch",
           "iter_framed_chunks", "ReadPool", "kway_supported",
           "kway_merge_paths"]

log = get_logger()

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libuda_tpu_native.so")
_lib = None
_lib_stale = False  # cached "old .so lacks newer symbols" outcome
_lib_lock = threading.RLock()


def _load():
    global _lib, _lib_stale
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_stale or not os.path.exists(_SO):
            return None
        try:
            lib = _bind(ctypes.CDLL(_SO))
        except AttributeError as e:
            # a stale .so from an older build lacks newer symbols; fall
            # back to pure Python rather than poisoning every caller.
            # Cached (and cleared by a successful build()) so hot paths
            # don't re-dlopen + re-warn per call.
            log.warn(f"native library is stale ({e}); rebuild with "
                     f"`make -C uda_tpu/native` — using pure Python")
            _lib_stale = True
            return None
        _lib = lib
        return lib


def _bind(lib):
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.uda_crack.restype = ctypes.c_int64
    lib.uda_crack.argtypes = [u8p, ctypes.c_int64, i64p, i64p, i64p,
                              i64p, ctypes.c_int64, i64p,
                              ctypes.POINTER(ctypes.c_int32)]
    lib.uda_decode_vlongs.restype = ctypes.c_int64
    lib.uda_decode_vlongs.argtypes = [u8p, ctypes.c_int64, i64p,
                                      ctypes.c_int64]
    lib.uda_pool_create.restype = ctypes.c_void_p
    lib.uda_pool_create.argtypes = [ctypes.c_int]
    lib.uda_pool_destroy.argtypes = [ctypes.c_void_p]
    lib.uda_pool_submit.restype = ctypes.c_int
    lib.uda_pool_submit.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.c_int64, ctypes.c_int64,
                                    u8p, ctypes.c_uint64]
    lib.uda_pool_get_events.restype = ctypes.c_int
    lib.uda_pool_get_events.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), i64p,
        ctypes.c_int, ctypes.c_int, ctypes.c_double]
    lib.uda_pool_backend.restype = ctypes.c_int
    lib.uda_pool_backend.argtypes = [ctypes.c_void_p]
    lib.uda_pool_submit_batch.restype = ctypes.c_int
    lib.uda_pool_submit_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
        i64p, i64p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.uda_write_records.restype = ctypes.c_int64
    lib.uda_write_records.argtypes = [u8p, i64p, i64p, i64p, i64p,
                                      ctypes.c_int64, u8p,
                                      ctypes.c_int64, ctypes.c_int32]
    lib.uda_kway_create.restype = ctypes.c_void_p
    lib.uda_kway_create.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int64, i64p]
    lib.uda_kway_next_block.restype = ctypes.c_int64
    lib.uda_kway_next_block.argtypes = [ctypes.c_void_p, u8p,
                                        ctypes.c_int64, i64p]
    lib.uda_kway_destroy.argtypes = [ctypes.c_void_p]
    szp = ctypes.POINTER(ctypes.c_size_t)
    lib.uda_lzo1x_decompress_safe.restype = ctypes.c_int
    lib.uda_lzo1x_decompress_safe.argtypes = [u8p, ctypes.c_size_t,
                                              u8p, szp]
    lib.uda_lzo1x_1_compress.restype = ctypes.c_int
    lib.uda_lzo1x_1_compress.argtypes = [u8p, ctypes.c_size_t, u8p, szp]
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.uda_merge_rows.restype = None
    lib.uda_merge_rows.argtypes = [u32p, ctypes.c_int64, u32p,
                                   ctypes.c_int64, ctypes.c_int32, u32p]
    lib.uda_gather_spans.restype = None
    lib.uda_gather_spans.argtypes = [u8p, i64p, i64p, ctypes.c_int64,
                                     u8p, i64p]
    return lib


def available() -> bool:
    return _load() is not None


_build_attempted = False
_build_ok = False


def build(quiet: bool = True) -> bool:
    """Best-effort build of the shared library (g++ via make), run at
    most once per process — even when the .so already exists, so a
    STALE library (older than its sources, e.g. after a pull) is
    rebuilt instead of crashing symbol binds. The outcome (either way)
    is remembered so later callers don't re-spawn make per DataEngine
    construction. Thread-safe via the lib lock; concurrent PROCESSES
    are safe because the Makefile links to a temp file and renames
    (dlopen never sees a half-written .so) and make itself no-ops when
    the library is current."""
    global _build_attempted, _build_ok, _lib, _lib_stale
    with _lib_lock:
        if _build_attempted:
            return _build_ok
        _build_attempted = True
        try:
            subprocess.run(["make", "-C", _DIR],
                           check=True, capture_output=quiet)
            _lib = None       # rebind in case make refreshed a stale .so
            _lib_stale = False
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            if os.path.exists(_SO):
                log.warn(f"native rebuild failed; keeping the existing "
                         f"library: {e}")
                _build_ok = available()
                return _build_ok
            log.warn(f"native build failed, using pure-Python codec: {e}")
            _build_ok = False
            return False
        _build_ok = available()
        return _build_ok


def _u8ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i64ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def crack_partial_native(data) -> tuple[RecordBatch, int, bool]:
    """Native twin of ifile.crack_partial (same return contract)."""
    lib = _load()
    arr = (np.frombuffer(data, np.uint8) if not isinstance(data, np.ndarray)
           else np.ascontiguousarray(data, np.uint8))
    n = len(arr)
    cap = max(16, n // 2 + 1)  # a record is >= 2 bytes of framing
    ko = np.empty(cap, np.int64)
    kl = np.empty(cap, np.int64)
    vo = np.empty(cap, np.int64)
    vl = np.empty(cap, np.int64)
    consumed = ctypes.c_int64(0)
    saw_eof = ctypes.c_int32(0)
    count = lib.uda_crack(_u8ptr(arr), n, _i64ptr(ko), _i64ptr(kl),
                          _i64ptr(vo), _i64ptr(vl), cap,
                          ctypes.byref(consumed), ctypes.byref(saw_eof))
    if count == -1:
        raise StorageError("corrupt record framing (native crack)")
    if count == -2:  # capacity overflow: cannot happen with cap >= n/2+1
        raise StorageError("native crack capacity overflow")
    c = int(count)
    batch = RecordBatch(arr, ko[:c].copy(), kl[:c].copy(), vo[:c].copy(),
                        vl[:c].copy())
    return batch, int(consumed.value), bool(saw_eof.value)


def crack_native(data, expect_eof: bool = True) -> RecordBatch:
    """Native twin of ifile.crack."""
    batch, consumed, saw_eof = crack_partial_native(data)
    n = len(data)
    if expect_eof and not saw_eof:
        raise StorageError("IFile segment missing EOF marker (native)")
    if not saw_eof and consumed != n:
        raise StorageError(f"truncated IFile segment at offset {consumed}")
    return batch


def decode_vlongs_native(data, count: int = -1) -> np.ndarray:
    lib = _load()
    arr = (np.frombuffer(data, np.uint8) if not isinstance(data, np.ndarray)
           else np.ascontiguousarray(data, np.uint8))
    cap = len(arr) if count < 0 else count
    out = np.empty(max(cap, 1), np.int64)
    n = lib.uda_decode_vlongs(_u8ptr(arr), len(arr), _i64ptr(out), cap)
    if count >= 0 and n < count:
        raise IndexError("truncated VLong stream (native)")
    return out[:n].copy()


def write_records_native(batch: RecordBatch, write_eof: bool = True) -> bytes:
    """Native twin of ifile.write_records over a RecordBatch: re-frames
    the batch's records as one IFile byte stream (the emit hot path)."""
    lib = _load()
    n = batch.num_records
    # worst case: 20 framing bytes per record (two max-width VLongs)
    cap = int(batch.key_len.sum() + batch.val_len.sum()) + 20 * n + 2
    out = np.empty(cap, np.uint8)
    data = np.ascontiguousarray(batch.data, np.uint8)
    wrote = lib.uda_write_records(
        _u8ptr(data),
        _i64ptr(np.ascontiguousarray(batch.key_off)),
        _i64ptr(np.ascontiguousarray(batch.key_len)),
        _i64ptr(np.ascontiguousarray(batch.val_off)),
        _i64ptr(np.ascontiguousarray(batch.val_len)),
        n, _u8ptr(out), cap, 1 if write_eof else 0)
    if wrote < 0:
        raise StorageError("native write_records capacity overflow")
    return out[:wrote].tobytes()


def frame_batch(batch: RecordBatch, write_eof: bool = True) -> bytes:
    """Frame a whole RecordBatch as one IFile byte stream, native when
    enabled+built (one C pass over the columns — the emit/spill hot path
    the reference runs in C++, reference src/Merger/StreamRW.cc:151-225),
    pure Python otherwise. The two produce identical bytes
    (parity-tested in tests/test_native.py). Honors the
    ``uda.tpu.use.native`` kill switch (ifile.set_native_enabled), like
    every other native dispatch."""
    from uda_tpu.utils.ifile import native_enabled

    if native_enabled() and build():
        return write_records_native(batch, write_eof=write_eof)
    import io

    from uda_tpu.utils.ifile import IFileWriter

    out = io.BytesIO()
    w = IFileWriter(out)
    for k, v in batch.iter_records():
        w.append(k, v)
    if write_eof:
        w.close()
    return out.getvalue()


def iter_framed_chunks(batch: RecordBatch, chunk_records: int = 1 << 16,
                       write_eof: bool = True):
    """Frame a RecordBatch in bounded chunks: yields IFile byte pieces
    whose concatenation equals ``frame_batch(batch)``. Peak transient
    memory is one chunk's framed bytes, so multi-GB spills stream to
    their file instead of materializing wholesale."""
    n = batch.num_records
    for start in range(0, n, max(1, chunk_records)):
        stop = min(start + chunk_records, n)
        sub = RecordBatch(batch.data, batch.key_off[start:stop],
                          batch.key_len[start:stop],
                          batch.val_off[start:stop],
                          batch.val_len[start:stop])
        yield frame_batch(sub, write_eof=False)
    if write_eof:
        from uda_tpu.utils.ifile import EOF_MARKER

        yield EOF_MARKER


# KeyType.name -> (key_mode, key_param) for the native loser-tree merge
# (merge.cc). The mode family is exactly the reference CompareFunc
# dispatch (CompareFunc.cc:70-113): identity memcmp, Text VInt-skip,
# BytesWritable 4-byte skip, plus this framework's sign-flip numeric
# variants. Comparators outside this table (user-registered) fall back
# to the Python heap merge — the reference's unsupported-comparator
# posture (CompareFunc.cc:95-113).
_KWAY_MODES = {
    "raw": (0, 0), "boolean": (0, 0), "byte": (0, 0), "short": (0, 0),
    "int": (0, 0), "long": (0, 0),
    "text": (1, 0),
    "bytes": (2, 0), "ibytes": (2, 0),
    "int_numeric": (3, 4), "long_numeric": (3, 8),
}

_KWAY_ERRORS = {-1: "corrupt record framing / missing EOF marker",
                -4: "read failure"}


def kway_supported(kt) -> bool:
    """Whether the native merge implements this KeyType's comparator."""
    return kt.name in _KWAY_MODES


def kway_merge_paths(paths, kt, block_bytes: int = 1 << 20,
                     buffer_size: int = 1 << 20, write_eof: bool = True):
    """Streaming k-way merge of sorted IFile spill files: yields framed
    byte blocks whose concatenation is the merged record stream
    (+ EOF marker when ``write_eof``) — byte-identical to
    ``ops.merge.merge_record_streams`` over the same files re-framed.
    The C++ loser tree (merge.cc, the reference MergeQueue.h:276-427
    analogue) does all comparator and framing work; peak memory is one
    read buffer per file + one output block."""
    from uda_tpu.utils.ifile import EOF_MARKER

    mode, param = _KWAY_MODES[kt.name]
    if not paths:
        if write_eof:
            yield EOF_MARKER
        return
    lib = _load()
    if lib is None:
        raise StorageError("native library not built")
    arr = (ctypes.c_char_p * len(paths))(
        *[os.fsencode(p) for p in paths])
    err = ctypes.c_int64(0)
    h = lib.uda_kway_create(arr, len(paths), mode, param, buffer_size,
                            ctypes.byref(err))
    if not h:
        reason = _KWAY_ERRORS.get(int(err.value), "open failed")
        raise StorageError(f"native kway merge over {list(paths)}: "
                           f"{reason}")
    try:
        cap = block_bytes
        out = np.empty(cap, np.uint8)
        need = ctypes.c_int64(0)
        while True:
            n = lib.uda_kway_next_block(h, _u8ptr(out), cap,
                                        ctypes.byref(need))
            if n == -3:  # one record larger than the block: grow
                cap = max(cap * 2, int(need.value))
                out = np.empty(cap, np.uint8)
                continue
            if n < 0:
                raise StorageError(
                    f"native kway merge: "
                    f"{_KWAY_ERRORS.get(int(n), f'error {n}')}")
            if n == 0:
                break
            yield out[:n].tobytes()
        if write_eof:
            yield EOF_MARKER
    finally:
        lib.uda_kway_destroy(h)


def gather_spans_native(src: np.ndarray, src_off: np.ndarray,
                        lens: np.ndarray, dst: np.ndarray,
                        dst_off: np.ndarray) -> bool:
    """Per-record memcpy gather: dst[dst_off_i:+len_i] = src[src_off_i:
    +len_i]. The byte-movement core of the streaming interleave / slab
    gather (the numpy expand-index fallback moves 8 bytes of index per
    byte of payload). Returns False when the library isn't available."""
    lib = _load()
    if lib is None:
        return False
    # dst is written through its raw pointer: coercion would write into
    # a discarded copy, so demand the right layout outright; the C loop
    # is bounds-unchecked, so offset arrays must agree on n
    if dst.dtype != np.uint8 or not dst.flags["C_CONTIGUOUS"]:
        raise ValueError("gather destination must be contiguous uint8")
    n = src_off.shape[0]
    if lens.shape[0] != n or dst_off.shape[0] != n:
        raise ValueError(f"span arrays disagree: {n} offsets, "
                         f"{lens.shape[0]} lengths, "
                         f"{dst_off.shape[0]} destinations")
    # the C loop is a bounds-unchecked memcpy: corrupt spans (e.g. a
    # non-monotonic run offset sidecar producing negative lengths) must
    # fail HERE like the numpy fallback would, not scribble memory
    if n and (int(lens.min()) < 0
              or int((src_off + lens).max()) > src.size
              or int(src_off.min()) < 0 or int(dst_off.min()) < 0
              or int((dst_off + lens).max()) > dst.size):
        raise ValueError("gather spans out of bounds")
    src = np.ascontiguousarray(src, np.uint8)
    lib.uda_gather_spans(
        _u8ptr(src), _i64ptr(np.ascontiguousarray(src_off, np.int64)),
        _i64ptr(np.ascontiguousarray(lens, np.int64)), n,
        _u8ptr(dst), _i64ptr(np.ascontiguousarray(dst_off, np.int64)))
    return True


def merge_rows_native(a: np.ndarray, b: np.ndarray) -> Optional[np.ndarray]:
    """Linear lexicographic merge of two sorted uint32 row matrices
    (ties to ``a``): the host-engine twin of the Pallas merge-path
    kernel, used by the overlap run forest's CPU fallback. Returns None
    when the native library isn't available (caller re-lexsorts)."""
    lib = _load()
    if lib is None:
        return None
    a = np.ascontiguousarray(a, np.uint32)
    b = np.ascontiguousarray(b, np.uint32)
    assert a.ndim == 2 and b.ndim == 2 and a.shape[1] == b.shape[1]
    out = np.empty((a.shape[0] + b.shape[0], a.shape[1]), np.uint32)

    def u32(arr):
        return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))

    lib.uda_merge_rows(u32(a), a.shape[0], u32(b), b.shape[0],
                       a.shape[1], u32(out))
    return out


def merge_rows_native_into(a: np.ndarray, b: np.ndarray,
                           out: np.ndarray) -> bool:
    """merge_rows_native writing into a caller-owned ``out`` buffer
    (must be C-contiguous uint32 with a.shape[0]+b.shape[0] rows).
    Reusing merge outputs matters on this path: the overlap forest's
    merge traffic is k*log2(k) segment-loads, and a fresh np.empty per
    merge page-faults every output byte (the PR 6 large-alloc lesson) —
    the staging pipeline leases outputs from a buffer pool instead.
    Returns False when the native library isn't available."""
    lib = _load()
    if lib is None:
        return False
    assert a.flags.c_contiguous and b.flags.c_contiguous \
        and out.flags.c_contiguous
    assert out.shape[0] == a.shape[0] + b.shape[0] \
        and out.shape[1] == a.shape[1] == b.shape[1]

    def u32(arr):
        return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))

    lib.uda_merge_rows(u32(a), a.shape[0], u32(b), b.shape[0],
                       a.shape[1], u32(out))
    return True


class ReadPool:
    """Async read pool over the native worker threads — the AIOHandler
    submit/get_events contract (reference AIOHandler.cc:122-235)."""

    def __init__(self, threads: int = 2):
        lib = _load()
        if lib is None:
            raise StorageError("native library not built")
        self._lib = lib
        self._pool = lib.uda_pool_create(threads)
        self._lock = threading.Lock()
        self._next_tag = 0
        self._pending: dict[int, tuple[np.ndarray, object]] = {}

    def backend(self) -> str:
        """Which PARITY C15 rung this pool runs: "io_uring" when the
        ring backend was compiled in AND the running kernel accepted
        io_uring_setup, else "pool" (pread worker threads)."""
        if not self._pool:
            return "pool"
        return ("io_uring"
                if self._lib.uda_pool_backend(self._pool) == 1
                else "pool")

    def submit(self, fd: int, offset: int, length: int):
        """Returns a tag; the destination buffer is allocated here and
        returned by poll() with the completion."""
        buf = np.empty(length, np.uint8)
        with self._lock:
            tag = self._next_tag
            self._next_tag += 1
            self._pending[tag] = (buf, None)
        rc = self._lib.uda_pool_submit(self._pool, fd, offset, length,
                                       _u8ptr(buf), tag)
        if rc != 0:
            with self._lock:
                del self._pending[tag]
            raise StorageError("submit on stopped native pool")
        return tag

    def submit_batch(self, jobs) -> list:
        """Batched submission (the C15 submit_batch half): every
        ``(fd, offset, length)`` job enters the native pool in ONE
        call — one lock round / ring doorbell for the whole burst.
        Returns the tags in job order; completions ride poll() like
        single submits (per-tag isolation)."""
        n = len(jobs)
        if n == 0:
            return []
        bufs = [np.empty(length, np.uint8) for _, _, length in jobs]
        fds = (ctypes.c_int32 * n)(*[fd for fd, _, _ in jobs])
        offs = (ctypes.c_int64 * n)(*[off for _, off, _ in jobs])
        lens = (ctypes.c_int64 * n)(*[length for _, _, length in jobs])
        dsts = (ctypes.POINTER(ctypes.c_uint8) * n)(
            *[_u8ptr(b) for b in bufs])
        with self._lock:
            tags = list(range(self._next_tag, self._next_tag + n))
            self._next_tag += n
            for tag, buf in zip(tags, bufs):
                self._pending[tag] = (buf, None)
        ctags = (ctypes.c_uint64 * n)(*tags)
        rc = self._lib.uda_pool_submit_batch(self._pool, n, fds, offs,
                                             lens, dsts, ctags)
        if rc != 0:
            with self._lock:
                for tag in tags:
                    self._pending.pop(tag, None)
            raise StorageError("submit_batch on stopped native pool")
        return tags

    def poll(self, min_events: int = 1, timeout: float = 5.0
             ) -> list[tuple[int, object]]:
        """Drain completions: [(tag, result)] where result is the data
        sliced to the bytes actually read, or a StorageError for a failed
        read (per-tag: one bad read never poisons other requests)."""
        max_events = 256
        tags = (ctypes.c_uint64 * max_events)()
        results = (ctypes.c_int64 * max_events)()
        n = self._lib.uda_pool_get_events(self._pool, tags, results,
                                          max_events, min_events, timeout)
        out: list[tuple[int, object]] = []
        for i in range(n):
            tag = int(tags[i])
            res = int(results[i])
            with self._lock:
                ent = self._pending.pop(tag, None)
            if ent is None:
                # duplicate/stale completion (a tag already settled by
                # an error path): dropping it beats killing the router
                # thread that every native read in the process shares
                continue
            buf, _ = ent
            if res < 0:
                out.append((tag, StorageError(
                    f"native read failed: errno {-res}")))
            else:
                out.append((tag, buf[:res]))
        return out

    def close(self) -> None:
        if self._pool:
            self._lib.uda_pool_destroy(self._pool)
            self._pool = None

    def __enter__(self) -> "ReadPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
