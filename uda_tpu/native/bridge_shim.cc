// libuda_tpu_bridge.so — the native embedding surface of the uda_tpu
// bridge (the role the reference's libuda.so JNI layer plays,
// reference src/UdaBridge.cc).
//
// The reference exposes 4 native down-calls (startNative, doCommandNative,
// reduceExitMsgNative, setLogLevelNative; UdaBridge.cc:187-333) and 6
// up-calls into the host runtime (fetchOverMessage, dataFromUda,
// getPathUda, getConfData, logToJava, failureInUda; UdaBridge.cc:138-170,
// 516-522).  This shim re-creates that contract as a plain C ABI so any
// native host — a C++ service, a JVM through JNA/FFI, or a test driver —
// can embed the TPU engine:
//
//   down-calls:  uda_bridge_start / uda_bridge_do_command /
//                uda_bridge_reduce_exit / uda_bridge_set_log_level
//   up-calls:    the function pointers of uda_callbacks_t
//
// Internally the shim embeds CPython and drives uda_tpu.bridge.UdaBridge;
// the up-call glue is a C-defined Python type whose methods forward to
// the registered C function pointers (the inverse of the reference's
// cached jmethodID table, UdaBridge.cc:110-174).  GIL discipline mirrors
// the reference's JNI attach/detach rules (UdaUtil.cc:26-95): every
// down-call takes the GIL; every up-call RELEASES it around the C
// callback so a host callback may re-enter the bridge without
// deadlocking.
//
// Error contract: down-calls return 0 on success, -1 on Python-level
// failure (the exception text is routed to the log_to callback when
// registered, else stderr) — the fallback-to-vanilla signal of the
// reference (UdaBridge.cc:506-530) additionally arrives through the
// failure_in_uda up-call exactly as in the Python API.

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>

extern "C" {

typedef struct uda_index_record {
  char path[4096];
  long long start_offset;
  long long raw_length;
  long long part_length;
} uda_index_record_t;

typedef struct uda_callbacks {
  void *ctx;
  void (*fetch_over_message)(void *ctx);
  void (*data_from_uda)(void *ctx, const char *data, long long len);
  // return 0 and fill *rec on success, nonzero on failure
  int (*get_path_uda)(void *ctx, const char *job_id, const char *map_id,
                      int reduce_id, uda_index_record_t *rec);
  // copy the value (or dflt) into out (cap bytes incl. NUL)
  void (*get_conf_data)(void *ctx, const char *name, const char *dflt,
                        char *out, int cap);
  void (*log_to)(void *ctx, int level, const char *message);
  void (*failure_in_uda)(void *ctx, const char *what);
} uda_callbacks_t;

}  // extern "C"

namespace {

std::mutex g_mu;
uda_callbacks_t g_cbs;           // copied at start()
bool g_have_cbs = false;
PyObject *g_bridge = nullptr;    // uda_tpu.bridge.UdaBridge instance

void report_error(const char *where) {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  PyObject *s = value ? PyObject_Str(value) : nullptr;
  const char *msg = s ? PyUnicode_AsUTF8(s) : "unknown python error";
  char buf[1024];
  snprintf(buf, sizeof buf, "uda_tpu bridge shim: %s failed: %s", where,
           msg ? msg : "?");
  if (g_have_cbs && g_cbs.log_to) {
    Py_BEGIN_ALLOW_THREADS
    g_cbs.log_to(g_cbs.ctx, /*lsERROR=*/2, buf);
    Py_END_ALLOW_THREADS
  } else {
    fprintf(stderr, "%s\n", buf);
  }
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  PyErr_Clear();
}

// ---- the up-call forwarder: a C-defined Python type ----------------------
// Instances satisfy the UdaCallable protocol (uda_tpu/bridge/bridge.py);
// each method releases the GIL around the C callback.

struct Forwarder {
  PyObject_HEAD
};

PyObject *fw_fetch_over_message(PyObject *, PyObject *) {
  if (g_cbs.fetch_over_message) {
    Py_BEGIN_ALLOW_THREADS
    g_cbs.fetch_over_message(g_cbs.ctx);
    Py_END_ALLOW_THREADS
  }
  Py_RETURN_NONE;
}

PyObject *fw_data_from_uda(PyObject *, PyObject *args) {
  Py_buffer view;
  long long length = 0;
  if (!PyArg_ParseTuple(args, "y*L", &view, &length)) return nullptr;
  if (g_cbs.data_from_uda) {
    const char *data = static_cast<const char *>(view.buf);
    long long n = length < (long long)view.len ? length : (long long)view.len;
    Py_BEGIN_ALLOW_THREADS
    g_cbs.data_from_uda(g_cbs.ctx, data, n);
    Py_END_ALLOW_THREADS
  }
  PyBuffer_Release(&view);
  Py_RETURN_NONE;
}

PyObject *fw_get_path_uda(PyObject *, PyObject *args) {
  const char *job = nullptr, *map = nullptr;
  int reduce_id = 0;
  if (!PyArg_ParseTuple(args, "ssi", &job, &map, &reduce_id)) return nullptr;
  if (!g_cbs.get_path_uda) {
    PyErr_SetString(PyExc_RuntimeError,
                    "no get_path_uda callback registered");
    return nullptr;
  }
  uda_index_record_t rec;
  memset(&rec, 0, sizeof rec);
  int rc = 1;
  Py_BEGIN_ALLOW_THREADS
  rc = g_cbs.get_path_uda(g_cbs.ctx, job, map, reduce_id, &rec);
  Py_END_ALLOW_THREADS
  if (rc != 0) {
    PyErr_Format(PyExc_RuntimeError, "get_path_uda callback failed (%d)", rc);
    return nullptr;
  }
  // Build an uda_tpu IndexRecord (the IndexRecordBridge fields of the
  // reference, plugins/shared/.../IndexRecordBridge.java); positional
  // order is (start_offset, raw_length, part_length, path)
  PyObject *mod = PyImport_ImportModule("uda_tpu.mofserver");
  if (!mod) return nullptr;
  PyObject *cls = PyObject_GetAttrString(mod, "IndexRecord");
  Py_DECREF(mod);
  if (!cls) return nullptr;
  PyObject *out = PyObject_CallFunction(cls, "LLLs", rec.start_offset,
                                        rec.raw_length, rec.part_length,
                                        rec.path);
  Py_DECREF(cls);
  return out;
}

PyObject *fw_get_conf_data(PyObject *, PyObject *args) {
  const char *name = nullptr, *dflt = nullptr;
  if (!PyArg_ParseTuple(args, "ss", &name, &dflt)) return nullptr;
  if (!g_cbs.get_conf_data) return PyUnicode_FromString(dflt ? dflt : "");
  char buf[4096];
  buf[0] = '\0';
  Py_BEGIN_ALLOW_THREADS
  g_cbs.get_conf_data(g_cbs.ctx, name, dflt ? dflt : "", buf, sizeof buf);
  Py_END_ALLOW_THREADS
  buf[sizeof buf - 1] = '\0';
  return PyUnicode_FromString(buf);
}

PyObject *fw_log_to(PyObject *, PyObject *args) {
  int level = 0;
  const char *msg = nullptr;
  if (!PyArg_ParseTuple(args, "is", &level, &msg)) return nullptr;
  if (g_cbs.log_to) {
    Py_BEGIN_ALLOW_THREADS
    g_cbs.log_to(g_cbs.ctx, level, msg);
    Py_END_ALLOW_THREADS
  }
  Py_RETURN_NONE;
}

PyObject *fw_failure_in_uda(PyObject *, PyObject *args) {
  PyObject *err = nullptr;
  if (!PyArg_ParseTuple(args, "O", &err)) return nullptr;
  if (g_cbs.failure_in_uda) {
    PyObject *s = PyObject_Str(err);
    const char *what = s ? PyUnicode_AsUTF8(s) : "?";
    Py_BEGIN_ALLOW_THREADS
    g_cbs.failure_in_uda(g_cbs.ctx, what ? what : "?");
    Py_END_ALLOW_THREADS
    Py_XDECREF(s);
  }
  Py_RETURN_NONE;
}

PyMethodDef fw_methods[] = {
    {"fetch_over_message", fw_fetch_over_message, METH_NOARGS, nullptr},
    {"data_from_uda", fw_data_from_uda, METH_VARARGS, nullptr},
    {"get_path_uda", fw_get_path_uda, METH_VARARGS, nullptr},
    {"get_conf_data", fw_get_conf_data, METH_VARARGS, nullptr},
    {"log_to", fw_log_to, METH_VARARGS, nullptr},
    {"failure_in_uda", fw_failure_in_uda, METH_VARARGS, nullptr},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject fw_type = {
    PyVarObject_HEAD_INIT(nullptr, 0) /* name */ "uda_tpu_shim.Forwarder",
    sizeof(Forwarder),
};

// ---- lifecycle -----------------------------------------------------------

bool ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // Embedders configure the interpreter through an env hook (e.g.
    // forcing the CPU backend in tests): exec'd once, before any
    // uda_tpu import.
    const char *boot = getenv("UDA_TPU_PY_BOOTSTRAP");
    bool ok = true;
    if (boot && *boot) {
      if (PyRun_SimpleString(boot) != 0) {
        fprintf(stderr, "uda_tpu bridge shim: bootstrap failed\n");
        ok = false;
      }
    }
    // Py_Initialize leaves this thread holding the GIL; release it or
    // every Python thread the bridge spawns (merge thread, engine
    // workers) deadlocks the moment the embedder blocks in C. All
    // entry points re-acquire via PyGILState_Ensure.
    PyEval_SaveThread();
    return ok;
  }
  return true;
}

}  // namespace

extern "C" {

// start the bridge in the given role (reference startNative,
// UdaBridge.cc:187-263). argv uses the reference's short-option channel
// ("-w", "8", ...). Callbacks may be NULL (then only local-dir
// resolution works). Returns 0 on success.
int uda_bridge_start(int is_net_merger, int argc, const char **argv,
                     const uda_callbacks_t *cbs) {
  std::lock_guard<std::mutex> lk(g_mu);
  if (!ensure_python()) return -1;
  PyGILState_STATE st = PyGILState_Ensure();
  int rc = -1;
  PyObject *mod = nullptr, *cls = nullptr, *lst = nullptr, *fwd = nullptr,
           *res = nullptr;
  do {
    if (g_bridge) {
      // restart: stop the previous bridge's threads BEFORE touching
      // g_cbs — the old merge thread reads g_cbs concurrently (with the
      // GIL released around its up-calls), so swapping callbacks under
      // a live bridge would hand old data to the new embedder's ctx or
      // call through a half-written pointer. reduce_exit joins the
      // merge thread and stops the engine (bridge.py reduce_exit).
      PyObject *r = PyObject_CallMethod(g_bridge, "reduce_exit", nullptr);
      if (!r) report_error("uda_bridge_start (stopping previous bridge)");
      Py_XDECREF(r);
      Py_CLEAR(g_bridge);
    }
    if (cbs) {
      g_cbs = *cbs;
      g_have_cbs = true;
    } else {
      memset(&g_cbs, 0, sizeof g_cbs);
      g_have_cbs = false;
    }
    if (fw_type.tp_methods == nullptr) {
      fw_type.tp_methods = fw_methods;
      fw_type.tp_flags = Py_TPFLAGS_DEFAULT;
      fw_type.tp_new = PyType_GenericNew;
      if (PyType_Ready(&fw_type) != 0) break;
    }
    mod = PyImport_ImportModule("uda_tpu.bridge");
    if (!mod) break;
    cls = PyObject_GetAttrString(mod, "UdaBridge");
    if (!cls) break;
    g_bridge = PyObject_CallNoArgs(cls);  // previous cleared above
    if (!g_bridge) break;
    lst = PyList_New(argc);
    if (!lst) break;
    for (int i = 0; i < argc; i++)
      PyList_SET_ITEM(lst, i, PyUnicode_FromString(argv[i] ? argv[i] : ""));
    fwd = g_have_cbs ? PyObject_CallNoArgs((PyObject *)&fw_type) : Py_NewRef(Py_None);
    if (!fwd) break;
    res = PyObject_CallMethod(g_bridge, "start", "iOO",
                              is_net_merger ? 1 : 0, lst, fwd);
    if (!res) break;
    rc = 0;
  } while (false);
  if (rc != 0) report_error("uda_bridge_start");
  Py_XDECREF(res);
  Py_XDECREF(fwd);
  Py_XDECREF(lst);
  Py_XDECREF(cls);
  Py_XDECREF(mod);
  PyGILState_Release(st);
  return rc;
}

// 0 when the interpreter is live (calling PyGILState_Ensure on an
// uninitialized runtime is a fatal abort, not a soft error — every
// entry point that can run before start() must check first)
static int not_started(const char *where) {
  if (Py_IsInitialized()) return 0;
  fprintf(stderr, "uda_tpu bridge shim: %s before uda_bridge_start\n", where);
  return 1;
}

// doCommandNative (UdaBridge.cc:266-295): "count:header:params..." strings
int uda_bridge_do_command(const char *cmd) {
  if (not_started("do_command")) return -1;
  PyGILState_STATE st = PyGILState_Ensure();
  int rc = -1;
  if (g_bridge) {
    PyObject *res = PyObject_CallMethod(g_bridge, "do_command", "s", cmd);
    if (res) {
      rc = 0;
      Py_DECREF(res);
    } else {
      report_error("uda_bridge_do_command");
    }
  } else {
    fprintf(stderr, "uda_tpu bridge shim: do_command before start\n");
  }
  PyGILState_Release(st);
  return rc;
}

// reduceExitMsgNative (UdaBridge.cc:299-314): synchronous teardown
int uda_bridge_reduce_exit(void) {
  if (not_started("reduce_exit")) return -1;
  PyGILState_STATE st = PyGILState_Ensure();
  int rc = -1;
  if (g_bridge) {
    PyObject *res = PyObject_CallMethod(g_bridge, "reduce_exit", nullptr);
    if (res) {
      rc = 0;
      Py_DECREF(res);
    } else {
      report_error("uda_bridge_reduce_exit");
    }
  }
  PyGILState_Release(st);
  return rc;
}

// setLogLevelNative (UdaBridge.cc:318-333)
int uda_bridge_set_log_level(int level) {
  if (not_started("set_log_level")) return -1;
  PyGILState_STATE st = PyGILState_Ensure();
  int rc = -1;
  if (g_bridge) {
    PyObject *res =
        PyObject_CallMethod(g_bridge, "set_log_level", "i", level);
    if (res) {
      rc = 0;
      Py_DECREF(res);
    } else {
      report_error("uda_bridge_set_log_level");
    }
  }
  PyGILState_Release(st);
  return rc;
}

// 1 after a failure was signalled (the Java-side fallback latch,
// UdaShuffleConsumerPluginShared.java:162-177)
int uda_bridge_failed(void) {
  if (not_started("failed")) return 0;
  PyGILState_STATE st = PyGILState_Ensure();
  int failed = 0;
  if (g_bridge) {
    PyObject *v = PyObject_GetAttrString(g_bridge, "failed");
    if (v) {
      failed = PyObject_IsTrue(v) == 1 ? 1 : 0;
      Py_DECREF(v);
    } else {
      PyErr_Clear();
    }
  }
  PyGILState_Release(st);
  return failed;
}

}  // extern "C"
