// Native streaming k-way merge: the RPQ final-merge hot path.
//
// C++ equivalent of the reference's MergeQueue loser-walk over
// SuperSegment file cursors (reference src/Merger/MergeQueue.h:276-427
// feeding write_kv_to_stream, src/Merger/StreamRW.cc:151-225): k sorted
// IFile spill files stream through buffered cursors into a loser tree;
// the winning record's framed bytes are copied VERBATIM into the output
// block (framing is canonical, so verbatim copy == re-encode, and it is
// byte-identical to the Python heap path in uda_tpu/ops/merge.py by
// construction). Comparator semantics are the CompareFunc.cc family
// (reference src/Merger/CompareFunc.cc:70-113) expressed as key "modes"
// — see kway_key_mode in uda_tpu/native/__init__.py:
//   0 identity  — memcmp over the serialized key
//   1 text      — skip the VInt length prefix, then memcmp
//   2 bytes     — skip the 4-byte length prefix, then memcmp
//   3 flipsign  — first key_param bytes with byte 0 XOR 0x80 (the
//                 numeric-order variants), then memcmp
// All modes share the memcmp + shorter-is-smaller rule with ties broken
// by cursor index (stable by segment order, matching
// merge_record_streams' seq tiebreak).

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <vector>

#include "vlong.h"

namespace {

constexpr int64_t kErrCorrupt = -1;   // bad framing / missing EOF marker
constexpr int64_t kErrTooSmall = -3;  // record larger than the out block
constexpr int64_t kErrIo = -4;        // read() failure

// Sanity bound on a single key/value length: a corrupt VLong must fail
// as kErrCorrupt, not overflow int64 arithmetic or balloon the cursor
// buffer until bad_alloc escapes the C boundary.
constexpr int64_t kMaxPartLen = int64_t{1} << 30;  // 1 GiB

// One spill-file cursor: buffered sequential reads, one parsed record
// at a time (rec/key offsets point into buf and stay valid until the
// cursor's own next advance — the merge copies the record out before
// advancing, so no other cursor can invalidate them).
struct Cursor {
  int fd = -1;
  std::vector<uint8_t> buf;
  int64_t pos = 0;       // parse position
  int64_t filled = 0;    // valid bytes in buf
  bool file_done = false;
  bool exhausted = false;  // saw the (-1,-1) EOF marker
  int64_t rec_off = 0, rec_len = 0;  // current framed record
  int64_t key_off = 0, key_len = 0;  // serialized key within buf

  // Compact the unparsed tail to the front and read more. Returns
  // bytes added, 0 at file end, <0 on errno.
  int64_t refill() {
    if (pos > 0) {
      std::memmove(buf.data(), buf.data() + pos, filled - pos);
      filled -= pos;
      pos = 0;
    }
    if (filled == static_cast<int64_t>(buf.size())) {
      buf.resize(buf.size() * 2);  // record larger than the buffer
    }
    ssize_t n = read(fd, buf.data() + filled, buf.size() - filled);
    if (n < 0) return kErrIo;
    if (n == 0) {
      file_done = true;
      return 0;
    }
    filled += n;
    return n;
  }

  // Parse the record at pos (refilling as needed). Returns 0 ok /
  // negative error; sets exhausted at the EOF marker.
  int64_t advance() {
    for (;;) {
      int64_t klen, vlen;
      int64_t start = pos;
      int used = uda::decode_vlong(buf.data(), filled, pos, &klen);
      if (used) {
        int64_t p = start + used;
        int used2 = uda::decode_vlong(buf.data(), filled, p, &vlen);
        if (used2) {
          p += used2;
          if (klen == -1 && vlen == -1) {
            pos = p;
            exhausted = true;
            return 0;
          }
          if (klen < 0 || vlen < 0 ||
              klen > kMaxPartLen || vlen > kMaxPartLen) {
            return kErrCorrupt;
          }
          if (p + klen + vlen <= filled) {
            rec_off = start;
            rec_len = (p + klen + vlen) - start;
            key_off = p;
            key_len = klen;
            pos = p + klen + vlen;
            return 0;
          }
        }
      }
      // truncated mid-record: need more bytes
      if (file_done) return kErrCorrupt;  // missing EOF marker
      int64_t n = refill();
      if (n < 0) return n;
      if (file_done && pos >= filled) return kErrCorrupt;
    }
  }
};

struct KwayMerger {
  std::vector<Cursor> cur;
  std::vector<int> node;  // loser tree: node[1..k-1] losers, leaves k..2k-1
  int winner = -1;
  int key_mode = 0;
  int key_param = 0;
  int64_t err = 0;  // first cursor error, sticky

  // Comparable content view of cursor i's current key (mode applied).
  void content(int i, const uint8_t** p, int64_t* n) const {
    const Cursor& c = cur[i];
    const uint8_t* k = c.buf.data() + c.key_off;
    int64_t kl = c.key_len;
    switch (key_mode) {
      case 1: {  // Text: skip the VInt prefix (CompareFunc.cc:82-86)
        int64_t clen;
        int used = uda::decode_vlong(k, kl, 0, &clen);
        if (!used || clen < 0) { *p = k; *n = 0; return; }
        *p = k + used;
        *n = std::min(clen, kl - used);
        return;
      }
      case 2:  // BytesWritable: skip the 4-byte length (:89-91)
        *p = k + std::min<int64_t>(4, kl);
        *n = std::max<int64_t>(0, kl - 4);
        return;
      case 3:  // numeric variants: first key_param bytes, byte0 ^ 0x80
        *p = k;
        *n = std::min<int64_t>(key_param, kl);
        return;
      default:
        *p = k;
        *n = kl;
        return;
    }
  }

  // true when cursor a's record sorts strictly before cursor b's.
  // Exhausted cursors sort after everything.
  bool beats(int a, int b) const {
    if (cur[a].exhausted) return false;
    if (cur[b].exhausted) return true;
    const uint8_t *pa, *pb;
    int64_t na, nb;
    content(a, &pa, &na);
    content(b, &pb, &nb);
    int64_t n = std::min(na, nb);
    if (n > 0) {
      uint8_t xa = pa[0], xb = pb[0];
      if (key_mode == 3) { xa ^= 0x80; xb ^= 0x80; }
      if (xa != xb) return xa < xb;
      int c = std::memcmp(pa + 1, pb + 1, n - 1);
      if (c) return c < 0;
    }
    if (na != nb) return na < nb;  // shorter-is-smaller
    return a < b;                  // stable by segment order
  }

  void build_tree() {
    int k = static_cast<int>(cur.size());
    if (k == 1) {
      winner = 0;
      return;
    }
    node.assign(2 * k, -1);
    std::vector<int> win(2 * k);
    for (int j = k; j < 2 * k; ++j) win[j] = j - k;
    for (int j = k - 1; j >= 1; --j) {
      int a = win[2 * j], b = win[2 * j + 1];
      if (beats(a, b)) {
        win[j] = a;
        node[j] = b;
      } else {
        win[j] = b;
        node[j] = a;
      }
    }
    winner = win[1];
  }

  // Re-play the winner's leaf-to-root path after its cursor advanced.
  void replay() {
    int k = static_cast<int>(cur.size());
    if (k == 1) return;
    int c = winner;
    for (int j = (winner + k) / 2; j >= 1; j /= 2) {
      if (beats(node[j], c)) std::swap(node[j], c);
    }
    winner = c;
  }
};

}  // namespace

extern "C" {

// Open the k spill files and prime every cursor. Returns NULL on
// failure with *err distinguishing the cause: kErrIo for open()/read()
// failures, kErrCorrupt for bad framing in a first record (partially
// opened fds are closed either way).
void* uda_kway_create(const char* const* paths, int32_t n,
                      int32_t key_mode, int32_t key_param,
                      int64_t buffer_size, int64_t* err) {
  if (err) *err = 0;
  if (n <= 0 || buffer_size < 64) {
    if (err) *err = kErrIo;
    return nullptr;
  }
  auto* m = new KwayMerger();
  m->key_mode = key_mode;
  m->key_param = key_param;
  m->cur.resize(n);
  for (int i = 0; i < n; ++i) {
    Cursor& c = m->cur[i];
    c.fd = open(paths[i], O_RDONLY);
    if (c.fd < 0) {
      if (err) *err = kErrIo;
      for (int j = 0; j <= i; ++j)
        if (m->cur[j].fd >= 0) close(m->cur[j].fd);
      delete m;
      return nullptr;
    }
    c.buf.resize(buffer_size);
  }
  for (int i = 0; i < n; ++i) {
    int64_t rc = m->cur[i].advance();
    if (rc < 0) {
      if (err) *err = rc;
      for (auto& c : m->cur) close(c.fd);
      delete m;
      return nullptr;
    }
  }
  m->build_tree();
  return m;
}

// Fill `out` with as many whole framed records as fit. Returns bytes
// written; 0 = end of stream (all cursors exhausted; no EOF marker is
// appended — the caller owns stream-level framing); kErrTooSmall with
// *need set when the next record alone exceeds cap; kErrCorrupt/kErrIo
// on cursor failure (sticky).
int64_t uda_kway_next_block(void* h, uint8_t* out, int64_t cap,
                            int64_t* need) {
  auto* m = static_cast<KwayMerger*>(h);
  if (m->err) return m->err;
  int64_t written = 0;
  while (m->winner >= 0) {
    Cursor& c = m->cur[m->winner];
    if (c.exhausted) break;  // winner exhausted => all exhausted
    if (written + c.rec_len > cap) {
      if (written == 0) {
        if (need) *need = c.rec_len;
        return kErrTooSmall;
      }
      break;
    }
    std::memcpy(out + written, c.buf.data() + c.rec_off, c.rec_len);
    written += c.rec_len;
    int64_t rc = c.advance();
    if (rc < 0) {
      m->err = rc;
      return rc;
    }
    m->replay();
  }
  return written;
}

void uda_kway_destroy(void* h) {
  auto* m = static_cast<KwayMerger*>(h);
  if (!m) return;
  for (auto& c : m->cur)
    if (c.fd >= 0) close(c.fd);
  delete m;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Row merge: two sorted uint32-row matrices -> one, lexicographic by all
// columns, ties to A (stability). The host-engine twin of the Pallas
// merge-path kernel for the overlap run forest (uda_tpu/merger/overlap.py):
// a linear two-pointer pass instead of re-lexsorting the concatenation.
// ---------------------------------------------------------------------------

extern "C" void uda_merge_rows(const uint32_t* a, int64_t na,
                               const uint32_t* b, int64_t nb,
                               int32_t k, uint32_t* out) {
  const size_t row = (size_t)k * sizeof(uint32_t);
  int64_t i = 0, j = 0;
  while (i < na && j < nb) {
    const uint32_t* pa = a + (size_t)i * k;
    const uint32_t* pb = b + (size_t)j * k;
    bool a_le_b = true;
    for (int32_t c = 0; c < k; ++c) {
      if (pa[c] != pb[c]) { a_le_b = pa[c] < pb[c]; break; }
    }
    if (a_le_b) {
      std::memcpy(out, pa, row);
      ++i;
    } else {
      std::memcpy(out, pb, row);
      ++j;
    }
    out += k;
  }
  if (i < na) {
    std::memcpy(out, a + (size_t)i * k, (size_t)(na - i) * row);
  } else if (j < nb) {
    std::memcpy(out, b + (size_t)j * k, (size_t)(nb - j) * row);
  }
}
