// Native IFile/VInt codec: the host-staging hot path.
//
// C++ equivalent of the reference's StreamUtility VInt/VLong codec and
// record framing walk (reference src/CommUtils/IOUtility.cc:167-397,
// src/Merger/StreamRW.cc:334-449), exposed through a C ABI consumed via
// ctypes (uda_tpu/native/__init__.py). One pass converts an IFile
// segment buffer into columnar (offset, length) arrays — the same
// contract as uda_tpu.utils.ifile.crack/crack_partial, which remain the
// pure-Python reference implementation these functions are parity-tested
// against (tests/test_native.py).

#include <cstdint>
#include <cstddef>
#include <cstring>

#include "vlong.h"

using uda::decode_vlong;

extern "C" {

// Error codes (negative returns)
enum : int64_t {
  UDA_ERR_CORRUPT = -1,     // negative length that isn't the EOF marker
  UDA_ERR_OVERFLOW = -2,    // more records than max_records
};

// Scan consecutive VLongs; returns count decoded (stops at truncation).
int64_t uda_decode_vlongs(const uint8_t* buf, int64_t len, int64_t* out,
                          int64_t max) {
  int64_t pos = 0, n = 0;
  while (pos < len && n < max) {
    int used = decode_vlong(buf, len, pos, &out[n]);
    if (used == 0) break;
    pos += used;
    ++n;
  }
  return n;
}

// One-pass columnar crack of an IFile segment (the native twin of
// ifile.crack_partial). Writes up to max_records (key_off, key_len,
// val_off, val_len) rows. Returns the record count or a UDA_ERR_* code;
// *consumed = bytes consumed (complete records + EOF marker),
// *saw_eof = 1 if the (-1,-1) marker was reached.
int64_t uda_crack(const uint8_t* buf, int64_t len,
                  int64_t* key_off, int64_t* key_len,
                  int64_t* val_off, int64_t* val_len,
                  int64_t max_records, int64_t* consumed, int32_t* saw_eof) {
  int64_t pos = 0, n = 0;
  *saw_eof = 0;
  while (pos < len) {
    int64_t start = pos;
    int64_t klen, vlen;
    int used = decode_vlong(buf, len, pos, &klen);
    if (used == 0) { pos = start; break; }
    int64_t p = pos + used;
    used = decode_vlong(buf, len, p, &vlen);
    if (used == 0) { pos = start; break; }
    p += used;
    if (klen == -1 && vlen == -1) {
      pos = p;
      *saw_eof = 1;
      break;
    }
    if (klen < 0 || vlen < 0) return UDA_ERR_CORRUPT;
    if (p + klen + vlen > len) { pos = start; break; }
    if (n >= max_records) return UDA_ERR_OVERFLOW;
    key_off[n] = p;
    key_len[n] = klen;
    val_off[n] = p + klen;
    val_len[n] = vlen;
    pos = p + klen + vlen;
    ++n;
  }
  *consumed = pos;
  return n;
}

// Serialize records into IFile framing (VInt klen, VInt vlen, key, val).
// Returns bytes written or -1 if out_cap is too small. Appends the EOF
// marker when write_eof != 0.
using uda::encode_vlong;

int64_t uda_write_records(const uint8_t* data,
                          const int64_t* key_off, const int64_t* key_len,
                          const int64_t* val_off, const int64_t* val_len,
                          int64_t n, uint8_t* out, int64_t out_cap,
                          int32_t write_eof) {
  int64_t pos = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t need = key_len[i] + val_len[i] + 20;
    if (pos + need > out_cap) return -1;
    pos += encode_vlong(key_len[i], out + pos);
    pos += encode_vlong(val_len[i], out + pos);
    const uint8_t* k = data + key_off[i];
    for (int64_t j = 0; j < key_len[i]; ++j) out[pos + j] = k[j];
    pos += key_len[i];
    const uint8_t* v = data + val_off[i];
    for (int64_t j = 0; j < val_len[i]; ++j) out[pos + j] = v[j];
    pos += val_len[i];
  }
  if (write_eof) {
    if (pos + 2 > out_cap) return -1;
    out[pos++] = 0xFF;
    out[pos++] = 0xFF;
  }
  return pos;
}

}  // extern "C"

// Span gather: dst[dst_off[i] : dst_off[i]+len[i]] = src[src_off[i] : ...]
// for every record i — the byte-movement core of the streaming
// interleave and slab gather (uda_tpu/merger/streaming.py). The numpy
// fallback builds an int64 index per BYTE (8x the memory traffic);
// this is a straight memcpy per record.
extern "C" void uda_gather_spans(const uint8_t* src, const int64_t* src_off,
                                 const int64_t* lens, int64_t n,
                                 uint8_t* dst, const int64_t* dst_off) {
  for (int64_t i = 0; i < n; ++i)
    std::memcpy(dst + dst_off[i], src + src_off[i], (size_t)lens[i]);
}
