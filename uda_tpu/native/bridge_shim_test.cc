// Standalone driver for libuda_tpu_bridge.so — the native-embedder
// analogue of the reference's JNI mechanism tests (reference
// tests/jni*/README: prove callback registration, data hand-off and
// packaged-class dispatch through the bridge in isolation).
//
// Usage: bridge_shim_test <mof_root> <job_id> <num_maps> <reduce_id> [upcall]
//   <mof_root> must hold the <job>/<map>/file.out[.index] tree the
//   uda_tpu MOF writer produces (tests/helpers.make_mof_tree).
//   With "upcall", INIT carries no local dir and index resolution runs
//   through the get_path_uda C callback (the reference's IndexCache
//   round trip, src/MOFServer/IndexInfo.cc:237-251): this driver parses
//   file.out.index itself (24-byte big-endian triples).
//
// Drives the full reduce flow over the C ABI: start -> INIT -> FETCH xN
// -> FINAL -> wait fetch_over -> reduce_exit, collecting dataFromUda
// bytes, then prints "MERGED <bytes> RECORDS <n>" for the harness to
// assert on. Exits nonzero on any failure (including failure_in_uda).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" {
typedef struct uda_index_record {
  char path[4096];
  long long start_offset;
  long long raw_length;
  long long part_length;
} uda_index_record_t;

typedef struct uda_callbacks {
  void *ctx;
  void (*fetch_over_message)(void *ctx);
  void (*data_from_uda)(void *ctx, const char *data, long long len);
  int (*get_path_uda)(void *ctx, const char *job_id, const char *map_id,
                      int reduce_id, uda_index_record_t *rec);
  void (*get_conf_data)(void *ctx, const char *name, const char *dflt,
                        char *out, int cap);
  void (*log_to)(void *ctx, int level, const char *message);
  void (*failure_in_uda)(void *ctx, const char *what);
} uda_callbacks_t;

int uda_bridge_start(int is_net_merger, int argc, const char **argv,
                     const uda_callbacks_t *cbs);
int uda_bridge_do_command(const char *cmd);
int uda_bridge_reduce_exit(void);
int uda_bridge_set_log_level(int level);
int uda_bridge_failed(void);
}

namespace {

struct Host {
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  bool failed = false;
  std::string merged;
  std::string failure;
  std::string root;  // for the get_path_uda upcall mode
  std::atomic<int> path_upcalls{0};
};

// read one 8-byte big-endian long
long long be64(const unsigned char *p) {
  long long v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

int on_get_path(void *ctx, const char *job, const char *map, int reduce_id,
                uda_index_record_t *rec) {
  Host *h = static_cast<Host *>(ctx);
  h->path_upcalls.fetch_add(1);
  std::string dir = h->root + "/" + job + "/" + map;
  std::string idx = dir + "/file.out.index";
  FILE *f = fopen(idx.c_str(), "rb");
  if (!f) return 1;
  unsigned char triple[24];
  if (fseek(f, 24L * reduce_id, SEEK_SET) != 0 ||
      fread(triple, 1, 24, f) != 24) {
    fclose(f);
    return 2;
  }
  fclose(f);
  snprintf(rec->path, sizeof rec->path, "%s/file.out", dir.c_str());
  rec->start_offset = be64(triple);
  rec->raw_length = be64(triple + 8);
  rec->part_length = be64(triple + 16);
  return 0;
}

void on_fetch_over(void *ctx) {
  Host *h = static_cast<Host *>(ctx);
  std::lock_guard<std::mutex> lk(h->mu);
  h->done = true;
  h->cv.notify_all();
}

void on_data(void *ctx, const char *data, long long len) {
  Host *h = static_cast<Host *>(ctx);
  std::lock_guard<std::mutex> lk(h->mu);
  h->merged.append(data, (size_t)len);
}

void on_conf(void *, const char *, const char *dflt, char *out, int cap) {
  snprintf(out, (size_t)cap, "%s", dflt ? dflt : "");
}

void on_log(void *, int level, const char *msg) {
  if (level <= 2) fprintf(stderr, "[bridge:%d] %s\n", level, msg);
}

void on_failure(void *ctx, const char *what) {
  Host *h = static_cast<Host *>(ctx);
  std::lock_guard<std::mutex> lk(h->mu);
  h->failed = true;
  h->failure = what ? what : "?";
  h->done = true;
  h->cv.notify_all();
}

// count IFile records: VInt klen, VInt vlen, key, value; EOF = (-1,-1)
// (byte-level contract of uda_tpu.utils.ifile / reference
// src/CommUtils/IOUtility.cc:167-332)
long decode_vint(const unsigned char *p, size_t n, size_t *used) {
  if (n == 0) return *used = 0, 0;
  signed char first = (signed char)p[0];
  if (first >= -112) return *used = 1, (long)first;
  int len = first >= -120 ? -112 - first : -120 - first;
  bool neg = first < -120;
  if ((size_t)len + 1 > n) return *used = 0, 0;
  long v = 0;
  for (int i = 0; i < len; i++) v = (v << 8) | p[1 + i];
  *used = (size_t)len + 1;
  return neg ? ~v : v;
}

int count_records(const std::string &buf) {
  const unsigned char *p = (const unsigned char *)buf.data();
  size_t n = buf.size(), pos = 0;
  int records = 0;
  while (pos < n) {
    size_t u1 = 0, u2 = 0;
    long klen = decode_vint(p + pos, n - pos, &u1);
    if (!u1) return -1;
    long vlen = decode_vint(p + pos + u1, n - pos - u1, &u2);
    if (!u2) return -1;
    pos += u1 + u2;
    if (klen == -1 && vlen == -1) continue;  // EOF marker between blocks
    if (klen < 0 || vlen < 0 || pos + (size_t)(klen + vlen) > n) return -1;
    pos += (size_t)(klen + vlen);
    records++;
  }
  return records;
}

}  // namespace

int main(int argc, char **argv) {
  if (argc < 5) {
    fprintf(stderr, "usage: %s <mof_root> <job_id> <num_maps> <reduce_id>\n",
            argv[0]);
    return 2;
  }
  const std::string root = argv[1], job = argv[2];
  const int num_maps = atoi(argv[3]);
  const std::string reduce_id = argv[4];
  const bool upcall = argc > 5 && strcmp(argv[5], "upcall") == 0;

  Host host;
  host.root = root;
  uda_callbacks_t cbs;
  memset(&cbs, 0, sizeof cbs);
  cbs.ctx = &host;
  cbs.fetch_over_message = on_fetch_over;
  cbs.data_from_uda = on_data;
  cbs.get_path_uda = on_get_path;
  cbs.get_conf_data = on_conf;
  cbs.log_to = on_log;
  cbs.failure_in_uda = on_failure;

  const char *args[] = {"-w", "8", "-s", "64"};
  if (uda_bridge_start(1, 4, args, &cbs) != 0) return 3;

  // INIT: job, reduce, num_maps, key_class, then optionally a local dir
  // (DirIndexResolver); without it resolution uses the get_path_uda
  // up-call
  std::string init = upcall
      ? "4:7:" + job + ":" + reduce_id + ":" + std::to_string(num_maps) +
            ":uda.tpu.RawBytes"
      : "5:7:" + job + ":" + reduce_id + ":" + std::to_string(num_maps) +
            ":uda.tpu.RawBytes:" + root;
  if (uda_bridge_do_command(init.c_str()) != 0) return 4;
  for (int m = 0; m < num_maps; m++) {
    char map_id[256];
    // map-attempt naming of tests/helpers.map_ids
    snprintf(map_id, sizeof map_id, "attempt_%s_m_%06d_0", job.c_str(), m);
    std::string fetch = std::string("4:4:localhost:") + job + ":" + map_id +
                        ":" + reduce_id;
    if (uda_bridge_do_command(fetch.c_str()) != 0) return 5;
  }
  if (uda_bridge_do_command("0:2") != 0) return 6;  // FINAL

  {
    std::unique_lock<std::mutex> lk(host.mu);
    if (!host.cv.wait_for(lk, std::chrono::seconds(60),
                          [&] { return host.done; })) {
      fprintf(stderr, "timeout waiting for fetch_over\n");
      return 7;
    }
  }
  if (host.failed || uda_bridge_failed()) {
    fprintf(stderr, "bridge failure: %s\n", host.failure.c_str());
    return 8;
  }
  if (uda_bridge_reduce_exit() != 0) return 9;

  if (upcall && host.path_upcalls.load() == 0) {
    fprintf(stderr, "upcall mode but get_path_uda was never invoked\n");
    return 11;
  }
  int records = count_records(host.merged);
  if (records < 0) {
    fprintf(stderr, "merged stream is not valid IFile framing\n");
    return 10;
  }
  printf("MERGED %zu RECORDS %d\n", host.merged.size(), records);
  return 0;
}
