// Native LZO1X block codec: the in-tree stand-in for liblzo2.
//
// The reference dlopen'd liblzo2 at runtime (reference
// src/Merger/LzoDecompressor.cc:83-127) and treated its absence as a
// runtime condition. This file makes the native path self-contained:
// an independent implementation of the LZO1X stream format (the token
// grammar is documented in uda_tpu/compress/lzo.py, whose pure-Python
// decoder is the semantic reference these entry points are
// parity-tested against, tests/test_compress.py). Exported under
// uda_-prefixed names so a real liblzo2, when present in the process,
// never collides; uda_tpu/compress/lzo.py prefers the system library
// and falls back here.
//
// Grammar recap (matching the Python decoder's state machine):
//   stream   := [initial-literals] { match [state-literals] | run match }
//               EOS
//   M2 token >=64: len 3..8, dist <= 0x808, state in token low bits
//   M3 token 32..63: len >= 3 (extended), dist <= 0x4000, 2-byte LE
//              distance field, state in d0 low bits
//   M4 token 16..31: dist 0x4001..0xBFFF (dist-0x4000 == 0 is EOS),
//              len >= 3 (extended), state in d0 low bits
//   run token < 16: literal run >= 4 (extended); runs of 1..3 ride the
//              previous match token's state bits
//   EOS      := 0x11 0x00 0x00

#include <cstdint>
#include <cstring>

extern "C" {

// error codes mirror lzo.h's public values for familiarity
enum {
  UDA_LZO_OK = 0,
  UDA_LZO_E_INPUT_OVERRUN = -4,
  UDA_LZO_E_OUTPUT_OVERRUN = -5,
  UDA_LZO_E_LOOKBEHIND_OVERRUN = -6,
  UDA_LZO_E_EOF_NOT_FOUND = -7,
  UDA_LZO_E_INPUT_NOT_CONSUMED = -8,
};

// ---------------------------------------------------------------------------
// decompressor (safe: every read/write bounds-checked)
// ---------------------------------------------------------------------------

int uda_lzo1x_decompress_safe(const uint8_t* src, size_t src_len,
                              uint8_t* dst, size_t* dst_len) {
  const size_t cap = *dst_len;
  size_t ip = 0, op = 0;
  *dst_len = 0;

#define NEED_IN(n) if (ip + (n) > src_len) return UDA_LZO_E_INPUT_OVERRUN
#define NEED_OUT(n) if (op + (n) > cap) return UDA_LZO_E_OUTPUT_OVERRUN

  // extended length: zero bytes each add 255, final nonzero byte adds
  // itself; `base` is the token-family bias
  auto extended = [&](size_t base, int* err) -> size_t {
    size_t t = 0;
    for (;;) {
      if (ip >= src_len) { *err = UDA_LZO_E_INPUT_OVERRUN; return 0; }
      uint8_t b = src[ip++];
      if (b == 0) {
        t += 255;
        if (t > (1u << 30)) { *err = UDA_LZO_E_INPUT_OVERRUN; return 0; }
      } else {
        return t + base + b;
      }
    }
  };

  int err = UDA_LZO_OK;
  size_t t;
  int state;          // trailing literal count after a match
  enum { LOOP, FIRST, MATCH } mode = LOOP;

  NEED_IN(1);
  if (src[0] > 17) {
    ip = 1;
    t = src[0] - 17;
    NEED_IN(t); NEED_OUT(t);
    std::memcpy(dst + op, src + ip, t); op += t; ip += t;
    NEED_IN(1);
    t = src[ip++];
    // short initial run < 4 -> the next token is a match token; else
    // first_literal_run semantics — same split as the Python decoder
    mode = (src[0] - 17 < 4) ? MATCH : FIRST;
  } else {
    t = 0;
  }

  for (;;) {
    if (mode == LOOP) {
      NEED_IN(1);
      t = src[ip++];
      if (t < 16) {
        if (t == 0) { t = extended(15, &err); if (err) return err; }
        t += 3;
        NEED_IN(t); NEED_OUT(t);
        std::memcpy(dst + op, src + ip, t); op += t; ip += t;
        NEED_IN(1);
        t = src[ip++];
        mode = FIRST;
        continue;
      }
      mode = MATCH;
      continue;
    }

    if (mode == FIRST) {
      if (t < 16) {
        // special M1 right after a literal run: 3-byte match with the
        // M2-offset bias
        NEED_IN(1);
        size_t dist = (1 + 0x800) + (t >> 2) + ((size_t)src[ip++] << 2);
        if (dist > op) return UDA_LZO_E_LOOKBEHIND_OVERRUN;
        NEED_OUT(3);
        const uint8_t* m = dst + op - dist;
        for (int i = 0; i < 3; ++i) dst[op++] = m[i];
        state = (int)(t & 3);  // state rides the TOKEN low bits for M1
      } else {
        mode = MATCH;
        continue;
      }
    } else {  // MATCH
      if (t >= 64) {           // M2
        NEED_IN(1);
        size_t dist = 1 + ((t >> 2) & 7) + ((size_t)src[ip++] << 3);
        size_t count = (t >> 5) - 1 + 2;
        if (dist > op) return UDA_LZO_E_LOOKBEHIND_OVERRUN;
        NEED_OUT(count);
        const uint8_t* m = dst + op - dist;
        for (size_t i = 0; i < count; ++i) dst[op++] = m[i];
        state = (int)(t & 3);
      } else if (t >= 32) {    // M3
        size_t count = t & 31;
        if (count == 0) { count = extended(31, &err); if (err) return err; }
        count += 2;
        NEED_IN(2);
        uint8_t d0 = src[ip++], d1 = src[ip++];
        size_t dist = 1 + (d0 >> 2) + ((size_t)d1 << 6);
        if (dist > op) return UDA_LZO_E_LOOKBEHIND_OVERRUN;
        NEED_OUT(count);
        const uint8_t* m = dst + op - dist;
        for (size_t i = 0; i < count; ++i) dst[op++] = m[i];
        state = d0 & 3;
      } else if (t >= 16) {    // M4 or EOS
        size_t hi = (t & 8) << 11;
        size_t count = t & 7;
        if (count == 0) { count = extended(7, &err); if (err) return err; }
        NEED_IN(2);
        uint8_t d0 = src[ip++], d1 = src[ip++];
        size_t dlow = (d0 >> 2) + ((size_t)d1 << 6);
        if (hi == 0 && dlow == 0) {
          if (count != 1) return UDA_LZO_E_EOF_NOT_FOUND;
          break;  // end of stream
        }
        count += 2;
        size_t dist = hi + dlow + 0x4000;
        if (dist > op) return UDA_LZO_E_LOOKBEHIND_OVERRUN;
        NEED_OUT(count);
        const uint8_t* m = dst + op - dist;
        for (size_t i = 0; i < count; ++i) dst[op++] = m[i];
        state = d0 & 3;
      } else {                 // M1: 2-byte match
        NEED_IN(1);
        size_t dist = 1 + (t >> 2) + ((size_t)src[ip++] << 2);
        if (dist > op) return UDA_LZO_E_LOOKBEHIND_OVERRUN;
        NEED_OUT(2);
        const uint8_t* m = dst + op - dist;
        dst[op++] = m[0]; dst[op++] = m[1];
        state = (int)(t & 3);
      }
    }

    // trailing literals per the match's state bits
    if (state == 0) {
      mode = LOOP;
    } else {
      NEED_IN((size_t)state); NEED_OUT((size_t)state);
      std::memcpy(dst + op, src + ip, state); op += state; ip += state;
      NEED_IN(1);
      t = src[ip++];
      mode = MATCH;
    }
  }

  *dst_len = op;
  if (ip != src_len) return UDA_LZO_E_INPUT_NOT_CONSUMED;
  return UDA_LZO_OK;

#undef NEED_IN
#undef NEED_OUT
}

// ---------------------------------------------------------------------------
// compressor: greedy hash-table matcher emitting M2/M3/M4 + literal runs
// ---------------------------------------------------------------------------

static inline uint32_t hash4(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return (v * 0x9E3779B1u) >> 18;  // 14-bit table
}

int uda_lzo1x_1_compress(const uint8_t* src, size_t src_len,
                         uint8_t* dst, size_t* dst_len) {
  const size_t cap = *dst_len;
  size_t op = 0;
  *dst_len = 0;
#define PUT(b) do { if (op >= cap) return UDA_LZO_E_OUTPUT_OVERRUN; \
                    dst[op++] = (uint8_t)(b); } while (0)

  static thread_local int32_t table[1 << 14];
  for (auto& e : table) e = -1;

  size_t pos = 0, lit_start = 0;
  long prev_state_at = -1;  // dst index whose low 2 bits carry the next
                            // run's 1..3 trailing literals (match d0/token)
  bool first_emit = true;

  // flush pending literals [lit_start, pos); returns error or 0
  auto flush_literals = [&]() -> int {
    size_t p = pos - lit_start;
    if (p == 0) return 0;
    if (first_emit) {
      // initial-run form: 17+p for p <= 238, else the extended loop form
      if (p <= 238) {
        PUT(17 + p);
      } else {
        size_t t = p - 3, x = t - 15, zeros = x / 255, fin = x % 255;
        if (fin == 0) { zeros -= 1; fin = 255; }
        PUT(0);
        for (size_t i = 0; i < zeros; ++i) PUT(0);
        PUT(fin);
      }
    } else if (p < 4) {
      // ride the previous match's state bits
      if (prev_state_at < 0) return UDA_LZO_E_OUTPUT_OVERRUN;  // logic bug
      dst[prev_state_at] = (uint8_t)(dst[prev_state_at] | (p & 3));
    } else {
      size_t t = p - 3;
      if (t <= 15) {
        PUT(t);
      } else {
        size_t x = t - 15, zeros = x / 255, fin = x % 255;
        if (fin == 0) { zeros -= 1; fin = 255; }
        PUT(0);
        for (size_t i = 0; i < zeros; ++i) PUT(0);
        PUT(fin);
      }
    }
    if (op + p > cap) return UDA_LZO_E_OUTPUT_OVERRUN;
    std::memcpy(dst + op, src + lit_start, p); op += p;
    lit_start = pos;
    first_emit = false;
    return 0;
  };

  while (pos + 4 <= src_len) {
    uint32_t h = hash4(src + pos);
    int32_t cand = table[h];
    table[h] = (int32_t)pos;
    size_t mlen = 0, dist = 0;
    if (cand >= 0) {
      dist = pos - (size_t)cand;
      if (dist >= 1 && dist <= 0xBFFF &&
          std::memcmp(src + cand, src + pos, 4) == 0) {
        mlen = 4;
        size_t maxl = src_len - pos;
        while (mlen < maxl && mlen < 0x800 &&
               src[cand + mlen] == src[pos + mlen])
          ++mlen;
        // short far matches don't pay for their token
        if (mlen == 4 && dist > 0x4000) mlen = 0;
      }
    }
    if (mlen < 3) {
      ++pos;
      continue;
    }
    int rc = flush_literals();
    if (rc) return rc;
    // emit the match; remember where its state bits live
    if (dist <= 0x800 && mlen <= 8) {                  // M2
      prev_state_at = (long)op;
      PUT(((mlen - 1) << 5) | (((dist - 1) & 7) << 2));
      PUT((dist - 1) >> 3);
    } else if (dist <= 0x4000) {                       // M3
      size_t lt = mlen - 2;
      if (lt <= 31) {
        PUT(32 | lt);
      } else {
        size_t x = lt - 31, zeros = x / 255, fin = x % 255;
        if (fin == 0) { zeros -= 1; fin = 255; }
        PUT(32);
        for (size_t i = 0; i < zeros; ++i) PUT(0);
        PUT(fin);
      }
      size_t D = dist - 1;
      prev_state_at = (long)op;
      PUT((D & 0x3F) << 2);
      PUT(D >> 6);
    } else {                                           // M4
      size_t D = dist - 0x4000;  // 1..0x7FFF
      size_t lt = mlen - 2;
      uint8_t hi = (uint8_t)((D >> 11) & 8);
      if (lt <= 7) {
        PUT(16 | hi | lt);
      } else {
        size_t x = lt - 7, zeros = x / 255, fin = x % 255;
        if (fin == 0) { zeros -= 1; fin = 255; }
        PUT(16 | hi);
        for (size_t i = 0; i < zeros; ++i) PUT(0);
        PUT(fin);
      }
      size_t dlow = D & 0x3FFF;
      prev_state_at = (long)op;
      PUT((dlow & 0x3F) << 2);
      PUT(dlow >> 6);
    }
    first_emit = false;
    // seed the table through the matched span (sparse: every 2nd byte
    // keeps the scan cheap on long matches)
    for (size_t i = 1; i < mlen && pos + i + 4 <= src_len; i += 2)
      table[hash4(src + pos + i)] = (int32_t)(pos + i);
    pos += mlen;
    lit_start = pos;
  }
  pos = src_len;
  int rc = flush_literals();
  if (rc) return rc;
  // EOS
  PUT(0x11); PUT(0x00); PUT(0x00);
  *dst_len = op;
  return UDA_LZO_OK;
#undef PUT
}

}  // extern "C"
