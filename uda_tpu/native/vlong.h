// Hadoop zero-compressed VLong codec, shared by the native codec
// (codec.cc) and the k-way merge (merge.cc). Byte-exact twin of
// uda_tpu/utils/vint.py, mirroring the reference's
// decodeVIntSize/readVLong/writeVLong semantics (reference
// src/CommUtils/IOUtility.cc:167-397).
#ifndef UDA_TPU_NATIVE_VLONG_H_
#define UDA_TPU_NATIVE_VLONG_H_

#include <cstdint>

namespace uda {

// Decode one VLong at buf[pos]. Returns bytes consumed, 0 on truncation.
inline int decode_vlong(const uint8_t* buf, int64_t len, int64_t pos,
                        int64_t* out) {
  if (pos >= len) return 0;
  int8_t first = static_cast<int8_t>(buf[pos]);
  if (first >= -112) {
    *out = first;
    return 1;
  }
  int size = (first >= -120) ? (-111 - first) : (-119 - first);
  if (pos + size > len) return 0;
  uint64_t v = 0;
  for (int i = 1; i < size; ++i) {
    v = (v << 8) | buf[pos + i];
  }
  *out = (first < -120) ? static_cast<int64_t>(~v) : static_cast<int64_t>(v);
  return size;
}

// Encode one VLong into out (needs up to 9 bytes). Returns bytes written.
inline int encode_vlong(int64_t v, uint8_t* out) {
  if (v >= -112 && v <= 127) {
    out[0] = static_cast<uint8_t>(v);
    return 1;
  }
  int tag = -112;
  uint64_t u = static_cast<uint64_t>(v);
  if (v < 0) {
    u = ~u;
    tag = -120;
  }
  int body = 0;
  for (uint64_t t = u; t; t >>= 8) ++body;
  out[0] = static_cast<uint8_t>(tag - body);
  for (int i = 0; i < body; ++i) {
    out[1 + i] = static_cast<uint8_t>(u >> (8 * (body - 1 - i)));
  }
  return body + 1;
}

}  // namespace uda

#endif  // UDA_TPU_NATIVE_VLONG_H_
