"""ShuffleServer: the event-loop supplier endpoint with a zero-copy
serve path.

The supplier side of the data plane rebuilt on the selector core
(:mod:`uda_tpu.net.evloop`): ONE loop thread multiplexes every
connection — non-blocking sockets, per-connection state machines for
frame reassembly and outbound queues — replacing PR 4's reader+writer
thread pair per connection (the shape that was "fine at 64 suppliers,
dead at 10k", ROADMAP item 3). Semantics are the threaded core's,
exactly:

- **credit cap** (``mapred.rdma.wqe.per.conn``): where the threaded
  reader *blocked* at the credit gate, this core *parks the decoded
  request and pauses read interest* — the kernel receive buffer fills,
  TCP flow control pushes back on the client, credit flow without a
  credit message. A settled response re-arms read interest.
- **out-of-order completion** from DataEngine futures;
- **typed ERR frames** for engine errors (missing MOF, admission
  rejection, injected faults) — never connection teardown;
- **drain-on-stop** (``uda.tpu.net.drain.s``) vs ``stop(drain=False)``
  = killed supplier.

The zero-copy serve path (``uda.tpu.net.zerocopy``, default on): DATA
chunks are served from the DataEngine's fd cache as
:class:`~uda_tpu.mofserver.data_engine.FdSlice` plans and streamed with
``os.sendfile`` — the chunk bytes go disk-cache -> socket without ever
existing as a Python object (the RDMA-WRITE-from-registered-memory
analogue, RDMAServer.cc:537-631). The fallback ladder when a chunk is
not fd-backed (CRC stamping on, ``data_engine.pread`` failpoint armed,
or a sendfile-refusing fd): ``socket.sendmsg`` scatter-gather of
``[head, chunk]`` memoryviews — one heap copy (the engine's read), zero
encode-side copies. ``net.serve.fd`` / ``net.serve.copy`` count the
split; ``net.sendfile.bytes`` counts the zero-copy bytes.

**Opportunistic inline writes** (the RDMAbox lesson — batched
submission and completion ordering beat thread ping-pong,
arXiv:2104.12197): an engine completion WRITES the response inline on
the completing thread under the connection's write lock when the
socket has room, instead of waking the loop — the loop only takes over
the residual when a send would block (EAGAIN -> writable interest).
Frame ordering is preserved by the lock (writers always drain from the
queue head); credit settlement is marshalled back to the loop OFF the
data path. On this box that removes two thread handoffs per chunk from
the serve critical path.

**Batched byte-path serves** (``uda.tpu.read.batch``, the other half
of the RDMAbox lesson): requests that will take the engine's byte path
(zerocopy off, CRC stamping on, pread failpoint armed) accumulate per
connection during one recv's frame burst / one credit-unpark sweep and
flush as ONE ``DataEngine.submit_batch`` — per-fd grouping, range
coalescing and vectored reads turn a burst against a hot MOF into
O(files) syscalls with one pool handoff, while slice-eligible requests
keep the zero-copy plane untouched. ``off`` reproduces the
one-handoff-one-pread-per-chunk path exactly (the io_bench identity
oracle).

**Multi-tenant daemon mode** (``uda.tpu.tenant.enable``, the
Exoshuffle shuffle-as-a-service shape — uda_tpu/tenant/): HELLO
advertises ``CAP_TENANT``; MSG_JOB frames register (tenant, job,
epoch) in the :class:`~uda_tpu.tenant.TenantRegistry` and bind them to
the connection; every bound REQ is validated per request (unknown/
retired/stale-epoch -> typed TenantError). Admission then flows
through the daemon-wide :class:`~uda_tpu.tenant.CreditScheduler` —
weighted deficit round-robin over per-tenant parked queues — BEFORE
the per-conn credit gate (gate-order invariant: a conn-parked entry
always holds a tenant credit, a scheduler-parked entry never does),
the engine's read budget partitions per tenant, and serve-path
counters/watermarks/ledger books carry the tenant. Off (the default)
this file is the single-job data plane of PRs 4-13, bit for bit.

Failpoints (same sites, same frequencies as the threaded core):
``net.accept`` per accepted connection, ``net.frame`` per outbound
response frame — applied to the frame head; a truncated head is a torn
frame and the connection is closed deterministically after sending it.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import os
import selectors
import socket
import threading
import time
from collections import deque
from typing import Optional

from uda_tpu.mofserver.data_engine import DataEngine, FdSlice
from uda_tpu.net import wire
from uda_tpu.net.evloop import EventLoop, loop_callback
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import (ProtocolError, StorageError, TenantError,
                                  TransportError, UdaError)
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.flightrec import flightrec
from uda_tpu.utils.locks import TrackedLock
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["ShuffleServer", "EvLoopShuffleServer"]

log = get_logger()

_READ = selectors.EVENT_READ
_WRITE = selectors.EVENT_WRITE

_RECV_CHUNK = 256 * 1024   # reusable inbound buffer per connection
_SENDFILE_MAX = 4 << 20    # bytes per sendfile syscall (fairness bound)

# errnos on which os.sendfile is permanently useless for this pairing
# (fs/socket refuses the splice) -> fall back to the pread+sendmsg path
_SENDFILE_FALLBACK_ERRNOS = (errno.EINVAL, errno.ENOSYS, errno.EOPNOTSUPP)


def _pick_zerocopy_mode() -> str:
    """One-time per-process probe for ``zerocopy.mode=auto``: time
    ``os.sendfile`` against ``send``-from-mmap over a loopback
    socketpair and serve with the faster mechanism. Both are zero-copy
    in the sense that matters (chunk bytes never become a Python-heap
    object); which one the KERNEL moves faster varies — sandboxed/
    emulated kernels (gVisor-style) implement sendfile as an internal
    copy loop at a fraction of plain send throughput, while bare-metal
    Linux favors sendfile. Preference goes to sendfile unless mmap
    beats it by >30% (the probe's noise floor); any probe failure
    falls back to sendfile."""
    global _PROBED_MODE
    with _PROBE_LOCK:
        if _PROBED_MODE is not None:
            return _PROBED_MODE
        mode = "sendfile"
        try:
            import mmap as mmap_mod
            import tempfile

            nbytes = 4 << 20
            with tempfile.NamedTemporaryFile() as tf:
                tf.write(b"\0" * nbytes)
                tf.flush()
                fd = tf.fileno()
                mm = mmap_mod.mmap(fd, 0, prot=mmap_mod.PROT_READ)

                def tcp_pair():
                    # a real TCP loopback pair — the transport the data
                    # plane rides; AF_UNIX pairs take a different (and
                    # differently-optimized) kernel path for mapped
                    # memory and would mis-rank the mechanisms
                    srv = socket.socket(socket.AF_INET,
                                        socket.SOCK_STREAM)
                    srv.bind(("127.0.0.1", 0))
                    srv.listen(1)
                    c = socket.create_connection(srv.getsockname()[:2])
                    s, _ = srv.accept()
                    srv.close()  # udalint: disable=UDA004 - probe-local
                    # listener, nothing blocked on it
                    for x in (c, s):
                        x.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_NODELAY, 1)
                    return c, s

                def timed(send_once) -> float:
                    a, b = tcp_pair()
                    stop = threading.Event()
                    sink = bytearray(1 << 20)

                    def drain() -> None:
                        while not stop.is_set():
                            try:
                                if not b.recv_into(sink):
                                    return
                            except OSError:
                                return

                    t = threading.Thread(target=drain, daemon=True)
                    t.start()
                    # untimed warmup pass: the serve path's mappings
                    # and fds are PERSISTENT (fd-cache retention), so
                    # steady-state behavior — page faults already
                    # taken — is what must be measured, not the cold
                    # first touch
                    send_once(a)
                    t0 = time.perf_counter()
                    for _ in range(3):
                        send_once(a)
                    dt = time.perf_counter() - t0
                    stop.set()
                    wire.close_hard(a)
                    wire.close_hard(b)
                    t.join(timeout=1.0)
                    return dt

                def via_sendfile(sock) -> None:
                    off = 0
                    while off < nbytes:
                        off += os.sendfile(sock.fileno(), fd, off,
                                           nbytes - off)

                view = memoryview(mm)

                def via_mmap(sock) -> None:
                    sock.sendall(view)

                t_sf = timed(via_sendfile)
                t_mm = timed(via_mmap)
                view.release()
                mm.close()
                if t_mm * 1.3 < t_sf:
                    mode = "mmap"
                log.info(f"net: zerocopy auto-probe: sendfile "
                         f"{t_sf * 1e3:.1f} ms vs mmap+send "
                         f"{t_mm * 1e3:.1f} ms for {3 * nbytes >> 20} MB "
                         f"-> {mode}")
        except Exception as e:  # noqa: BLE001 - a probe failure must
            # never break serving; sendfile is the safe default
            log.warn(f"net: zerocopy auto-probe failed ({e}); "
                     f"using sendfile")
        _PROBED_MODE = mode
        return mode


_PROBED_MODE: Optional[str] = None
_PROBE_LOCK = threading.Lock()


class _BufItem:
    """An outbound frame already materialized as buffers: ERR, SIZE,
    the byte-path DATA frames (``[head, chunk]`` scatter-gather — the
    chunk memoryview donates the engine's buffer, no concat), and
    mmap-mode zero-copy DATA frames (the chunk memoryview points into
    the MOF's page-cache mapping; ``slice`` pins it until written)."""

    __slots__ = ("bufs", "credited", "t0", "close_after", "slice",
                 "zc_bytes", "tenant")

    def __init__(self, bufs, credited: bool, t0: float,
                 close_after: bool = False, sl=None, zc_bytes: int = 0,
                 tenant: str = ""):
        self.bufs = [memoryview(b) for b in bufs]
        self.credited = credited
        self.t0 = t0
        self.close_after = close_after
        self.slice = sl
        self.zc_bytes = zc_bytes
        self.tenant = tenant  # the credit's tenant (scheduler release)


def _release_item(item) -> None:
    """Release an item's fd-cache pin (idempotent), dropping any
    mmap-backed memoryviews first so the cache can unmap cleanly."""
    if item.slice is None:
        return
    if isinstance(item, _BufItem):
        item.bufs.clear()
    item.slice.release()


class _FileItem:
    """An outbound DATA frame whose chunk is an fd-backed FdSlice:
    head bytes then ``os.sendfile`` straight from the MOF fd."""

    __slots__ = ("head", "slice", "file_off", "remaining", "credited",
                 "t0", "close_after", "tenant")

    def __init__(self, head: bytes, sl: FdSlice, t0: float,
                 tenant: str = ""):
        self.head: Optional[memoryview] = memoryview(head)
        self.slice = sl
        self.file_off = sl.file_offset
        self.remaining = sl.length
        self.credited = True
        self.t0 = t0
        self.close_after = False
        self.tenant = tenant


class _EvConn:
    """One accepted connection's state machine.

    Ownership split: the READ side (reassembly, credits, parked
    requests, selector interest) belongs to the loop thread; the WRITE
    side (outbound queue + socket sends) is guarded by ``_wlock`` so
    completion threads can write inline. The stop path only reads the
    monotone ``closed``/``inflight`` flags and marshals mutations
    through ``call_soon``."""

    def __init__(self, server: "EvLoopShuffleServer", sock: socket.socket,
                 peer: str):
        self.server = server
        self.loop = server._loop
        self.sock = sock
        self.peer = peer
        # inbound reassembly: reusable recv buffer + header/payload asm
        self._rbuf = memoryview(bytearray(_RECV_CHUNK))
        self._hdr = bytearray(wire.HEADER.size)
        self._hdr_got = 0
        self._payload: Optional[bytearray] = None
        self._pay_got = 0
        self._cur = (0, 0)  # (msg_type, req_id) of the frame being read
        # outbound (under _wlock) + credit state (loop thread)
        self._wlock = TrackedLock("net.conn.write")
        self._outq: "deque" = deque()
        self._poison = False        # no more writes (torn/failed/closed)
        self._parked: "deque" = deque()  # decoded reqs waiting for CONN
        # credit (each HOLDS a tenant credit while parked when the
        # tenant plane is on — see _admit's gate order)
        self._credits = server.credit
        self._unparking = False
        # multi-tenant service plane (uda_tpu/tenant/): the MSG_JOB
        # bindings of this connection (job -> (tenant, epoch); REQs of
        # bound jobs are validated against the registry per request)
        # and the count of requests parked in the server's per-tenant
        # scheduler queues (creditless until granted)
        self.tenant = server.default_tenant
        self.bindings: dict = {}
        self._tparked = 0
        # batched byte-path serves (loop thread): requests that would
        # take the engine's byte path accumulate here during one recv's
        # frame burst / one unpark sweep and flush as ONE
        # engine.submit_batch — one pool handoff for the burst
        self._batch: list = []
        self._batch_flushing = False
        self.inflight = 0
        self._read_paused = False
        self._mask = 0
        self.draining = False
        self.closed = False

    # -- registration / interest (loop thread) -------------------------------

    def register(self) -> None:
        self.loop.register(self.sock, _READ, self._on_event)
        self._mask = _READ

    def _set_mask(self, mask: int) -> None:
        if mask == self._mask or self.closed:
            return
        if mask == 0:
            self.loop.set_events(self.sock, 0)
        elif self._mask == 0:
            self.loop.resume(self.sock, mask)
        else:
            self.loop.set_events(self.sock, mask)
        self._mask = mask

    def _update_interest(self) -> None:
        if self.closed:
            return
        mask = 0
        if not self._read_paused and not self.draining:
            mask |= _READ
        if self._outq:  # racy read is fine: _kick converges it
            mask |= _WRITE
        self._set_mask(mask)

    @loop_callback
    def _kick(self) -> None:
        """A foreign-thread writer left residual bytes: arm writable
        interest so the loop takes the backlog over."""
        self._update_interest()

    # -- inbound (loop thread) -----------------------------------------------

    @loop_callback
    def _on_event(self, mask: int) -> None:
        if self.closed:
            return
        if mask & _WRITE:
            self._flush()
        if self.closed:
            return
        if mask & _READ and not self._read_paused and not self.draining:
            # the transitive recv_into is on THIS loop's non-blocking
            # socket: it returns EWOULDBLOCK instead of parking
            self._do_read()  # udalint: disable=UDA102

    def _do_read(self) -> None:
        try:
            n = self.sock.recv_into(self._rbuf)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(TransportError("recv failed (peer reset?)"))
            return
        if n == 0:
            self._eof()
            return
        metrics.add("net.bytes.in", n, role="server")
        try:
            self._feed(self._rbuf[:n])
        except TransportError as e:
            self._drop(e)
        # one recv's decoded burst -> one batch submission (requests
        # parked for credit flush later, from the unpark sweep)
        self._flush_batch()

    def _feed(self, mv) -> None:
        """Incremental frame reassembly over one recv's bytes; may park
        requests (credit) or pause reading — state survives across
        recvs, this is the per-connection state machine."""
        off, n = 0, len(mv)
        while off < n and not self.closed:
            if self._payload is None:
                take = min(wire.HEADER.size - self._hdr_got, n - off)
                self._hdr[self._hdr_got:self._hdr_got + take] = \
                    mv[off:off + take]
                self._hdr_got += take
                off += take
                if self._hdr_got < wire.HEADER.size:
                    return
                msg_type, req_id, length = wire.decode_header(
                    bytes(self._hdr))
                self._cur = (msg_type, req_id)
                self._payload = bytearray(length)
                self._pay_got = 0
                if length == 0:
                    self._frame_done()
            else:
                take = min(len(self._payload) - self._pay_got, n - off)
                self._payload[self._pay_got:self._pay_got + take] = \
                    mv[off:off + take]
                self._pay_got += take
                off += take
                if self._pay_got == len(self._payload):
                    self._frame_done()

    def _frame_done(self) -> None:
        msg_type, req_id = self._cur
        payload = memoryview(self._payload)
        self._payload = None
        self._hdr_got = 0
        if msg_type == wire.MSG_REQ:
            req, trace = wire.decode_request_ex(payload)
            self._admit(("req", req_id, (req, trace)))
        elif msg_type == wire.MSG_SIZE_REQ:
            self._admit(("size", req_id,
                         wire.decode_size_request_ex(payload)))
        elif msg_type == wire.MSG_STATS:
            # uncredited, the HELLO precedent: an introspection poll
            # must answer even when the data pipeline holds every
            # credit (that contended state is exactly what the poller
            # wants to see). The optional CAP_OBS tail (requested
            # rollup window + sections) is length-versioned exactly
            # like the trace context — a wrong-length tail is a torn
            # frame, an absent one is the PR 11 snapshot shape.
            self._start_stats(req_id, wire.decode_stats_request(payload))
        elif msg_type == wire.MSG_JOB:
            # the tenant handshake, uncredited like HELLO. Handled
            # INLINE on the loop thread deliberately: TCP ordering is
            # the registration contract (a client sends MSG_JOB then
            # its first REQ back-to-back; dispatching the registration
            # to another thread would let the REQ overtake it). The
            # registry is a dict under a leaf lock — the only blocking
            # risk is the chaos-only tenant.register failpoint, the
            # same deliberate stall shape as net.accept's.
            self._on_job(req_id, payload)
        elif msg_type == wire.MSG_PUSH_SUB \
                and self.server.push is not None:
            # push subscription, uncredited like MSG_JOB (and inline
            # for the same TCP-ordering reason: a SUB must be recorded
            # before any REQ behind it is admitted, or the catch-up
            # pushes could race the first fetch's claim). A push-less
            # server falls through to the typed-ERR refusal below —
            # the forward-compat contract doubles as the capability
            # refusal, and the client just stays pull-only.
            try:
                job_id, reduce_id, window, chunk = \
                    wire.decode_push_sub(payload)
            except UdaError as e:
                self._drop(e)
                return
            self.server.push.subscribe(self, job_id, reduce_id,
                                       window, chunk)
        elif msg_type == wire.MSG_PUSH_ACK \
                and self.server.push is not None:
            if len(payload):
                self._drop(TransportError("malformed PUSH_ACK frame"))
                return
            self.server.push.on_ack(self, req_id)
        elif msg_type == wire.MSG_PUSH_NACK \
                and self.server.push is not None:
            try:
                reason = wire.decode_push_nack(payload)
            except UdaError as e:
                self._drop(e)
                return
            self.server.push.on_nack(self, req_id, reason)
        else:
            # in-range but unknown/unexpected type: a NEWER peer
            # probing an optional message. Refuse it with a typed ERR
            # on the same req id and keep serving — tearing the
            # connection down would fail every in-flight fetch over a
            # harmless capability probe.
            log.warn(f"net: unsupported frame type {msg_type} from "
                     f"{self.peer}; answering typed ERR")
            metrics.add("net.errors")
            err = ProtocolError(
                f"unsupported frame type {msg_type} (this peer speaks "
                f"wire v{wire.WIRE_VERSION})")
            frame = wire.encode_error(req_id, err)
            self._enqueue(_BufItem([frame], credited=False,
                                   t0=time.perf_counter()), frame)

    def _eof(self) -> None:
        if self._hdr_got or self._payload is not None:
            self._drop(TransportError("connection closed mid-frame"))
            return
        # clean peer hangup at a frame boundary: half-close — in-flight
        # responses still flush, then the connection closes itself
        self.draining = True
        self._drop_parked()  # never started; the threaded reader
        # dropped un-admitted requests on drain the same way (tenant
        # credits held by conn-parked entries flow back to neighbors)
        self.server._sweep()
        self._update_interest()
        if self.inflight == 0 and not self._outq:
            self.close()

    def _drop(self, cause: Exception) -> None:
        if not self.closed:
            log.warn(f"net: dropping connection {self.peer}: {cause}")
            metrics.add("net.disconnects", role="server")
        self.close()

    # -- the tenant handshake (loop thread) ----------------------------------

    def _on_job(self, req_id: int, payload) -> None:
        """MSG_JOB: register/heartbeat/retire one (tenant, job, epoch)
        in the daemon's registry and bind it to this connection. The
        reply is MSG_JOB_OK (granted epoch) or a typed ERR carrying
        the exact registry refusal (TenantError: auth, stale epoch,
        retired) — uncredited either way. A malformed payload raises
        TransportError out of the frame machine (stream desync — the
        caller drops the connection, every decoder's contract)."""
        tenant, job, epoch, weight, token, retire = \
            wire.decode_job(payload)
        reg = self.server.registry
        if reg is None:
            metrics.add("net.errors")
            err = ProtocolError(
                "this supplier runs no tenant plane "
                "(uda.tpu.tenant.enable is off); MSG_JOB refused")
            reply = wire.encode_error(req_id, err)
        else:
            try:
                if retire:
                    reg.retire(tenant, job, epoch, token=token)
                    # the binding is KEPT: later REQs for the job must
                    # keep flowing through validate (-> typed
                    # "retired" errors), not fall back to the unbound
                    # default-tenant pass
                    reply = wire.encode_job_ok(req_id, epoch)
                else:
                    rec = reg.register(tenant, job, epoch,
                                       weight=weight, token=token)
                    self.tenant = rec.tenant_id
                    self.bindings[job] = (rec.tenant_id, rec.epoch)
                    reply = wire.encode_job_ok(req_id, rec.epoch)
            except UdaError as e:  # typed refusal (TenantError), never
                # a teardown: the client re-raises the registry's exact
                # error and the job fails terminally, not the stream.
                # The FENCE: a refused registration poisons the job's
                # binding (epoch 0) so its REQs draw TenantError too —
                # a stale-epoch predecessor must not slide back onto
                # the unbound default-tenant pass and read its
                # successor's chunks.
                if not retire:
                    self.bindings[job] = (tenant, 0)
                metrics.add("net.errors")
                reply = wire.encode_error(req_id, e)
        self._enqueue(_BufItem([reply], credited=False,
                               t0=time.perf_counter()), reply)

    def _entry_tenant(self, entry) -> str:
        """The scheduling tenant of one decoded request: its job's
        MSG_JOB binding, else this connection's tenant (the default
        tenant for never-bound old clients)."""
        kind, _rid, body = entry
        job = body[0].job_id if kind == "req" else body[0][0]
        bound = self.bindings.get(job)
        return (bound[0] or self.tenant) if bound else self.tenant

    def _entry_cost(self, entry) -> int:
        """The WDRR deficit charge of one request: its REQUESTED bytes
        under byte quanta (uda.tpu.tenant.quantum.kb > 0), 1 in
        request-count mode. chunk_size == 0 means 'the server default'
        on the wire — charge what the engine will actually serve
        (data_engine resolves it the same way), or a zero-size request
        would draw default-sized chunks at cost 1 and defeat the byte
        fairness. SIZE probes are metadata — nominal cost 1 either
        way."""
        if not self.server.quantum_bytes:
            return 1
        kind, _rid, body = entry
        if kind != "req":
            return 1
        return max(1, int(body[0].chunk_size)
                   or self.server.chunk_bytes_default)

    # -- credit + request admission (loop thread) ----------------------------

    def _admit(self, entry) -> None:
        if self.draining:
            return  # same as the threaded credit gate under drain
        if self.server.tenancy:
            # the tenant gate FIRST (gate order invariant: an entry in
            # self._parked always HOLDS a tenant credit, an entry in
            # the scheduler's queues never does): no credit -> park in
            # the tenant's WDRR queue. Reading pauses only past the
            # per-conn HIGH-water mark (the wqe.per.conn cap — parked
            # entries are decoded request structs, not data, so the
            # memory bound is loose by design): pausing on the FIRST
            # park made each connection's queue a sawtooth that hit
            # zero before refilling, and weights cannot bite unless
            # several tenants hold backlog simultaneously
            if not self.server._sched.admit(self._entry_tenant(entry),
                                            (self, entry),
                                            cost=self._entry_cost(entry)):
                self._tparked += 1
                if not self._read_paused \
                        and self._tparked >= self.server.credit:
                    self._read_paused = True
                    self._update_interest()
                return
        self._conn_gate(entry)

    def _maybe_resume_read(self) -> None:
        """Resume reading once nothing is conn-parked and the tenant
        backlog is under the LOW-water mark (hysteresis: half the
        per-conn cap — refills land before the queue runs dry)."""
        if self._read_paused and not self._parked \
                and self._tparked <= self.server.credit // 2:
            self._read_paused = False
            self._update_interest()

    def _conn_gate(self, entry) -> None:
        """The per-connection credit bound (entry holds a tenant credit
        already when the tenant plane is on)."""
        if self._credits <= 0:
            self._parked.append(entry)
            if not self._read_paused:
                # the wqe.per.conn bound: stop READING until a response
                # settles; TCP backpressure is the credit return
                self._read_paused = True
                self._update_interest()
            return
        self._start(entry)

    def _granted(self, entry) -> None:
        """A WDRR grant arrived from the server sweep (loop thread):
        the entry now holds a tenant credit; run it through the conn
        gate and resume reading once nothing of ours is parked."""
        self._tparked -= 1
        if self.closed or self.draining:
            self.server._sched.release(self._entry_tenant(entry))
            return
        self._conn_gate(entry)
        self._maybe_resume_read()
        self._flush_batch()

    def _drop_parked(self) -> None:
        """Drop every parked entry (EOF/drain/close): conn-parked ones
        hold tenant credits — release them; scheduler-parked ones are
        creditless — just remove them from the queues."""
        if self.server.tenancy:
            for entry in self._parked:
                self.server._sched.release(self._entry_tenant(entry))
            if self._tparked:
                self.server._sched.drop_conn(self)
                self._tparked = 0
        self._parked.clear()

    def _start(self, entry) -> None:
        kind, req_id, body = entry
        self._credits -= 1
        self.inflight += 1
        metrics.gauge_add("net.server.inflight", 1)
        if kind == "req":
            self._start_req(req_id, body)
        else:
            self._start_size(req_id, body)

    def _settle(self, credited: bool, tenant: str = "") -> None:
        """The single credit-settle point (loop thread): every response
        — written, torn or abandoned — feeds through here exactly once.
        ``tenant`` is the credit's scheduler account (rides the
        outbound item so out-of-order completion settles the right
        tenant); empty falls back to the connection's tenant.

        The unpark loop is ITERATIVE, not recursive: starting a parked
        entry can serve it fully inline (try_plan -> enqueue -> send
        completes -> settle), which re-enters here — the ``_unparking``
        guard turns that nested settle into a plain credit increment
        and the OUTER while loop picks it up. Without the guard a
        backlog of a few hundred parked requests blew the recursion
        limit and tore the connection down under plain burst load.
        (The server-wide WDRR sweep has the same guard on the server,
        ``_sweeping`` — a grant that serves inline re-enters here.)"""
        if not credited:
            return
        self._credits += 1
        self.inflight -= 1
        metrics.gauge_add("net.server.inflight", -1)
        if self.server.tenancy:
            self.server._sched.release(tenant or self.tenant)
        if self.closed or self.draining or self._unparking:
            if not self.closed:
                self.server._sweep()  # the freed tenant credit must
                # still flow to parked neighbors even when this conn
                # cannot unpark (nested settles hit the sweep guard)
            return
        self._unparking = True
        try:
            while self._credits > 0 and self._parked \
                    and not self.closed and not self.draining:
                # conn-parked entries already hold their tenant credit
                # (the _admit gate order) — no second tenant gate here
                self._start(self._parked.popleft())
            self._maybe_resume_read()
        finally:
            self._unparking = False
        # the unpark sweep's byte-path starts batch exactly like a
        # recv burst's (nested settles returned at the guard above and
        # never reach here — the OUTER settle flushes once)
        self._flush_batch()
        # weighted-fair grant sweep: the freed tenant credit may belong
        # to ANOTHER connection's parked backlog
        self.server._sweep()

    def _settle_offloop(self, res, span, tenant: str = "") -> None:
        """Settle a completion that arrived for a dead connection (or
        after the loop stopped): runs on whatever thread noticed. The
        loop no longer touches this connection's state, so the gauge
        decrement cannot race a loop-side settle. The tenant credit is
        marshalled back to the loop (the scheduler is loop-confined);
        a dead loop means a dead scheduler — nothing to return to."""
        if isinstance(res, FdSlice):
            res.release()
        metrics.gauge_add("net.server.inflight", -1)
        span.end(error="closed")
        if self.server.tenancy and self.loop.alive():
            self.loop.call_soon(self.server._release_and_sweep,
                                tenant or self.tenant)

    # -- serving -------------------------------------------------------------

    def _start_req(self, req_id: int, body) -> None:
        req, trace = body
        metrics.add("net.requests")
        t0 = time.perf_counter()
        # wire-level trace adoption: a REQ that carried (trace_id,
        # parent_span_id) makes this serve span a CHILD of the remote
        # reduce task's fetch span — the supplier-side work it caused
        # lands in the same trace tree, stitched across processes by
        # scripts/trace_merge.py
        parent = (metrics.remote_parent(*trace) if trace is not None
                  else None)
        span = metrics.start_span("net.serve", parent=parent,
                                  map=req.map_id,
                                  reduce=req.reduce_id, offset=req.offset,
                                  peer=self.peer)
        try:
            if self.server.tenancy:
                # THE per-REQ registry gate: a bound job is validated
                # every request (unknown/retired -> typed TenantError;
                # a stale epoch fences a restarted job's predecessor
                # off its successor's chunks). The tenant is stamped
                # from the connection's AUTHENTICATED binding — never
                # anything the request payload could spoof — and
                # BEFORE validation, so a refused request's ERR item
                # settles its credit under the SAME tenant the _admit
                # gate charged (the engine partitions and metric
                # labels read the same stamp).
                req = dataclasses.replace(
                    req, tenant=self._entry_tenant(
                        ("req", req_id, (req, trace))))
                self.server._validate_req(self, req)
            # the engine adopts the serve span across its pool handoff
            # (DataEngine.submit captures the current span), so
            # engine.pread / zero-copy plan work is a child of net.serve
            with metrics.use_span(span):
                if self.server.zero_copy:
                    # the inline fast path: an index-cache hit plans the
                    # (fd, offset, len) slice right here on the loop
                    # thread and the response leaves without a single
                    # pool handoff — every chunk after a partition's
                    # first
                    plan = self.server.engine.try_plan(req)
                    if plan is not None:
                        self._complete(req_id, plan, None, t0, span, req)
                        return
                if self.server.batch_reads and not (
                        self.server.zero_copy
                        and self.server.engine.slice_eligible()):
                    # the byte path will be taken (zerocopy off, CRC
                    # stamping on, or the pread failpoint armed):
                    # accumulate the burst and flush ONE submit_batch
                    # (uda.tpu.read.batch; the RDMAbox lesson) instead
                    # of one pool handoff per chunk
                    self._batch.append((req_id, req, t0, span))
                    return
                if self.server.zero_copy:
                    fut = self.server.engine.submit_serve(req)
                else:
                    fut = self.server.engine.submit(req)
        except Exception as e:  # noqa: BLE001 - sync rejection (stopped
            # engine, admission push-back, bad offset) -> typed ERR
            self._complete(req_id, None, e, t0, span, req)
            return
        fut.add_done_callback(
            lambda f: self._engine_done(req_id, f, t0, span, req))

    def _flush_batch(self) -> None:
        """Submit the accumulated byte-path burst (loop thread). The
        loop is ITERATIVE like the unpark sweep: a synchronously-
        failed batch (stopped engine) completes inline -> settle ->
        unpark -> more entries may land in self._batch — the outer
        while picks them up instead of recursing."""
        if self._batch_flushing or self.closed or not self._batch:
            return
        self._batch_flushing = True
        try:
            while self._batch:
                entries, self._batch = self._batch, []
                bmax = self.server.batch_max
                for i in range(0, len(entries), bmax):
                    part = entries[i:i + bmax]
                    futs = self.server.engine.submit_batch(
                        [ent[1] for ent in part],
                        parent_spans=[ent[3] for ent in part])
                    for (req_id, req, t0, span), fut in zip(part, futs):
                        fut.add_done_callback(
                            lambda f, req_id=req_id, t0=t0, span=span,
                            req=req:
                            self._engine_done(req_id, f, t0, span, req))
        finally:
            self._batch_flushing = False

    def _engine_done(self, req_id: int, f, t0: float, span, req) -> None:
        """Engine worker thread (or the loop, when the future was
        already resolved at callback registration)."""
        err = f.exception()
        res = None if err is not None else f.result(timeout=0)
        if self.closed or not self.loop.alive():
            self._settle_offloop(res, span,
                                 getattr(req, "tenant", ""))
            return
        self._complete(req_id, res, err, t0, span, req)

    def _complete(self, req_id: int, res, err, t0: float, span,
                  req=None) -> None:
        """Engine completion -> outbound item, on the COMPLETING thread
        (inline-write fast path). Responses complete out of order
        across requests, exactly like the threaded core's
        future->queue pipeline."""
        tenant = getattr(req, "tenant", "") if req is not None else ""
        try:
            if err is not None:
                head = wire.encode_error(req_id, err)
                item = _BufItem([head], credited=True, t0=t0,
                                tenant=tenant)
                metrics.add("net.errors")
                span.end(error=type(err).__name__)
                if self.server.tenancy and tenant and \
                        isinstance(err, (StorageError, TenantError)):
                    # tenant-scoped penalty feedback: repeated
                    # admission push-back / injected faults box THIS
                    # tenant in the WDRR (deprioritized, not starved);
                    # marshalled — the scheduler is loop-confined
                    self.loop.call_soon(self.server._note_fault, tenant)
            elif isinstance(res, FdSlice):
                view = (res.view()
                        if self.server.zc_mode == "mmap" else None)
                if view is None and self.server._sendfile_refused:
                    # last rung: neither sendfile (refused) nor mmap
                    # (unmappable file) works — serve the bytes once
                    # and stop planning slices; future requests take
                    # the engine's worker-thread byte path
                    data = os.pread(res.fd, res.length, res.file_offset)
                    if len(data) != res.length:
                        # truncated MOF under its cached index entry:
                        # fail loudly (the _send_file fallback's exact
                        # contract), never serve a silently-short frame
                        raise TransportError(
                            f"short read {len(data)}/{res.length} at "
                            f"{res.path}:{res.file_offset}")
                    res.release()
                    self.server.zero_copy = False
                    log.warn("net: zero-copy serve disabled (sendfile "
                             "refused and MOF not mappable); serving "
                             "via engine byte reads")
                    head = wire.encode_result_head(
                        req_id, raw_length=res.raw_length,
                        part_length=res.part_length, offset=res.offset,
                        last=res.last, path=res.path, crc=None,
                        data_len=len(data))
                    item = _BufItem([head, data], credited=True, t0=t0,
                                    tenant=tenant)
                    self._count_serve("net.serve.copy", tenant)
                    span.end(bytes=len(data))
                else:
                    head = wire.encode_result_head(
                        req_id, raw_length=res.raw_length,
                        part_length=res.part_length, offset=res.offset,
                        last=res.last, path=res.path, crc=None,
                        data_len=res.length)
                    if view is not None:
                        # mmap mode: the chunk memoryview points into
                        # the MOF's page-cache mapping — sendmsg moves
                        # it kernel-side, no Python-heap object either
                        item = _BufItem([head, view], credited=True,
                                        t0=t0, sl=res,
                                        zc_bytes=res.length,
                                        tenant=tenant)
                    else:
                        item = _FileItem(head, res, t0, tenant=tenant)
                    self._count_serve("net.serve.fd", tenant)
                    span.end(bytes=res.length, zero_copy=True)
            else:
                head = wire.encode_result_head(
                    req_id, raw_length=res.raw_length,
                    part_length=res.part_length, offset=res.offset,
                    last=res.last, path=res.path, crc=res.crc,
                    data_len=len(res.data))
                item = _BufItem([head, res.data], credited=True, t0=t0,
                                tenant=tenant)
                self._count_serve("net.serve.copy", tenant)
                span.end(bytes=len(res.data))
        except Exception as e:  # noqa: BLE001 - an unencodable response
            # would strand the request's credit; settle and drop, the
            # client re-fetches on the disconnect (threaded parity)
            log.error(f"net: response encoding for {self.peer} failed: "
                      f"{e}; dropping the connection")
            if isinstance(res, FdSlice):
                res.release()
            span.end(error="encode_failed")
            self.loop.call_soon(self._abandon_item,
                                _BufItem([], credited=True, t0=t0,
                                         tenant=tenant), e)
            return
        if err is None and req is not None:
            # warm-restart watermark: the highest partition offset this
            # server has answered (advisory — the resuming client's own
            # offset ledger is authoritative; see the handoff docstring)
            served = res.length if isinstance(res, FdSlice) \
                else len(res.data)
            self.server._mark_served(self.peer, req, req.offset + served,
                                     tenant=tenant)
        self._enqueue(item, head)

    @staticmethod
    def _count_serve(name: str, tenant: str) -> None:
        """Serve-path counters with a tenant label when the request is
        tenant-stamped (both the total and the series advance);
        literal names only — the metrics linter audits call sites."""
        if name == "net.serve.fd":
            if tenant:
                metrics.add("net.serve.fd", tenant=tenant)
            else:
                metrics.add("net.serve.fd")
        else:
            if tenant:
                metrics.add("net.serve.copy", tenant=tenant)
            else:
                metrics.add("net.serve.copy")

    def _start_size(self, req_id: int, body) -> None:
        """SIZE probes are credited like DATA (no frame escapes the
        wqe.per.conn bound) but the resolver sums may ride an embedder
        upcall — run them on the dispatcher thread, never the loop."""
        (job_id, mids, reduce_id), trace = body
        t0 = time.perf_counter()
        self.loop.dispatch(self._do_size, req_id, job_id, mids,
                           reduce_id, t0, trace,
                           self._entry_tenant(("size", req_id, body))
                           if self.server.tenancy else "")

    def _do_size(self, req_id: int, job_id: str, mids, reduce_id: int,
                 t0: float, trace=None, tenant: str = "") -> None:
        """Dispatcher thread: delegate to LocalFetchClient so wire and
        in-process estimates cannot diverge (exact-or-unknown). A
        wire-carried trace context parents the serve span under the
        remote net.size_probe, same adoption as _start_req."""
        from uda_tpu.merger.segment import LocalFetchClient

        parent = (metrics.remote_parent(*trace) if trace is not None
                  else None)
        span = metrics.start_span("net.serve", parent=parent, kind="size",
                                  reduce=reduce_id, peer=self.peer)
        with metrics.use_span(span):
            total = LocalFetchClient(self.server.engine) \
                .estimate_partition_bytes(job_id, mids, reduce_id)
        span.end(known=total is not None)
        frame = wire.encode_size(req_id, total)
        if self.closed or not self.loop.alive():
            metrics.gauge_add("net.server.inflight", -1)
            if self.server.tenancy and self.loop.alive():
                self.loop.call_soon(self.server._release_and_sweep,
                                    tenant or self.tenant)
            return
        self._enqueue(_BufItem([frame], credited=True, t0=t0,
                               tenant=tenant), frame)

    def _start_stats(self, req_id: int,
                     opt: Optional[tuple] = None) -> None:
        """MSG_STATS (loop thread): snapshot building walks metrics and
        provider locks — cheap, but off the loop on principle (a
        provider is component code). Uncredited: the reply rides the
        outbound queue like the HELLO banner. ``opt`` is the decoded
        CAP_OBS tail (window seconds, section bits) or None for the
        plain PR 11 poll."""
        self.loop.dispatch(self._do_stats, req_id, opt)

    def _do_stats(self, req_id: int, opt: Optional[tuple] = None) -> None:
        """Dispatcher thread: build + encode the introspection
        snapshot, folding in the observability sections a CAP_OBS
        poller asked for (time-series window, per-tenant SLI book,
        active anomalies). Old pollers pay nothing: the sections are
        built only on request."""
        from uda_tpu.utils.stats import introspection_snapshot

        metrics.add("net.stats.requests")
        try:
            snap = introspection_snapshot()
            if opt is not None:
                window_s, sections = opt
                if sections & wire.STATS_SEC_TS:
                    from uda_tpu.utils.timeseries import timeseries
                    snap["timeseries"] = timeseries.wire_block(
                        seconds=window_s or None)
                if sections & wire.STATS_SEC_SLI:
                    from uda_tpu.tenant.sli import sli_book
                    snap["sli"] = sli_book.snapshot()
                if sections & wire.STATS_SEC_ANOMALY:
                    from uda_tpu.utils.anomaly import anomaly_engine
                    snap["anomalies"] = anomaly_engine.snapshot()
            frame = wire.encode_stats_reply(req_id, snap)
        except Exception as e:  # noqa: BLE001 - an unencodable snapshot
            # must degrade to a typed ERR, never strand the poller
            log.warn(f"net: stats snapshot failed: {e}")
            frame = wire.encode_error(req_id, e)
        if self.closed or not self.loop.alive():
            return  # uncredited: nothing to settle
        self._enqueue(_BufItem([frame], credited=False,
                               t0=time.perf_counter()), frame)

    # -- outbound (any thread; _wlock serializes writers) --------------------

    def push_frame(self, frame: bytes, close_after: bool = False) -> None:
        """Queue one supplier-initiated frame (MSG_PUSH), any thread.
        Uncredited — the push plane runs its OWN window (PUSH_ACK
        settles it), so pushes never consume the fetch pipeline's
        credits; ordering and inline writes ride the normal outbound
        path."""
        self._enqueue(_BufItem([frame], credited=False,
                               t0=time.perf_counter(),
                               close_after=close_after), frame)

    def _enqueue(self, item, head: bytes) -> None:
        """Queue one response and opportunistically write it NOW on the
        calling thread. The net.frame failpoint fires here, once per
        response frame, against the frame HEAD — a truncated head is a
        torn frame (the peer's stream desyncs mid-header/meta)
        regardless of how the chunk itself would have travelled."""
        try:
            out = failpoint("net.frame", data=head, key=self.peer)
        except Exception as e:  # noqa: BLE001 - injected send failure:
            # the connection is over (threaded write-loop parity)
            _release_item(item)
            self.loop.call_soon(self._abandon_item, item, e)
            return
        if len(out) != len(head):
            # torn frame: send the damaged head bytes, then finish the
            # damage deterministically (mid-stream disconnect)
            _release_item(item)
            item = _BufItem([out], credited=item.credited, t0=item.t0,
                            close_after=True,
                            tenant=getattr(item, "tenant", ""))
        abandoned = False
        with self._wlock:
            if self.closed or self._poison:
                abandoned = True
            else:
                self._outq.append(item)
                completed, err = self._drain_locked()
                backlog = bool(self._outq) and not self._poison
        if abandoned:
            _release_item(item)
            self.loop.call_soon(self._abandon_item, item, None)
            return
        on_loop = self.loop.on_loop_thread()
        for it in completed:
            if on_loop:
                self._settle_item(it)
            else:
                self.loop.call_soon(self._settle_item, it)
        if err is not None:
            self.loop.call_soon(self._writer_failed, err)
        elif backlog:
            if on_loop:
                self._update_interest()
            else:
                self.loop.call_soon(self._kick)

    def _drain_locked(self):
        """_wlock held. Send from the queue head until it would block.
        Returns (completed items, fatal send error or None)."""
        completed = []
        while self._outq and not self._poison:
            item = self._outq[0]
            try:
                done = (self._send_file(item)
                        if isinstance(item, _FileItem)
                        else self._send_bufs(item))
            except (BlockingIOError, InterruptedError):
                break
            except Exception as e:  # noqa: BLE001 - send failure: peer
                # gone or injected; the client's reader sees the
                # disconnect and fails its in-flight fetches into the
                # Segment retry machinery
                self._poison = True
                return completed, e
            if not done:
                break
            self._outq.popleft()
            completed.append(item)
            if item.close_after:
                self._poison = True
                break
        return completed, None

    @loop_callback
    def _flush(self) -> None:
        """Loop-side writable handler: take the backlog over."""
        with self._wlock:
            completed, err = self._drain_locked()
        for it in completed:
            self._settle_item(it)
        if err is not None:
            self._writer_failed(err)
            return
        self._update_interest()
        if self.draining and self.inflight == 0 and not self._outq:
            self.close()

    @loop_callback
    def _settle_item(self, item) -> None:
        if item.credited:
            metrics.observe("net.frame.latency_ms",
                            (time.perf_counter() - item.t0) * 1e3,
                            role="server")
        self._settle(item.credited, getattr(item, "tenant", ""))
        if item.close_after and not self.closed:
            log.warn(f"net: frame to {self.peer} torn by failpoint; "
                     f"closing")
            metrics.add("net.disconnects", role="server")
            self.close()
        elif self.draining and self.inflight == 0 and not self._outq:
            self.close()

    @loop_callback
    def _abandon_item(self, item, cause) -> None:
        """Settle a response that will never be written (enqueued
        against a closed/poisoned connection, injected send failure, or
        unencodable)."""
        self._settle(item.credited, getattr(item, "tenant", ""))
        if cause is not None:
            if not self.closed:
                log.warn(f"net: send to {self.peer} failed: {cause}")
                metrics.add("net.disconnects", role="server")
            self.close()

    @loop_callback
    def _writer_failed(self, cause: Exception) -> None:
        if not self.closed:
            log.warn(f"net: send to {self.peer} failed: {cause}")
            metrics.add("net.disconnects", role="server")
        self.close()

    def _send_bufs(self, item: _BufItem) -> bool:
        while item.bufs:
            sent = self.sock.sendmsg(item.bufs)
            metrics.add("net.bytes.out", sent, role="server")
            while sent:
                if sent >= len(item.bufs[0]):
                    sent -= len(item.bufs[0])
                    item.bufs.pop(0)
                else:
                    item.bufs[0] = item.bufs[0][sent:]
                    sent = 0
        if item.zc_bytes:
            metrics.add("net.mmap.bytes", item.zc_bytes)
        if item.slice is not None:
            item.slice.release()
        return True

    def _send_file(self, item: _FileItem) -> bool:
        while item.head is not None:
            n = self.sock.send(item.head)
            metrics.add("net.bytes.out", n, role="server")
            item.head = item.head[n:] if n < len(item.head) else None
        while item.remaining:
            try:
                n = os.sendfile(self.sock.fileno(), item.slice.fd,
                                item.file_off,
                                min(item.remaining, _SENDFILE_MAX))
            except OSError as e:
                if isinstance(e, (BlockingIOError, InterruptedError)):
                    raise
                if e.errno in _SENDFILE_FALLBACK_ERRNOS:
                    # fs/socket pairing refuses the splice: degrade to
                    # the one-copy pread + sendmsg ladder rung, and
                    # memoize the refusal so this stays a ONE-shot
                    # event, not a per-chunk loop-stalling disk read
                    self.server._sendfile_refused_once()
                    metrics.add("net.serve.copy")
                    data = os.pread(item.slice.fd, item.remaining,
                                    item.file_off)
                    if len(data) != item.remaining:
                        raise TransportError(
                            f"short read {len(data)}/{item.remaining} "
                            f"at {item.slice.path}:{item.file_off}")
                    item.slice.release()
                    self._outq[0] = _BufItem(
                        [data], credited=item.credited, t0=item.t0,
                        tenant=item.tenant)
                    return self._send_bufs(self._outq[0])
                raise
            if n == 0:
                raise TransportError(
                    f"sendfile hit EOF mid-chunk at {item.slice.path}:"
                    f"{item.file_off} (truncated MOF?)")
            item.file_off += n
            item.remaining -= n
            metrics.add("net.bytes.out", n, role="server")
            metrics.add("net.sendfile.bytes", n)
        item.slice.release()
        return True

    # -- teardown (loop thread) ----------------------------------------------

    @loop_callback
    def begin_drain(self) -> None:
        """Stop reading; let in-flight responses flush (the stop(drain=
        True) path)."""
        if self.closed or self.draining:
            return
        self.draining = True
        self._drop_parked()
        self.server._sweep()
        self._update_interest()
        if self.inflight == 0 and not self._outq:
            self.close()

    def drained(self) -> bool:
        return self.inflight == 0 and not self._outq

    @loop_callback
    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self.loop.unregister(self.sock)
        wire.close_hard(self.sock)  # shutdown-then-close: forces the
        # FIN out and wakes the peer's blocked reader (see close_hard)
        with self._wlock:
            items = list(self._outq)
            self._outq.clear()
            self._poison = True
        for item in items:
            _release_item(item)
            self._settle(item.credited, getattr(item, "tenant", ""))
        # batched-but-unflushed requests die with the connection: they
        # were credited at _start, so settle them like torn responses
        # (closed flag is set — _settle only rebalances the gauge and
        # returns the tenant credit)
        batch, self._batch = self._batch, []
        for (_req_id, req, _t0, span) in batch:
            span.end(error="closed")
            self._settle(True, getattr(req, "tenant", ""))
        self._drop_parked()
        if self.server.push is not None:
            # settle the push window (resledger: a dead peer must not
            # strand push.on_air) and forget its subscriptions
            self.server.push.drop_conn(self)
        self.server._forget(self)
        metrics.gauge_add("net.server.connections", -1)
        self.server._sweep()  # freed tenant credits flow to neighbors


class EvLoopShuffleServer:
    """Serves many concurrent reduce clients over TCP from one
    DataEngine, all on one event loop. ``port=0`` binds an ephemeral
    port (tests); read the bound address back from :attr:`address` /
    :attr:`port`."""

    def __init__(self, engine: DataEngine, config: Optional[Config] = None,
                 host: Optional[str] = None, port: Optional[int] = None,
                 registry=None):
        cfg = config or Config()
        self.engine = engine
        self.bind_host = host if host is not None \
            else str(cfg.get("uda.tpu.net.bind"))
        self.bind_port = int(port if port is not None
                             else cfg.get("uda.tpu.net.port"))
        self.credit = max(1, int(cfg.get("mapred.rdma.wqe.per.conn")))
        # multi-tenant service plane (uda_tpu/tenant/): on when a
        # registry is injected or uda.tpu.tenant.enable is set. Off =
        # the single-job data plane of PRs 4-13, bit for bit (no
        # registry lookups, no scheduler, empty tenant stamps).
        self.tenancy = registry is not None \
            or bool(cfg.get("uda.tpu.tenant.enable"))
        self.registry = registry
        self._sched = None
        self.quantum_bytes = 0
        self.default_tenant = ""
        self.strict_tenancy = False
        self._sweeping = False
        if self.tenancy:
            from uda_tpu.tenant import (DEFAULT_TENANT, CreditScheduler,
                                        TenantRegistry)
            if self.registry is None:
                self.registry = TenantRegistry.from_config(cfg)
            self.default_tenant = DEFAULT_TENANT
            self.strict_tenancy = bool(cfg.get("uda.tpu.tenant.strict"))
            # the shared credit pool: uda.tpu.tenant.wqe.total, default
            # = the per-conn cap (the bound the single knob provided,
            # now weighted-fair ACROSS connections and jobs)
            total = int(cfg.get("uda.tpu.tenant.wqe.total")) \
                or self.credit
            # byte-cost quanta: deficits earned/charged in requested
            # bytes so mixed chunk sizes stay byte-fair (0 = the
            # request-count quanta of the original scheduler); a
            # chunk_size=0 REQ is charged the engine's default serve
            # size (the same resolution data_engine applies)
            self.quantum_bytes = max(
                0, int(cfg.get("uda.tpu.tenant.quantum.kb"))) * 1024
            # the ENGINE's own default-serve size — one resolution,
            # read not re-derived (stub engines in tests fall back to
            # the same flag the engine derives it from)
            self.chunk_bytes_default = max(1, int(getattr(
                engine, "chunk_size_default",
                int(cfg.get("mapred.rdma.buf.size")) * 1024)))
            self._sched = CreditScheduler(
                total, weight_of=self.registry.weight_of,
                quantum=float(self.quantum_bytes or 1),
                penalty_threshold=int(
                    cfg.get("uda.tpu.tenant.penalty.threshold")),
                penalty_ms=int(cfg.get("uda.tpu.tenant.penalty.ms")))
            # per-tenant read-budget partitions + retire-time ledger
            # drains (getattr: stub engines in tests have no registry
            # seam and simply skip the partition layer)
            wire_registry = getattr(engine, "set_tenant_registry", None)
            if wire_registry is not None:
                wire_registry(self.registry)
        self.drain_s = float(cfg.get("uda.tpu.net.drain.s"))
        self.sockbuf_kb = int(cfg.get("uda.tpu.net.sockbuf.kb"))
        self.zero_copy = bool(cfg.get("uda.tpu.net.zerocopy"))
        mode = str(cfg.get("uda.tpu.net.zerocopy.mode")).strip().lower()
        if not self.zero_copy:
            self.zc_mode = "off"
        elif mode in ("sendfile", "mmap"):
            self.zc_mode = mode
        else:  # auto: probe once per process
            self.zc_mode = _pick_zerocopy_mode()
        self._sendfile_refused = False
        # batched byte-path serves (uda.tpu.read.batch; the engine owns
        # the knob/tuning-cache resolution — getattr keeps stub engines
        # in tests working)
        self.batch_reads = bool(getattr(engine, "batch_enabled", False))
        self.batch_max = int(getattr(engine, "batch_max", 256))
        self._cfg = cfg  # start() arms the live-telemetry plane from it
        self._listener: Optional[socket.socket] = None
        self._loop: Optional[EventLoop] = None
        self._conns: set = set()
        self._lock = TrackedLock("net.server")
        self._stopping = threading.Event()
        # warm-restart handoff (uda.tpu.net.handoff.path): generation
        # identity + served-offset watermarks; minted per start()
        self.handoff_path = str(cfg.get("uda.tpu.net.handoff.path"))
        self.generation = 0
        self.warm_restart = False
        # elastic drain (ISSUE 18): once announce_drain() flips this,
        # every subsequent HELLO banner carries CAP_DRAINING so reduce
        # sides stop placing NEW work here while in-flight serves
        # complete; the store layer migrates retained MOFs in parallel
        # udarace: lockfree=_draining - one-way bool latch flipped by
        # the control thread; the loop reading it one accept late just
        # sends one more non-draining banner (harmless, self-corrects)
        self._draining = False
        self._marks: dict = {}  # "peer|job|map|reduce" -> served end
        self._marks_lock = threading.Lock()
        # push plane (ISSUE 19, uda.tpu.push.enable): supplier-
        # initiated MSG_PUSH of committed partitions to subscribed
        # reduce connections. Off = the pull-only plane, bit for bit
        # (no CAP_PUSH in the banner, MSG_PUSH_SUB answered with the
        # typed-ERR refusal every unknown frame gets).
        self.push = None
        if bool(cfg.get("uda.tpu.push.enable")):
            from uda_tpu.net.push import PushScheduler
            self.push = PushScheduler(self, engine, cfg)

    # -- warm-restart handoff -----------------------------------------------

    def _load_generation(self) -> tuple[int, bool]:
        """The advertised server generation: a persisted handoff record
        continues as generation+1 with the warm flag (clients may keep
        resumed offsets); without one — first boot, kill -9, unreadable
        record — a fresh random generation is minted so a COLD restart
        can never masquerade as the same server instance."""
        path = self.handoff_path
        if path:
            try:
                failpoint("net.handoff", key="load")
                with open(path) as f:
                    rec = json.load(f)
                # CONSUME the record: it proves exactly ONE graceful
                # stop. Left in place, a later kill -9 would replay it
                # and the cold restart would advertise the same warm
                # generation as the killed instance — clients would
                # see no generation change and keep resuming against
                # possibly-different bytes.
                os.unlink(path)
                gen = (int(rec["generation"]) + 1) & 0x7FFFFFFF
                metrics.add("net.handoff.loaded")
                return max(1, gen), True
            except FileNotFoundError:
                pass  # first boot: cold by definition
            except Exception as e:  # noqa: BLE001 - a bad record is a
                # cold start, never a refused start
                metrics.add("errors.swallowed")
                log.warn(f"net: handoff record {path} unreadable ({e}); "
                         f"cold start")
        gen = int.from_bytes(os.urandom(4), "big") & 0x7FFFFFFF
        return max(1, gen), False

    # -- the weighted-fair credit plane (loop thread) ------------------------

    def _sweep(self) -> None:
        """The WDRR grant sweep: move freed credits to parked requests
        across ALL connections by weighted deficit round-robin.
        ITERATIVE like the per-conn unpark loop (the PR 6 recursion
        lesson): a grant served fully inline re-enters via _settle —
        the ``_sweeping`` guard turns that into a no-op and the outer
        loop re-runs grant_parked until nothing moves."""
        if not self.tenancy or self._sweeping:
            return
        self._sweeping = True
        try:
            while True:
                granted = self._sched.grant_parked()
                if not granted:
                    return
                for conn, entry in granted:
                    conn._granted(entry)
        finally:
            self._sweeping = False

    def _release_and_sweep(self, tenant: str) -> None:
        """Loop-marshalled credit return for off-loop settles (dead
        connection, stopped-loop races)."""
        if self.tenancy:
            self._sched.release(tenant)
            self._sweep()

    def _note_fault(self, tenant: str) -> None:
        """Loop-marshalled tenant-penalty feedback (see _complete)."""
        if self.tenancy:
            self._sched.note_fault(tenant)

    def _validate_req(self, conn: _EvConn, req) -> None:
        """The per-REQ registry gate. Bound jobs validate every
        request (typed TenantError on unknown/retired/stale-epoch).
        Never-bound jobs keep the pre-tenancy contract — they ride the
        default tenant — unless ``uda.tpu.tenant.strict`` demands
        registration. (The tenant itself is resolved by
        ``_entry_tenant`` and stamped before this gate runs, so a
        refusal settles the same account the admit charged.)"""
        bound = conn.bindings.get(req.job_id)
        if bound is None:
            if self.strict_tenancy:
                raise TenantError(
                    f"job {req.job_id!r} is not registered on this "
                    f"connection and the daemon requires MSG_JOB "
                    f"registration (uda.tpu.tenant.strict)")
            return
        tenant, epoch = bound
        if epoch <= 0:
            raise TenantError(
                f"job {req.job_id!r}: registration was refused on "
                f"this connection (stale epoch or failed auth); its "
                f"fetches stay fenced")
        self.registry.validate(tenant, req.job_id, epoch)

    _MARKS_CAP = 4096  # bound the table: oldest partition evicted

    def _mark_served(self, peer: str, req, end: int,
                     tenant: str = "") -> None:
        """Track the served-offset watermark per PARTITION (not per
        conn — peers carry ephemeral ports, and keying by them would
        grow the table one entry per reconnect for the server's
        lifetime). Advisory: it may lead the wire by in-flight frames
        — resume correctness never depends on it (the CLIENT's offset
        ledger is authoritative); the record is the drain proof +
        diagnostics a restarted supplier starts from. Bounded: beyond
        the cap the oldest partition's mark is evicted (insertion
        order — long-finished partitions go first).

        Keyed by (tenant, job, map, reduce) — partition identity alone
        was the PR 8 single-tenant assumption: two tenants may carry
        the SAME job/map/reduce ids (each embedder mints its own), and
        a warm bounce must never hand one job's served offsets to
        another's fetch ledger."""
        if not self.handoff_path:
            return
        key = f"{tenant}|{req.job_id}|{req.map_id}|{req.reduce_id}"
        with self._marks_lock:
            if end > self._marks.get(key, -1):
                self._marks.pop(key, None)  # refresh insertion order
                self._marks[key] = end
                if len(self._marks) > self._MARKS_CAP:
                    self._marks.pop(next(iter(self._marks)))

    def _write_handoff(self) -> None:
        if not self.handoff_path:
            return
        with self._marks_lock:
            marks = dict(self._marks)
        try:
            failpoint("net.handoff", key="save")
            tmp = self.handoff_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"generation": self.generation,
                           "watermarks": marks}, f)
            os.replace(tmp, self.handoff_path)
            metrics.add("net.handoff.persisted")
        except Exception as e:  # noqa: BLE001 - losing the handoff
            # downgrades the NEXT start to cold; it must not turn a
            # graceful stop into a crash
            metrics.add("errors.swallowed")
            log.warn(f"net: handoff record {self.handoff_path} not "
                     f"persisted ({e}); next start will be cold")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "EvLoopShuffleServer":
        if self._listener is not None:
            raise UdaError("ShuffleServer already started")
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self.bind_host, self.bind_port))
        ls.listen(128)
        ls.setblocking(False)
        # the handoff record is CONSUMED by _load_generation, so it
        # must survive a failed start: load only after bind/listen
        # succeeded — a transient EADDRINUSE (old socket in TIME_WAIT)
        # must not silently downgrade the supervisor's retry to cold
        self.generation, self.warm_restart = self._load_generation()
        metrics.gauge("net.server.generation", self.generation)
        self._listener = ls
        self._stopping.clear()
        self._loop = EventLoop("uda-net-loop").start()
        self._loop.call_soon(self._loop.register, ls, _READ,
                             self._on_accept)
        # the MSG_STATS scrape surface: this server's conn table +
        # generation, folded into every introspection snapshot — plus
        # the time-accounting block (serve-bucket-dominant on a pure
        # supplier), so udatop's where-time-goes column answers for
        # both roles
        from uda_tpu.utils.critpath import install_stats_provider
        from uda_tpu.utils.stats import register_stats_provider
        register_stats_provider("net.server", self._stats_snapshot)
        install_stats_provider()
        # the live-telemetry plane (ISSUE 17): rollup ring + anomaly
        # detectors + SLI book + optional OpenMetrics exposition —
        # armed once per process, gated on the stats plane like the
        # StatsReporter (arm_observability_plane is idempotent)
        from uda_tpu.utils.timeseries import arm_observability_plane
        arm_observability_plane(self._cfg)
        if self.tenancy and self._sched is not None:
            # the fairness audit needs the scheduler's granted-byte
            # view regardless of whether the ring is armed yet — the
            # book holds state only once rollups flow
            from uda_tpu.tenant.sli import sli_book
            sli_book.attach(scheduler=self._sched,
                            registry=self.registry)
        log.info(f"shuffle server listening on {self.address[0]}:"
                 f"{self.address[1]} (credit/conn={self.credit}, "
                 f"core=evloop, zerocopy={self.zero_copy}, "
                 f"generation={self.generation}"
                 f"{' warm' if self.warm_restart else ''})")
        return self

    @property
    def address(self) -> tuple:
        if self._listener is None:
            raise UdaError("ShuffleServer not started")
        return self._listener.getsockname()[:2]

    @property
    def port(self) -> int:
        return self.address[1]

    @loop_callback
    def _on_accept(self, mask: int) -> None:
        ls = self._listener  # stop() nulls the attribute concurrently
        if ls is None:
            return
        while True:
            try:
                sock, addr = ls.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return  # listener closed (stop path)
            peer = f"{addr[0]}:{addr[1]}"
            try:
                # slow-accept / dropped-at-birth injection point (a
                # delay here stalls the loop like a slow accept stalls
                # the reference's cm_event_handler — chaos-only)
                failpoint("net.accept", key=peer)
            except UdaError as e:
                log.warn(f"net: accept of {peer} rejected: {e}")
                wire.close_hard(sock)
                continue
            sock.setblocking(False)
            wire.tune_socket(sock, self.sockbuf_kb)
            conn = _EvConn(self, sock, peer)
            with self._lock:
                # stopping-check and _conns.add are ATOMIC under the
                # lock (threaded-core parity): a connection accepted
                # during stop() must either be closed here or appear
                # in stop()'s snapshot — never slip between them and
                # leak an ESTABLISHED socket with no reader
                if self._stopping.is_set():
                    wire.close_hard(sock)
                    return
                self._conns.add(conn)
            metrics.add("net.accepts")
            metrics.gauge_add("net.server.connections", 1)
            conn.register()
            # the accept banner: generation + warm flag + capability
            # bits (CAP_TENANT advertises the tenant plane), the FIRST
            # frame on the connection (uncredited — it answers no
            # request); rides _enqueue so the net.frame failpoint can
            # tear it like any other frame
            caps = wire.CAP_TRACE | wire.CAP_OBS | wire.CAP_ELASTIC \
                | (wire.CAP_TENANT if self.tenancy else 0) \
                | (wire.CAP_DRAINING if self._draining else 0) \
                | (wire.CAP_PUSH if self.push is not None
                   and not self._draining else 0)
            hello = wire.encode_hello(self.generation, self.warm_restart,
                                      caps=caps)
            conn._enqueue(_BufItem([hello], credited=False,
                                   t0=time.perf_counter()), hello)

    def _forget(self, conn: _EvConn) -> None:
        with self._lock:
            self._conns.discard(conn)

    def notify_commit(self, job_id: str, map_id: str) -> None:
        """The MOFWriter commit seam: a map output just became
        fetchable — push it to every subscribed reduce connection
        (wire a writer with ``on_commit=server.notify_commit``). A
        no-op on a pull-only or draining server, so embedders can
        call it unconditionally."""
        if self.push is not None and not self._draining:
            self.push.notify_commit(job_id, map_id)

    def _stats_snapshot(self) -> dict:
        """The introspection provider: generation, bound port, loop
        health and the per-connection table (peer, in-flight depth,
        parked backlog, drain state). Lock-light reads of monotone
        fields — a racy glance is the contract of a live console."""
        with self._lock:
            conns = list(self._conns)
        loop = self._loop
        with self._marks_lock:
            nmarks = len(self._marks)
        snap = {
            "generation": self.generation,
            "warm_restart": self.warm_restart,
            "port": (self._listener.getsockname()[1]
                     if self._listener is not None else None),
            "credit_per_conn": self.credit,
            "zerocopy_mode": self.zc_mode,
            "loop": (loop.stats() if loop is not None
                     else {"alive": False}),
            "watermarks": nmarks,
            "connections": [
                {"peer": c.peer, "inflight": c.inflight,
                 "parked": len(c._parked), "credits": c._credits,
                 "tenant": c.tenant,
                 "draining": c.draining, "closed": c.closed}
                for c in conns],
        }
        if self.tenancy:
            # racy glance of loop-owned scheduler state (the live-
            # console contract); a mid-mutation dict walk degrades to
            # an error marker, never a broken MSG_STATS reply
            try:
                snap["tenancy"] = {"registry": self.registry.snapshot(),
                                   "scheduler": self._sched.stats()}
            except RuntimeError:  # udalint: disable=UDA006 - a racing
                snap["tenancy"] = {"racing": True}  # sweep moved the
                # dicts under the walk; the next poll answers
        return snap

    def _sendfile_refused_once(self) -> None:
        """First sendfile refusal (EINVAL-class: the fs/socket pairing
        will never splice): memoize it so the serve path stops planning
        sendfile — the one-shot pread fallback must not become a
        per-chunk loop-stalling disk read. Subsequent fd slices ride
        the mmap mechanism; files that cannot be mapped either drop
        zero-copy planning entirely (see _complete's last rung)."""
        if self._sendfile_refused:
            return
        self._sendfile_refused = True
        if self.zc_mode == "sendfile":
            self.zc_mode = "mmap"
            log.warn("net: sendfile refused by the fs/socket pairing; "
                     "switching the zero-copy serve mechanism to mmap")

    def announce_drain(self, store=None, job_id: Optional[str] = None):
        """Begin elastic departure (the symmetric half of mid-job join):
        flip the banner to CAP_DRAINING — every connection accepted
        from here on learns this supplier is leaving and demotes it in
        candidate ranking (already-connected peers keep their credits;
        in-flight serves complete normally) — and, when a StoreManager
        is attached, migrate the retained MOF partitions to the blob
        tier so the job can still fetch them AFTER this process exits
        (migrated, not reconstructed). Idempotent; returns the list of
        migration records (empty without a store). The caller follows
        with ``stop(drain=True)`` once its producers are quiesced."""
        first = not self._draining
        self._draining = True
        if first:
            metrics.add("elastic.drains")
            flightrec.record("elastic.drain", generation=self.generation)
            log.info(f"net: drain announced (generation "
                     f"{self.generation}); new banners carry "
                     f"CAP_DRAINING")
        moved = []
        if store is not None:
            moved = store.drain(job_id)
        return moved

    def stop(self, drain: bool = True) -> None:
        """Stop serving. ``drain=True`` (the default) completes what the
        engine already accepted: stop reading new requests everywhere,
        flush in-flight responses for up to ``uda.tpu.net.drain.s``,
        then close. ``drain=False`` tears connections down mid-stream
        (clients see TransportError — the killed-supplier shape the
        retry/penalty machinery must absorb)."""
        if self._loop is None:
            return
        self._stopping.set()
        if self.push is not None:
            self.push.stop()
        from uda_tpu.utils.stats import unregister_stats_provider
        unregister_stats_provider("net.server", self._stats_snapshot)
        if self.tenancy and self._sched is not None:
            from uda_tpu.tenant.sli import sli_book
            sli_book.detach(self._sched)  # only if still ours
        loop = self._loop
        ls, self._listener = self._listener, None
        if ls is not None:
            loop.call_soon(loop.unregister, ls)
            wire.close_hard(ls)
        with self._lock:
            conns = list(self._conns)
        if drain:
            for c in conns:
                loop.call_soon(c.begin_drain)
            deadline = time.monotonic() + self.drain_s
            while time.monotonic() < deadline:
                if all(c.drained() or c.closed for c in conns):
                    break
                time.sleep(0.01)
            # the graceful-stop handoff: everything the engine accepted
            # has flushed (or the drain window closed) — persist the
            # generation + watermarks so the NEXT start advertises a
            # warm generation+1 and clients keep their resumed offsets
            self._write_handoff()
        for c in conns:
            loop.call_soon(c.close)
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            if all(c.closed for c in conns):
                break
            time.sleep(0.005)
        loop.stop()
        self._loop = None
        # Deliberately NOT a ResourceLedger drain point: the engine
        # outlives the server (a warm bounce reuses it, and its pool
        # may still be running a delayed pread for a force-closed conn
        # — that pread's fd pin is live, not leaked). fd-pin quiescence
        # is asserted where it is a contract: DataEngine.stop (pool
        # drained, cache closed) and the bridge-EXIT full drain.

    def __enter__(self) -> "EvLoopShuffleServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# The event loop is THE server core: the legacy thread-per-connection
# baseline (PR 4) was deleted once BENCH_NET_r07.json recorded the
# second evloop-only point (last A/B: BENCH_NET_r06.json, 2.92x).
ShuffleServer = EvLoopShuffleServer
