"""ThreadedFetchClient: the LEGACY thread-per-host fetch client.

PR 4's original reduce-side core — one blocking reader thread per
supplier host — kept selectable behind ``uda.tpu.net.core=threaded``
as the measured baseline for ``scripts/net_bench.py`` and the
dual-core parametrization of ``tests/test_net.py``; scheduled for
deletion once the ``BENCH_NET_*`` trajectory has a second
event-loop-only data point. Do not grow features here — the live core
is ``net/client.py``.

The TCP stand-in for the reference's RDMAClient (reference
src/DataNet/RDMAClient.cc:498-527): ONE multiplexed connection per
supplier host, many fetches in flight on it, completions correlated
back to their requests by id — the socket analogue of work completions
matched to posted WQEs. An :class:`~uda_tpu.merger.segment.InputClient`,
so it plugs into Segment / MergeManager / HostRoutingClient unchanged.

Shape:

- lazy connect on first fetch; ONE connect attempt per ``start_fetch``
  — a failed connect completes the fetch with ``TransportError`` and
  the *Segment's* ``RetryPolicy`` (the existing
  ``mapred.rdma.fetch.*`` backoff/deadline machinery) paces the
  reconnect attempts, exactly as it paces every other transport fault
  (the reference's connect-retry-then-fail dance, RDMAClient.cc:
  215-356, already lives there);
- a correlation table ``req_id -> waiter`` under one lock; a reader
  thread (``uda-net-client-<host>``) dispatches DATA/ERR frames to
  their waiters out of order;
- a dead connection (EOF, torn frame, decode error) fails EVERY
  in-flight request with ``TransportError`` — each flows into its
  Segment's retry/penalty/fallback machinery independently — and the
  next ``start_fetch`` dials a fresh connection (a new epoch: frames
  from the old socket can never complete new requests);
- typed ERR frames re-raise the server-side error class (a supplier
  ``StorageError`` admission rejection stays a StorageError, so the
  reduce side's backoff semantics match the in-process path);
- ``estimate_partition_bytes`` rides the same connection (SIZE frames),
  giving the auto merge-approach policy real sizes across the wire.

Failpoints: ``net.connect`` fires per dial (error = connect refused,
delay = slow handshake); ``net.frame`` fires on every outbound request
frame (truncation desyncs the server's stream — a torn-request
disconnect).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Callable, Optional, Sequence

from uda_tpu.merger.segment import InputClient
from uda_tpu.mofserver.data_engine import ShuffleRequest
from uda_tpu.net import wire
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import TransportError
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.locks import TrackedLock
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["ThreadedFetchClient"]

log = get_logger()

_SIZE_PROBE_TIMEOUT_S = 30.0


class _Waiter:
    """One in-flight request's completion slot."""

    __slots__ = ("on_complete", "span", "t0")

    def __init__(self, on_complete: Callable, span, t0: float):
        self.on_complete = on_complete
        self.span = span
        self.t0 = t0


class ThreadedFetchClient(InputClient):
    """Multiplexed fetch client for one supplier host."""

    def __init__(self, host: str, port: Optional[int] = None,
                 config: Optional[Config] = None):
        cfg = config or Config()
        self.host = host
        self.port = int(port if port is not None
                        else cfg.get("uda.tpu.net.port"))
        self.connect_timeout_s = float(
            cfg.get("uda.tpu.net.connect.timeout.s"))
        self.sockbuf_kb = int(cfg.get("uda.tpu.net.sockbuf.kb"))
        # lockdep-tracked: PR 4's deadlock lived exactly here (reader
        # blocked in recv holding what close needed)
        self._lock = TrackedLock("net.client")    # table + conn state
        self._wlock = TrackedLock("net.client.write")  # write serial.
        self._sock: Optional[socket.socket] = None
        self._reader: Optional[threading.Thread] = None
        self._pending: dict[int, _Waiter] = {}
        self._next_id = 0
        self._epoch = 0
        self._stopped = False

    # -- connection management ----------------------------------------------

    def _ensure_connected(self) -> socket.socket:
        """The live socket, dialing a fresh connection when there is
        none. Raises TransportError on a failed dial — the caller turns
        that into a completion error (Segment retries drive the
        reconnect pacing)."""
        with self._lock:
            if self._stopped:
                raise TransportError(
                    f"ThreadedFetchClient({self.host}) is stopped")
            if self._sock is not None:
                return self._sock
            epoch = self._epoch + 1
        # dial OUTSIDE the lock: a slow handshake must not block the
        # reader thread's teardown of the previous connection
        failpoint("net.connect", key=f"{self.host}:{self.port}")
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_s)
        except OSError as e:
            metrics.add("net.connect.failures", host=self.host)
            raise TransportError(
                f"connect to supplier {self.host}:{self.port} failed: "
                f"{e}") from e
        sock.settimeout(None)
        wire.tune_socket(sock, self.sockbuf_kb)
        with self._lock:
            if self._stopped or self._sock is not None:
                # lost the dial race (or stopped underneath): keep the
                # winner's connection
                wire.close_hard(sock)
                if self._stopped:
                    raise TransportError(
                        f"ThreadedFetchClient({self.host}) is stopped")
                return self._sock
            self._sock = sock
            self._epoch = epoch
            self._reader = threading.Thread(
                target=self._read_loop, args=(sock, epoch), daemon=True,
                name=f"uda-net-client-{self.host}")
            reader = self._reader
        metrics.add("net.connects", host=self.host)
        metrics.gauge_add("net.client.connections", 1)
        reader.start()
        return sock

    def _drop_connection(self, sock: socket.socket, epoch: int,
                         cause: Exception) -> None:
        """Tear down one connection epoch and fail every request still
        in flight on it. Idempotent per epoch; a newer connection's
        table entries are untouched (requests registered after the
        reconnect belong to the new epoch by construction: the table is
        cleared under the same lock that swaps the socket)."""
        with self._lock:
            if self._epoch != epoch or self._sock is not sock:
                return  # an earlier caller already tore this epoch down
            self._sock = None
            self._reader = None
            orphans = list(self._pending.items())
            self._pending.clear()
        wire.close_hard(sock)
        metrics.gauge_add("net.client.connections", -1)
        metrics.add("net.disconnects", role="client")
        err = TransportError(
            f"connection to supplier {self.host}:{self.port} lost "
            f"({type(cause).__name__}: {cause}); "
            f"{len(orphans)} fetches in flight")
        for req_id, waiter in orphans:
            waiter.span.end(error="disconnect")
            try:
                waiter.on_complete(err)
            except Exception as e:  # noqa: BLE001 - one waiter's bug
                # must not starve the other orphans of their completion
                log.warn(f"net: completion callback for req {req_id} "
                         f"raised during disconnect: {e}")

    def _read_loop(self, sock: socket.socket, epoch: int) -> None:
        """Dispatch frames to waiters until the connection dies."""
        try:
            while True:
                frame = wire.recv_frame(sock)
                if frame is None:
                    raise TransportError("supplier closed the connection")
                msg_type, req_id, payload = frame
                metrics.add("net.bytes.in",
                            wire.HEADER.size + len(payload), role="client")
                if msg_type == wire.MSG_DATA:
                    result = wire.decode_result(payload)
                elif msg_type == wire.MSG_ERR:
                    result = wire.decode_error(payload)
                elif msg_type == wire.MSG_SIZE:
                    result = wire.decode_size(payload)
                else:
                    raise TransportError(
                        f"unexpected frame type {msg_type} on the "
                        f"client side")
                with self._lock:
                    waiter = self._pending.pop(req_id, None)
                if waiter is None:
                    # stale epoch / cancelled request: count and move on
                    metrics.add("net.frames.orphaned")
                    continue
                if msg_type != wire.MSG_SIZE:
                    metrics.observe("net.frame.latency_ms",
                                    (time.perf_counter() - waiter.t0) * 1e3,
                                    role="client")
                if isinstance(result, Exception):
                    waiter.span.end(error=type(result).__name__)
                else:
                    waiter.span.end()
                try:
                    waiter.on_complete(result)
                except Exception as e:  # noqa: BLE001 - one waiter's
                    # bug must not tear down the multiplexed connection
                    # under every OTHER in-flight fetch (same policy as
                    # the teardown paths)
                    log.warn(f"net: completion callback for req "
                             f"{req_id} raised: {e}")
        except (OSError, TransportError) as e:
            self._drop_connection(sock, epoch, e)
        except Exception as e:  # noqa: BLE001 - a decode/dispatch bug
            # must still fail the in-flight fetches, not strand them
            log.error(f"net: client reader died unexpectedly: {e}")
            self._drop_connection(sock, epoch, e)

    # -- InputClient --------------------------------------------------------

    def start_fetch(self, req: ShuffleRequest, on_complete) -> None:
        """Issue one fetch on the multiplexed connection. Completion
        (FetchResult, typed remote error, or disconnect TransportError)
        arrives on the reader thread — the same thread shape as the
        reference's completion-channel upcalls."""
        span = metrics.start_span(
            "net.fetch", host=self.host, map=req.map_id,
            reduce=req.reduce_id, offset=req.offset)
        try:
            sock = self._ensure_connected()
        except TransportError as e:
            span.end(error=type(e).__name__)
            on_complete(e)
            return
        with self._lock:
            died = self._sock is not sock
            if not died:
                self._next_id += 1
                req_id = self._next_id
                self._pending[req_id] = _Waiter(on_complete, span,
                                                time.perf_counter())
                epoch = self._epoch
        if died:
            # connection died between dial and registration; complete
            # OUTSIDE the lock — the callback may re-issue immediately
            span.end(error="disconnect")
            on_complete(TransportError(
                f"connection to {self.host}:{self.port} lost before "
                f"the fetch was issued"))
            return
        frame = wire.encode_request(req_id, req)
        if not self._send(sock, epoch, req_id, frame):
            return  # completion already delivered by the teardown path

    def _send(self, sock: socket.socket, epoch: int, req_id: int,
              frame: bytes) -> bool:
        """Write one frame; on failure tears the connection down (which
        fails req_id along with every other in-flight request). Returns
        False when the send failed."""
        try:
            out = failpoint("net.frame", data=frame,
                            key=f"client:{self.host}")
            torn = len(out) != len(frame)
            with self._wlock:
                sock.sendall(out)
            if torn:
                # we knowingly desynced the server's stream: finish the
                # damage deterministically instead of waiting for the
                # server's decoder to notice
                raise TransportError("request frame torn by failpoint")
        except Exception as e:  # noqa: BLE001
            self._drop_connection(sock, epoch, e)
            return False
        metrics.add("net.bytes.out", len(out), role="client")
        return True

    def estimate_partition_bytes(self, job_id: str, map_ids: Sequence[str],
                                 reduce_id: int) -> Optional[int]:
        """Partition size probe over the wire (SIZE frames). Best
        effort: any transport trouble or timeout returns None — the
        auto merge-approach policy then takes its bounded-memory
        default, it must never fail a task over a size probe."""
        try:
            sock = self._ensure_connected()
        except TransportError:
            return None
        box: list = [None]
        got = threading.Event()

        def on_size(result) -> None:
            box[0] = result
            got.set()

        span = metrics.start_span("net.size_probe", host=self.host,
                                  reduce=reduce_id, maps=len(map_ids))
        with self._lock:
            if self._sock is not sock:
                span.end(error="disconnect")
                return None
            self._next_id += 1
            req_id = self._next_id
            self._pending[req_id] = _Waiter(on_size, span,
                                            time.perf_counter())
            epoch = self._epoch
        frame = wire.encode_size_request(req_id, job_id, list(map_ids),
                                         reduce_id)
        if not self._send(sock, epoch, req_id, frame):
            return None
        if not got.wait(timeout=_SIZE_PROBE_TIMEOUT_S):
            with self._lock:
                self._pending.pop(req_id, None)  # late reply -> orphaned
            span.end(error="timeout")
            return None
        result = box[0]
        return None if isinstance(result, Exception) else result

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            sock, self._sock = self._sock, None
            self._reader = None
            orphans = list(self._pending.values())
            self._pending.clear()
        if sock is not None:
            wire.close_hard(sock)
            metrics.gauge_add("net.client.connections", -1)
        err = TransportError(
            f"ThreadedFetchClient({self.host}) stopped with "
            f"{len(orphans)} fetches in flight")
        for waiter in orphans:
            waiter.span.end(error="stopped")
            try:
                waiter.on_complete(err)
            except Exception as e:  # noqa: BLE001
                log.warn(f"net: completion callback raised during "
                         f"stop: {e}")
