"""The selector event-loop core of the shuffle data plane.

The socket analogue of the reference's completion-channel epoll loop
(reference src/DataNet/RDMAComm.cc ``cm_event_handler``/
``comp_event_handler``: one thread parked in epoll over the completion
channels, dispatching work completions to per-connection state): ONE
thread multiplexes every registered socket through
``selectors.DefaultSelector`` — non-blocking fds, per-connection state
machines, no thread pair per connection. This is what PR 4's
thread-per-connection stand-in could never scale to (ROADMAP item 3:
"fine at 64 suppliers, dead at 10k").

Threading contract (the whole module is built around it):

- **loop thread**: ``select()`` + registered handlers + ``call_soon``
  callbacks run here. Handlers must never block — that is udalint rule
  **UDA008**: every registered callback in ``uda_tpu/net/`` is marked
  with :func:`loop_callback`, and no ``recv``/``sendall``/unbounded
  ``.result()``/unbounded ``queue.get()`` may appear inside one (use
  ``recv_into``/``send``/``sendmsg`` on the non-blocking fd, or move
  the work to :meth:`EventLoop.dispatch`). The loop's own run loop is
  exempt — parking in ``select()`` is its job.
- **selector mutation** (register/modify/unregister) happens ON the
  loop thread only; other threads marshal through
  :meth:`EventLoop.call_soon` (deque append + wake byte — the
  self-pipe trick), because ``selectors`` objects are not safe against
  concurrent mutation from outside ``select()``.
- **dispatcher thread**: completion *upcalls* (a Segment's
  ``on_complete``, which may legitimately block on arena admission)
  run on a separate dispatcher thread via :meth:`dispatch`, so one
  slow consumer stalls other *completions* but never the data plane
  itself — the reference's completion-channel-thread shape, where the
  epoll loop hands WCs off rather than running reducer code inline.

Backpressure note: nothing here queues unboundedly on its own — the
server's per-connection credit cap pauses *read interest* when the
pipeline is full (TCP flow control pushes back on the peer, exactly
like the threaded core's blocking reader), and dispatcher depth is
bounded by the fetch windows of the clients feeding it.
"""

from __future__ import annotations

import queue
import selectors
import socket
import threading
from collections import deque
from typing import Callable, Optional

from uda_tpu.utils.locks import TrackedLock
from uda_tpu.utils.logging import get_logger

__all__ = ["EventLoop", "loop_callback", "shared_client_loop"]

log = get_logger()


def loop_callback(fn):
    """Marker for functions registered as event-loop callbacks (read/
    write handlers, ``call_soon`` targets). Purely declarative — the
    decorated function is returned unchanged — but the marker is a
    machine-checked contract: udalint's UDA008 walks every
    ``@loop_callback`` body in ``uda_tpu/net/`` and rejects blocking
    calls (``recv``/``sendall``/unbounded ``.result()``/unbounded
    ``queue.get()``) that would park the shared loop thread."""
    fn.__uda_loop_callback__ = True
    return fn


class EventLoop:
    """One selector thread + one completion-dispatch thread.

    Handlers are registered per socket as ``handler(mask)`` callables;
    ``call_soon(fn, *args)`` marshals work onto the loop thread from
    anywhere; ``dispatch(fn, *args)`` hands potentially-blocking
    completion upcalls to the dispatcher thread in FIFO order."""

    def __init__(self, name: str = "uda-net-loop"):
        self.name = name
        self._sel = selectors.DefaultSelector()
        self._pending: "deque[tuple[Callable, tuple]]" = deque()
        self._stopping = threading.Event()
        # SimpleQueue: the C-implemented put/get pair — the dispatcher
        # handoff sits on the completion path of every fetch, and the
        # Condition machinery of queue.Queue costs real syscalls on
        # emulated kernels
        self._dispatchq: "queue.SimpleQueue[Optional[tuple[Callable, tuple]]]" = \
            queue.SimpleQueue()
        # the wake pipe (self-pipe trick): call_soon from any thread
        # appends to the deque and sends one byte so a parked select()
        # returns immediately
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._wake_buf = bytearray(4096)  # reusable drain scratch
        self._sel.register(self._wake_r, selectors.EVENT_READ,
                           self._drain_wake)
        # sock -> handler for connections with interest mask 0 (read
        # paused for credit backpressure with nothing left to write)
        self._parked: dict = {}
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            daemon=True,
                                            name=f"{name}-upcall")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "EventLoop":
        self._thread.start()
        self._dispatcher.start()
        return self

    def alive(self) -> bool:
        return self._thread.is_alive() and not self._stopping.is_set()

    def on_loop_thread(self) -> bool:
        return threading.current_thread() is self._thread

    def stop(self) -> None:
        """Stop both threads and release the selector. Sockets still
        registered are NOT closed — their owners tear them down (the
        loop never owns connection lifecycle). Straggler work queued
        after the threads exit (a late engine completion's call_soon, a
        dispatched size probe) is drained INLINE here so accounting
        callbacks (credit gauges, slice releases) always run."""
        if self._stopping.is_set():
            return
        self._stopping.set()
        self._wake()
        self._thread.join(timeout=5.0)
        self._dispatchq.put(None)
        self._dispatcher.join(timeout=5.0)
        self._run_pending()
        while True:
            try:
                item = self._dispatchq.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            fn, args = item
            try:
                fn(*args)
            except Exception as e:  # noqa: BLE001 - teardown stragglers
                log.warn(f"net: straggler completion raised during loop "
                         f"stop: {type(e).__name__}: {e}")
        self._run_pending()
        try:
            self._sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()  # udalint: disable=UDA004 - the wake pipe is
                # a loop-internal socketpair, not a peer connection: no
                # reader blocks on it (the loop thread has exited) and
                # there is no peer to FIN
            except OSError:
                pass

    # -- cross-thread marshalling -------------------------------------------

    def call_soon(self, fn: Callable, *args) -> None:
        """Run ``fn(*args)`` on the loop thread at the next turn. Safe
        from any thread; deque.append is atomic, the wake byte is best
        effort (a full pipe means a wakeup is already pending)."""
        self._pending.append((fn, args))
        self._wake()

    def dispatch(self, fn: Callable, *args) -> None:
        """Run ``fn(*args)`` on the dispatcher thread (FIFO). For
        completion upcalls that may block — they must not run on the
        loop thread (UDA008)."""
        self._dispatchq.put((fn, args))

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\0")
        except (BlockingIOError, OSError):
            pass  # pipe full (wakeup already pending) or torn down

    @loop_callback
    def _drain_wake(self, mask: int) -> None:
        try:
            while self._wake_r.recv_into(self._wake_buf):
                pass
        except (BlockingIOError, OSError):
            pass

    # -- selector surface (loop thread only) --------------------------------

    def register(self, sock, events: int, handler: Callable) -> None:
        """Register ``handler(mask)`` for ``sock``. Loop thread only —
        marshal through call_soon from anywhere else."""
        self._sel.register(sock, events, handler)

    def set_events(self, sock, events: int) -> None:
        """Change the interest mask (loop thread only). ``events=0`` is
        expressed by modifying to neither flag — selectors require at
        least one, so 0 unregisters and a later set re-registers."""
        key = self._sel.get_key(sock)
        if events:
            if key.events != events:
                self._sel.modify(sock, events, key.data)
        else:
            self._sel.unregister(sock)
            self._parked[sock] = key.data

    def resume(self, sock, events: int) -> None:
        """Re-register a socket parked by ``set_events(sock, 0)``."""
        handler = self._parked.pop(sock, None)
        if handler is not None:
            self._sel.register(sock, events, handler)
        else:
            self.set_events(sock, events)

    def unregister(self, sock) -> None:
        self._parked.pop(sock, None)
        try:
            self._sel.unregister(sock)
        except KeyError:
            pass

    def registered(self, sock) -> bool:
        try:
            self._sel.get_key(sock)
            return True
        except KeyError:
            return sock in self._parked

    def stats(self) -> dict:
        """Introspection glance (the MSG_STATS conn-table provider):
        liveness plus queue depths. Racy reads by design — this is a
        console view, not a synchronization point; the selector map
        read is guarded because selectors are not safe against
        concurrent mutation (a torn read degrades to -1, never an
        exception on the poll path)."""
        try:
            registered = len(self._sel.get_map())
        except (OSError, RuntimeError):
            registered = -1
        return {"alive": self.alive(),
                "registered": registered,
                "parked": len(self._parked),
                "pending_callbacks": len(self._pending),
                "dispatch_depth": self._dispatchq.qsize()}

    # -- the loop -----------------------------------------------------------

    def _run(self) -> None:
        while not self._stopping.is_set():
            try:
                events = self._sel.select(timeout=0.25)
            except OSError:
                # fd closed under select (owner teardown race). The
                # pending queue MUST still drain: the queued unregister
                # is what removes the bad fd — skipping it busy-loops
                # non-epoll selectors (epoll auto-removes closed fds,
                # poll/select raise EBADF forever)
                self._run_pending()
                continue
            for key, mask in events:
                try:
                    key.data(mask)
                except Exception as e:  # noqa: BLE001 - a handler bug
                    # must not take down the loop under every OTHER
                    # connection; the broken connection's own teardown
                    # path is responsible for failing its requests
                    log.error(f"net: event handler died: "
                              f"{type(e).__name__}: {e}")
            self._run_pending()

    def _run_pending(self) -> None:
        # bounded by the deque length at entry: a callback that
        # re-schedules itself runs next turn, not forever in this one
        for _ in range(len(self._pending)):
            try:
                fn, args = self._pending.popleft()
            except IndexError:
                break
            try:
                fn(*args)
            except Exception as e:  # noqa: BLE001 - same survival policy
                log.error(f"net: call_soon callback died: "
                          f"{type(e).__name__}: {e}")

    def _dispatch_loop(self) -> None:
        while True:
            item = self._dispatchq.get()
            if item is None:
                return
            fn, args = item
            try:
                fn(*args)
            except Exception as e:  # noqa: BLE001 - one consumer's bug
                # must not starve every later completion of delivery
                log.warn(f"net: dispatched completion raised: "
                         f"{type(e).__name__}: {e}")


# -- the shared client loop ---------------------------------------------------

# One process-wide loop serves every RemoteFetchClient connection (the
# reference ran ONE completion-channel epoll thread for all QPs, not one
# per peer). Created lazily, daemon threads, never torn down mid-process
# — like an executor, its lifetime is the process's.
_shared: Optional[EventLoop] = None
_shared_lock = TrackedLock("net.loop")


def shared_client_loop() -> EventLoop:
    global _shared
    with _shared_lock:
        if _shared is None or not _shared.alive():
            _shared = EventLoop("uda-net-client-loop").start()
        return _shared
