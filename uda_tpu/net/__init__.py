"""The shuffle data plane (the DataNet/ layer of SURVEY §1): wire
framing, supplier-side socket server, reduce-side multiplexed fetch
client — the TCP stand-in for the reference's RDMAServer/RDMAClient
ibverbs plane. This is what turns the in-process library into a
deployable shuffle service: a MOFSupplier listens next to its
DataEngine (``uda.tpu.net.listen``) and reduce hosts dial it through
``HostRoutingClient``'s default socket factory (``uda.tpu.net.fetch``).
"""

from uda_tpu.net.client import RemoteFetchClient, fetch_remote_stats
from uda_tpu.net.server import ShuffleServer
from uda_tpu.net.wire import MAX_FRAME, WIRE_VERSION

__all__ = ["RemoteFetchClient", "ShuffleServer", "WIRE_VERSION",
           "MAX_FRAME", "fetch_remote_stats"]
