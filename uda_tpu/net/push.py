"""Push-based pipelined shuffle: the supplier-initiated MSG_PUSH plane.

The data plane was strictly pull: reducers discover finished MOFs, then
fetch — so merge work cannot start until the first fetch wave returns
and the map→shuffle→reduce phases serialize at that barrier. Exoshuffle
(arXiv:2203.05072) shows that push-vs-pull belongs to the *shuffle
library as a policy*: map tasks eagerly push partitions to reduce-side
staging as they materialize, and the three phases fully overlap
(Exoshuffle-CloudSort, arXiv:2301.03734, rides the same seam at
production sort scale). This module is that policy for uda_tpu, built
on seams the plane already owns:

- **Negotiation**: the HELLO banner advertises :data:`wire.CAP_PUSH`;
  a client that wants pushes subscribes a (job, reduce) with
  MSG_PUSH_SUB. No subscription, no pushes — a push-less client on a
  push server (or vice versa) degrades to pure pull byte-identically.
- **Supplier side** (:class:`PushScheduler`, owned by the
  ShuffleServer): ``MOFWriter`` commit notifications enqueue one push
  task per subscribed connection; a per-connection window of un-ACKed
  pushes (min of both peers' knobs — MSG_DATA's credit discipline,
  receiver-paced) gates chunk reads off the same DataEngine that
  serves fetches. A draining supplier (PR 18) stops initiating.
- **Reduce side** (:class:`PushStaging`, owned by the MergeManager):
  pushed chunks accumulate per map as the partition's contiguous
  raw-byte prefix — exactly the coordinates of a resumed fetch. The
  admission ladder decides per chunk: eager-accept in memory while
  under the MemoryBudget-derived cap, spill the prefix to a staging
  run file while under the staged cap, else PUSH_NACK(BUDGET) — the
  supplier marks that partition pull-only and the prefix already
  accepted stays usable, so refusal costs zero bytes.
- **Adoption**: when the merge's fetch wave constructs a Segment, it
  ``take()``s the staged prefix and arms it via ``Segment.
  ckpt_preload`` — pushed bytes land in the offset ledger *as if they
  were a resumed fetch*, so retry, speculation, k-of-n reconstruction,
  warm-restart and checkpoint/resume compose unchanged. The LAST
  staged chunk is always withheld: the pull path re-fetches the tail,
  staying the byte-identity oracle on every partition (and satisfying
  the engine's offset-past-EOF rejection).

``take()`` claims the map: later pushes for it get PUSH_NACK(CLAIMED),
which is the dedup against in-flight fetches.
"""

from __future__ import annotations

import os
import tempfile
from collections import OrderedDict, deque
from typing import Optional

from uda_tpu.utils.errors import UdaError
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.ifile import crack_partial
from uda_tpu.utils.locks import TrackedLock, race_instrument
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

log = get_logger("push")

# PUSH_NACK reason codes (the wire carries the int; names are for
# metrics labels and logs — branch on the CODE, never the name).
NACK_BUDGET = 1    # staging caps exhausted; prefix kept, pull the rest
NACK_UNKNOWN = 2   # no staging for (job, reduce) — e.g. unregistered
NACK_CLAIMED = 3   # a Segment already took this map (in-flight fetch)
NACK_DISABLED = 4  # push plane off on this peer
NACK_GAP = 5       # offset is not the contiguous next byte (dup
                   # supplier or reordered stream) — prefix kept

NACK_REASONS = {
    NACK_BUDGET: "budget",
    NACK_UNKNOWN: "unknown",
    NACK_CLAIMED: "claimed",
    NACK_DISABLED: "disabled",
    NACK_GAP: "gap",
}


def nack_reason_name(code: int) -> str:
    return NACK_REASONS.get(code, f"code{code}")


# -- reduce side -------------------------------------------------------------


class _MapStage:
    """One partition's staged contiguous prefix: raw on-disk bytes from
    offset 0, split between an in-memory bytearray (the eager tier) and
    an overflow run file (the spill tier, strictly after the memory
    bytes)."""

    __slots__ = ("mem", "spill_path", "spill_bytes", "chunk_lens",
                 "next_off", "raw_length", "complete", "claimed")

    def __init__(self):
        self.mem = bytearray()
        self.spill_path: Optional[str] = None
        self.spill_bytes = 0
        self.chunk_lens: list[int] = []
        self.next_off = 0
        self.raw_length: Optional[int] = None
        self.complete = False
        self.claimed = False

    @property
    def total(self) -> int:
        return len(self.mem) + self.spill_bytes


@race_instrument("_maps")
class PushStaging:
    """Reduce-side staging for one (job, reduce): the landing zone of
    MSG_PUSH chunks and the preload source of the merge's Segments.

    Thread contract: ``offer`` runs on transport dispatcher threads
    (one per connection is possible — multiple supplier hosts push
    concurrently), ``take``/``close`` on the merge manager's thread;
    one leaf lock serializes them.
    """

    def __init__(self, job_id: str, reduce_id: int, *, cfg,
                 budget=None):
        self.job_id = job_id
        self.reduce_id = int(reduce_id)
        eager_mb = float(cfg.get("uda.tpu.push.eager.mb"))
        staged_mb = float(cfg.get("uda.tpu.push.staged.mb"))
        if eager_mb > 0:
            self.eager_cap = int(eager_mb * (1 << 20))
        elif budget is not None:
            # auto: an eighth of the host read budget — pushes must
            # never crowd out the fetch pipeline's own admission
            self.eager_cap = max(1 << 20, budget.host_budget_bytes // 8)
        else:
            self.eager_cap = 8 << 20
        self.staged_cap = (int(staged_mb * (1 << 20)) if staged_mb > 0
                           else 4 * self.eager_cap)
        self.spill_ok = bool(cfg.get("uda.tpu.push.spill"))
        from uda_tpu.merger.streaming import spill_dirs
        self._spill_dir = spill_dirs(cfg)[0]
        self._lock = TrackedLock("push.staging")
        self._maps: "OrderedDict[str, _MapStage]" = OrderedDict()
        self._closed = False

    # -- admission ladder (one verdict per pushed chunk) --

    def offer(self, map_id: str, offset: int, raw_length: int,
              last: bool, data) -> int:
        """Admit one pushed chunk. Returns 0 (ACK) or a NACK reason
        code. The contiguous prefix accepted so far survives every
        refusal — a NACK converts the REMAINDER to ordinary pull."""
        n = len(data)
        with self._lock:
            if self._closed:
                return self._refused(NACK_UNKNOWN)
            st = self._maps.get(map_id)
            if st is None:
                st = self._maps[map_id] = _MapStage()
            if st.claimed:
                return self._refused(NACK_CLAIMED)
            if offset != st.next_off:
                return self._refused(NACK_GAP)
            try:
                failpoint("push.admit", key=f"{self.job_id}:{map_id}")
            except UdaError:
                return self._refused(NACK_BUDGET)
            total = sum(s.total for s in self._maps.values())
            if total + n > self.staged_cap:
                return self._refused(NACK_BUDGET)
            mem = sum(len(s.mem) for s in self._maps.values())
            if st.spill_path is None and mem + n <= self.eager_cap:
                st.mem += data
                tier = "eager"
            elif self.spill_ok:
                try:
                    self._spill(st, data)
                except OSError as e:
                    log.warn(f"push: staging spill failed ({e}); "
                             f"refusing chunk")
                    return self._refused(NACK_BUDGET)
                tier = "spill"
            else:
                return self._refused(NACK_BUDGET)
            st.chunk_lens.append(n)
            st.next_off = offset + n
            st.raw_length = int(raw_length)
            st.complete = bool(last)
            metrics.add("push.accepted", tier=tier)
            metrics.add("push.accepted.bytes", n)
            metrics.gauge_add("push.staged.bytes", n)  # udalint: disable=UDA101 - released by take()/close()
            return 0

    @staticmethod
    def _refused(reason: int) -> int:
        metrics.add("push.refused", reason=nack_reason_name(reason))
        return reason

    def _spill(self, st: _MapStage, data) -> None:
        """Append ``data`` to the map's staging run file (the spill
        tier keeps strict byte order after the memory prefix)."""
        if st.spill_path is None:
            fd, st.spill_path = tempfile.mkstemp(
                prefix=f"uda-push-{self.reduce_id}-", suffix=".stage",
                dir=self._spill_dir)
            os.close(fd)
        with open(st.spill_path, "ab") as f:
            f.write(data)
        st.spill_bytes += len(data)
        metrics.add("push.spilled.bytes", len(data))

    # -- adoption --

    def take(self, map_id: str) -> Optional[dict]:
        """Claim ``map_id`` and return ``Segment.ckpt_preload`` kwargs
        for its staged prefix, or None when nothing usable is staged.
        Claiming is unconditional — from here on pushes for this map
        are NACK_CLAIMED (the dedup against the now in-flight fetch).

        The last staged chunk is withheld so ``next_offset`` stays
        strictly inside the partition: the pull path always re-fetches
        a tail chunk, remaining the byte-identity oracle (and the
        engine's offset-past-EOF rejection is never tripped)."""
        with self._lock:
            st = self._maps.get(map_id)
            if st is None:
                st = self._maps[map_id] = _MapStage()
                st.claimed = True
                return None
            if st.claimed:
                return None
            st.claimed = True
            total = st.total
            if total:
                metrics.gauge_add("push.staged.bytes", -total)
            if not st.chunk_lens:
                return None
            drop = st.chunk_lens[-1]
            usable = total - drop
            if usable <= 0:
                self._free(st)
                return None
            data = bytes(st.mem)
            if st.spill_bytes:
                with open(st.spill_path, "rb") as f:
                    data += f.read()
            raw_length = st.raw_length
            self._free(st)
        data = data[:usable]
        try:
            batch, consumed, _ = crack_partial(data, expect_eof=False)
        except UdaError:
            metrics.add("push.invalidated")
            return None
        return dict(data=data, carry_len=len(data) - consumed,
                    next_offset=usable, raw_length=raw_length,
                    num_records=batch.num_records)

    @staticmethod
    def _free(st: _MapStage) -> None:
        """Lock held: drop a claimed map's staged bytes (the gauge was
        already settled by the claim)."""
        st.mem = bytearray()
        st.chunk_lens = []
        if st.spill_path is not None:
            try:
                os.unlink(st.spill_path)
            except OSError:
                pass
            st.spill_path = None
        st.spill_bytes = 0

    def staged_bytes(self) -> int:
        with self._lock:
            return sum(s.total for s in self._maps.values()
                       if not s.claimed)

    def close(self) -> None:
        """Discard everything unclaimed and settle the staged gauge
        (idempotent; the MergeManager calls this when the run ends)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for st in self._maps.values():
                if not st.claimed and st.total:
                    metrics.gauge_add("push.staged.bytes", -st.total)
                st.claimed = True
                self._free(st)
            self._maps.clear()


# -- supplier side -----------------------------------------------------------


class _PushTask:
    """One (subscription, map) pair being pushed: chunks go out
    sequentially (ONE outstanding chunk per task — ordering by
    construction; the window parallelizes across tasks)."""

    __slots__ = ("job_id", "map_id", "reduce_id", "offset", "inflight",
                 "dead")

    def __init__(self, job_id: str, map_id: str, reduce_id: int):
        self.job_id = job_id
        self.map_id = map_id
        self.reduce_id = reduce_id
        self.offset = 0
        self.inflight = False
        self.dead = False


class _ConnSub:
    """Per-connection push state: the subscriptions this peer asked
    for, the task queue feeding it and the un-ACKed window."""

    __slots__ = ("conn", "subs", "tasks", "window", "chunk", "on_air",
                 "pull_only")

    def __init__(self, conn, window: int, chunk: int):
        self.conn = conn
        self.subs: set = set()        # {(job_id, reduce_id)}
        self.tasks: deque = deque()
        self.window = window
        self.chunk = chunk
        self.on_air = 0
        self.pull_only: set = set()   # {(job_id, reduce_id, map_id)}


@race_instrument("_subs", "_commits", "_inflight")
class PushScheduler:
    """Supplier-side push pump, owned by the event-loop ShuffleServer.

    Entry points and their threads: ``subscribe``/``on_ack``/
    ``on_nack`` arrive from the loop thread (frame dispatch),
    ``notify_commit`` from whatever thread runs the MOFWriter,
    ``drop_conn`` from the loop (connection close), chunk completions
    from the engine's pool threads. One leaf lock guards the tables;
    the lock is NEVER held across an engine submit or a connection
    enqueue (both can run arbitrary downstream work)."""

    def __init__(self, server, engine, cfg):
        self.server = server
        self.engine = engine
        self.window = max(1, int(cfg.get("uda.tpu.push.window")))
        self.chunk = int(cfg.get("mapred.rdma.buf.size")) * 1024
        self._lock = TrackedLock("push.sched")
        self._subs: dict = {}        # id(conn) -> _ConnSub
        self._commits: dict = {}     # job_id -> OrderedDict[map_id]
        self._inflight: dict = {}    # push_id -> (_ConnSub, _PushTask)
        self._next_id = 1
        self._stopped = False

    # -- control-plane entry points --

    def subscribe(self, conn, job_id: str, reduce_id: int,
                  window: int, chunk: int) -> None:
        """MSG_PUSH_SUB: remember the subscription and catch up on
        maps that committed before it arrived."""
        metrics.add("push.subs")
        with self._lock:
            if self._stopped:
                return
            cs = self._subs.get(id(conn))
            if cs is None:
                cs = self._subs[id(conn)] = _ConnSub(
                    conn,
                    window=max(1, min(self.window, int(window) or 1)),
                    chunk=max(4096, min(self.chunk, int(chunk)
                                        or self.chunk)))
            key = (job_id, int(reduce_id))
            if key in cs.subs:
                return
            cs.subs.add(key)
            for map_id in self._commits.get(job_id, ()):
                cs.tasks.append(_PushTask(job_id, map_id,
                                          int(reduce_id)))
        self._pump(conn)

    def notify_commit(self, job_id: str, map_id: str) -> None:
        """A MOFWriter committed ``map_id``: fan one push task out to
        every subscribed connection (any thread)."""
        metrics.add("push.commits")
        conns = []
        with self._lock:
            if self._stopped:
                return
            self._commits.setdefault(job_id, OrderedDict())[map_id] = \
                None
            for cs in self._subs.values():
                for (job, reduce_id) in cs.subs:
                    if job == job_id:
                        cs.tasks.append(_PushTask(job_id, map_id,
                                                  reduce_id))
                        conns.append(cs.conn)
        for conn in conns:
            self._pump(conn)

    def on_ack(self, conn, push_id: int) -> None:
        metrics.add("push.acks")
        with self._lock:
            entry = self._inflight.pop(push_id, None)
            if entry is not None:
                self._settle_locked(entry[0])
        if entry is not None:
            self._pump(conn)

    def on_nack(self, conn, push_id: int, reason: int) -> None:
        """The receiver refused a chunk: the partition goes pull-only
        on this connection — its ACKed prefix stays valid over there,
        the pull path serves the remainder."""
        metrics.add("push.nacks", reason=nack_reason_name(reason))
        with self._lock:
            entry = self._inflight.pop(push_id, None)
            if entry is not None:
                cs, task = entry
                self._settle_locked(cs)
                task.dead = True
                cs.pull_only.add((task.job_id, task.reduce_id,
                                  task.map_id))
        if entry is not None:
            self._pump(conn)

    def drop_conn(self, conn) -> None:
        """Connection closed: settle its whole window (resledger — a
        dead peer must not strand push.on_air)."""
        with self._lock:
            cs = self._subs.pop(id(conn), None)
            if cs is None:
                return
            dead = [pid for pid, (owner, _t) in self._inflight.items()
                    if owner is cs]
            for pid in dead:
                del self._inflight[pid]
            if cs.on_air:
                metrics.gauge_add("push.on_air", -cs.on_air)
            cs.on_air = 0
            cs.tasks.clear()

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            for cs in self._subs.values():
                if cs.on_air:
                    metrics.gauge_add("push.on_air", -cs.on_air)
                cs.on_air = 0
                cs.tasks.clear()
            self._subs.clear()
            self._inflight.clear()

    @staticmethod
    def _settle_locked(cs: _ConnSub) -> None:
        if cs.on_air > 0:
            cs.on_air -= 1
            metrics.gauge_add("push.on_air", -1)

    # -- the pump --

    def _pump(self, conn) -> None:
        """Issue engine chunk reads for ``conn`` until its window is
        full. Lock discipline: plan under the lock, submit outside."""
        issues = []
        with self._lock:
            if self._stopped or self.server._draining:
                return
            cs = self._subs.get(id(conn))
            if cs is None:
                return
            while cs.on_air < cs.window:
                task = self._next_task_locked(cs)
                if task is None:
                    break
                push_id = self._next_id
                self._next_id += 1
                task.inflight = True
                cs.on_air += 1
                metrics.gauge_add("push.on_air", 1)  # udalint: disable=UDA101 - released on ACK/NACK/error/drop_conn
                self._inflight[push_id] = (cs, task)
                issues.append((push_id, cs, task, task.offset))
        from uda_tpu.mofserver.data_engine import ShuffleRequest
        for push_id, cs, task, offset in issues:
            req = ShuffleRequest(job_id=task.job_id, map_id=task.map_id,
                                 reduce_id=task.reduce_id, offset=offset,
                                 chunk_size=cs.chunk)
            try:
                fut = self.engine.submit(req)
            except Exception as e:  # noqa: BLE001 - sync rejection
                self._push_failed(push_id, e)
                continue
            fut.add_done_callback(
                lambda f, pid=push_id: self._chunk_done(pid, f))

    def _next_task_locked(self, cs: _ConnSub) -> Optional[_PushTask]:
        while cs.tasks and cs.tasks[0].dead:
            cs.tasks.popleft()
        for task in cs.tasks:
            if task.dead or task.inflight:
                continue
            key = (task.job_id, task.reduce_id, task.map_id)
            if key in cs.pull_only:
                task.dead = True
                continue
            return task
        return None

    def _chunk_done(self, push_id: int, fut) -> None:
        """Engine completion (pool thread): frame the chunk, run the
        net.push failpoint, hand the frame to the connection's
        outbound queue — the same inline-write path DATA rides."""
        try:
            res = fut.result()
        except Exception as e:  # noqa: BLE001 - missing MOF, stopped
            # engine, injected fault: this partition goes pull-only
            self._push_failed(push_id, e)
            return
        with self._lock:
            entry = self._inflight.get(push_id)
            if entry is None:  # conn dropped while the read ran
                return
            cs, task = entry
            conn = cs.conn
        from uda_tpu.net import wire
        frame = wire.encode_push(
            push_id, job_id=task.job_id, map_id=task.map_id,
            reduce_id=task.reduce_id, offset=res.offset,
            raw_length=res.raw_length, last=res.last, data=res.data)
        try:
            out = failpoint("net.push", data=frame,
                            key=getattr(conn, "peer", ""))
        except Exception as e:  # noqa: BLE001 - injected push failure
            self._push_failed(push_id, e)
            return
        torn = len(out) != len(frame)
        with self._lock:
            if self._inflight.get(push_id) is None:
                return
            task.inflight = False
            if torn or res.last:
                # last chunk SENT (or the stream is about to tear):
                # the task is done; the window slot stays charged
                # until the ACK comes back
                task.dead = True
            else:
                task.offset = res.offset + len(res.data)
        metrics.add("push.chunks")
        metrics.add("push.bytes", len(res.data))
        conn.push_frame(out, close_after=torn)
        if not torn:
            self._pump(conn)

    def _push_failed(self, push_id: int, err: Exception) -> None:
        metrics.add("push.errors")
        with self._lock:
            entry = self._inflight.pop(push_id, None)
            if entry is None:
                return
            cs, task = entry
            task.inflight = False
            task.dead = True
            cs.pull_only.add((task.job_id, task.reduce_id,
                              task.map_id))
            self._settle_locked(cs)
            conn = cs.conn
        log.debug(f"push: {task.job_id}/{task.map_id} -> pull-only "
                  f"({err})")
        self._pump(conn)
