"""Wire framing of the shuffle data plane.

Binary encoding of the existing ``ShuffleRequest``/``FetchResult``
dataclasses — the socket stand-in for the reference's ibverbs message
pair: ``shuffle_req_t`` (jobid, map, reduceID, map_offset, chunk_size,
reference src/MOFServer/IndexInfo.h:64-77) and the RDMA ACK string
``"rawLen:partLen:sentSize:mofOffset:path"`` (reference
src/DataNet/RDMAServer.cc:597-607). Where the reference rode these on
pre-established QPs, here every message is one length-prefixed frame on
a TCP stream:

    +-------+---------+------+------------+-------------+---------+
    | magic | version | type | request id | payload len | payload |
    | 2 B   | 1 B     | 1 B  | 8 B        | 4 B         | ...     |
    +-------+---------+------+------------+-------------+---------+

(network byte order throughout). The request id is the multiplexing
correlation key: a client may have many requests in flight on one
connection and the server completes them out of order, exactly like
RDMA work completions.

Frame types::

    REQ        one chunk fetch            (ShuffleRequest)
    DATA       one chunk reply            (FetchResult; the ACK fields)
    ERR        typed failure for one req  (error kind + message)
    SIZE_REQ   partition size probe       (job, reduce, map ids)
    SIZE       size reply                 (total bytes, -1 = unknown)
    HELLO      accept banner              (server generation + warm flag +
                                           capability bits; the FIRST
                                           frame on every accepted
                                           connection — a warm-restarted
                                           supplier advertises
                                           generation+1 so clients know
                                           resumed offsets are
                                           continuous)
    STATS      introspection snapshot req (empty payload; uncredited,
                                           riding the HELLO-banner
                                           precedent — it answers no
                                           fetch and must not compete
                                           with data for credits)
    STATS_REPLY                           (UTF-8 JSON: the remote
                                           process's live counters,
                                           gauges, percentiles,
                                           ResourceLedger obligations
                                           and conn table —
                                           utils/stats.py
                                           introspection_snapshot)
    JOB        tenant handshake           (bind this connection to a
                                           (tenant, job, epoch) in the
                                           daemon's TenantRegistry —
                                           register/heartbeat/retire,
                                           HMAC-authenticated;
                                           uncredited like HELLO; sent
                                           to CAP_TENANT peers before
                                           a job's first REQ)
    JOB_OK     registration granted       (echoes the epoch; refusals
                                           are typed TenantError ERR
                                           frames on the same req id)
    PUSH       supplier-initiated chunk   (one partition chunk pushed
                                           from the supplier's commit
                                           point into reduce-side
                                           staging; req id is a
                                           server-minted push id the
                                           receiver echoes in PUSH_ACK/
                                           PUSH_NACK — sent ONLY on
                                           connections that subscribed
                                           via PUSH_SUB, so a push-less
                                           client never sees one)
    PUSH_SUB   push subscription          (client -> server: push this
                                           (job, reduce)'s partitions
                                           as they commit; carries the
                                           receiver's window and chunk
                                           preferences. Send only to
                                           CAP_PUSH peers)
    PUSH_ACK   push accepted              (empty payload; the push id
                                           correlates — releases one
                                           slot of the supplier's push
                                           window, the DATA credit
                                           discipline mirrored)
    PUSH_NACK  push refused               (reason code; the supplier
                                           marks the partition
                                           pull-only and the bytes
                                           already accepted stay
                                           usable as a resume prefix —
                                           over-budget/unknown pushes
                                           convert to ordinary pull
                                           with no bytes lost)

**Wire trace context** (versioned by LENGTH, the v2-UDIX back-compat
discipline): REQ and SIZE_REQ payloads may carry an optional trailing
``(trace_id, parent_span_id)`` pair (two u64s). An old decoder never
sees it — new clients append the block only to peers whose HELLO
banner advertises :data:`CAP_TRACE` — and a new decoder accepts both
shapes (exactly-zero or exactly-16 trailing bytes). The supplier
adopts the pair as the parent of its ``net.serve`` span, so
supplier-side serve/pread work lands in the reduce-side fetch span's
tree and ``scripts/trace_merge.py`` can stitch the processes' span
files into one trace.

Decoding is STRICT: a bad magic, an unknown version, an out-of-range
type, a length over :data:`MAX_FRAME`, a short buffer or trailing
garbage all raise :class:`TransportError` — the receiving side treats
any of them as a broken connection (the stream has lost frame sync;
there is no resynchronization, like a torn RDMA connection there is
only reconnect). One deliberate soft spot: an in-range but UNKNOWN
frame type decodes fine at the header layer and is answered by the
server with a typed ``ERR`` frame instead of a teardown — a newer peer
probing an optional message (MSG_STATS-style) must get a clean refusal,
not a disconnect. ``ERR`` payloads carry the error's class name so the
reduce side re-raises the TYPED error (a supplier-side
``StorageError`` admission rejection must look like a StorageError to
the Segment retry machinery, not like a generic transport fault).
"""

from __future__ import annotations

import socket as _socket
import struct
from typing import Optional, Sequence

from uda_tpu.mofserver.data_engine import FetchResult, ShuffleRequest
from uda_tpu.utils.errors import (CompressionError, ConfigError, MergeError,
                                  ProtocolError, StorageError, StoreError,
                                  TenantError, TransportError, UdaError)

__all__ = ["MAGIC", "WIRE_VERSION", "MAX_FRAME", "HEADER", "WIRE_CODECS",
           "MSG_REQ", "MSG_DATA", "MSG_ERR", "MSG_SIZE_REQ", "MSG_SIZE",
           "MSG_HELLO", "MSG_STATS", "MSG_STATS_REPLY",
           "MSG_JOB", "MSG_JOB_OK",
           "MSG_PUSH", "MSG_PUSH_SUB", "MSG_PUSH_ACK", "MSG_PUSH_NACK",
           "CAP_TRACE", "CAP_TENANT", "CAP_OBS",
           "CAP_ELASTIC", "CAP_DRAINING", "CAP_PUSH",
           "encode_push", "decode_push_take",
           "encode_push_sub", "decode_push_sub",
           "encode_push_ack", "encode_push_nack", "decode_push_nack",
           "STATS_SEC_TS", "STATS_SEC_SLI", "STATS_SEC_ANOMALY",
           "STATS_SEC_ALL", "decode_stats_request",
           "encode_job", "decode_job", "encode_job_ok", "decode_job_ok",
           "encode_request", "decode_request", "decode_request_ex",
           "encode_result",
           "encode_result_head", "decode_result", "decode_result_take",
           "encode_error", "decode_error", "encode_size_request",
           "decode_size_request", "decode_size_request_ex",
           "encode_size", "decode_size",
           "encode_hello", "decode_hello", "decode_hello_ex",
           "encode_stats_request", "encode_stats_reply",
           "decode_stats_reply",
           "encode_frame", "decode_header", "recv_frame", "close_hard",
           "tune_socket"]

MAGIC = b"UD"
WIRE_VERSION = 1
# Frames above this are rejected before allocation: a desynced stream
# read as a length field must not turn into a multi-GB recv buffer.
MAX_FRAME = (1 << 30) + 4096

HEADER = struct.Struct("!2sBBQI")  # magic, version, type, req id, len

MSG_REQ = 1
MSG_DATA = 2
MSG_ERR = 3
MSG_SIZE_REQ = 4
MSG_SIZE = 5
MSG_HELLO = 6
MSG_STATS = 7        # introspection snapshot request (empty payload)
MSG_STATS_REPLY = 8  # introspection snapshot (UTF-8 JSON payload)
MSG_JOB = 9          # tenant handshake: bind this connection to
                     # (tenant, job, epoch) in the daemon's registry
                     # (register / heartbeat / retire; authenticated by
                     # an HMAC token when the server carries a secret).
                     # Uncredited like HELLO — registration must never
                     # compete with data for credits.
MSG_JOB_OK = 10      # MSG_JOB accepted: echoes the granted epoch.
                     # Refusals ride a typed ERR (TenantError) on the
                     # MSG_JOB's req id instead.
MSG_PUSH = 11        # supplier-initiated partition chunk (server ->
                     # client). Sent ONLY on connections that
                     # subscribed with MSG_PUSH_SUB, so push-less
                     # clients never see one. The req id is a
                     # server-minted push id echoed by PUSH_ACK/NACK.
MSG_PUSH_SUB = 12    # client -> server: push me (job, reduce) chunks
                     # as maps commit. Uncredited like MSG_JOB. Send
                     # only to CAP_PUSH peers — an older server answers
                     # a typed ERR (forward-compat contract) and the
                     # client just stays pull-only.
MSG_PUSH_ACK = 13    # push accepted into reduce-side staging (empty
                     # payload). Releases one slot of the supplier's
                     # push window — MSG_DATA's credit discipline,
                     # receiver-paced.
MSG_PUSH_NACK = 14   # push refused: reason code. The supplier marks
                     # the partition pull-only on this connection; the
                     # contiguous prefix already ACKed stays usable as
                     # a resume preload, so refusal costs zero bytes.

_TYPES = (MSG_REQ, MSG_DATA, MSG_ERR, MSG_SIZE_REQ, MSG_SIZE, MSG_HELLO,
          MSG_STATS, MSG_STATS_REPLY, MSG_JOB, MSG_JOB_OK,
          MSG_PUSH, MSG_PUSH_SUB, MSG_PUSH_ACK, MSG_PUSH_NACK)

# The frame-family exhaustiveness table (udalint UDA204): every MSG_*
# constant maps to its (encoder, strict decoder) by NAME, and the lint
# verifies the named functions exist here and that a dispatch arm in
# net/server.py or net/client.py handles the type. A decoder of None is
# legal ONLY for header-only frames and must carry its reason on the
# same line — this is how the next PR-19-style frame family is forced
# to land fully wired (encoder + decoder + dispatch) or not at all.
WIRE_CODECS = {
    MSG_REQ: ("encode_request", "decode_request"),
    MSG_DATA: ("encode_result", "decode_result"),
    MSG_ERR: ("encode_error", "decode_error"),
    MSG_SIZE_REQ: ("encode_size_request", "decode_size_request"),
    MSG_SIZE: ("encode_size", "decode_size"),
    MSG_HELLO: ("encode_hello", "decode_hello"),
    MSG_STATS: ("encode_stats_request", "decode_stats_request"),
    MSG_STATS_REPLY: ("encode_stats_reply", "decode_stats_reply"),
    MSG_JOB: ("encode_job", "decode_job"),
    MSG_JOB_OK: ("encode_job_ok", "decode_job_ok"),
    MSG_PUSH: ("encode_push", "decode_push_take"),
    MSG_PUSH_SUB: ("encode_push_sub", "decode_push_sub"),
    MSG_PUSH_ACK: ("encode_push_ack",
                   None),  # header-only: the echoed push id IS the ack
    MSG_PUSH_NACK: ("encode_push_nack", "decode_push_nack"),
}
# the header accepts any type in this reserved range; semantically
# unknown ones get a typed ERR from the server, never a teardown (the
# forward-compat contract — see the module docstring). Anything past
# the range is a desynced stream, same as a bad magic.
_MAX_TYPE = 32

_REQ = struct.Struct("!IQI")      # reduce_id, offset, chunk_size
_DATA = struct.Struct("!QQQB")    # raw_length, part_length, offset, flags
_CRC = struct.Struct("!I")
_SIZE_REQ = struct.Struct("!II")  # reduce_id, num maps
_SIZE = struct.Struct("!q")       # total bytes, -1 = unknown
_HELLO = struct.Struct("!IB")     # server generation, flags
_TRACE = struct.Struct("!QQ")     # trace_id, parent_span_id (optional
                                  # REQ/SIZE_REQ tail — see docstring)
_JOB = struct.Struct("!IBH")      # epoch, flags (retire bit), weight
_JOB_OK = struct.Struct("!I")     # granted epoch echo
_PUSH = struct.Struct("!IQQB")    # reduce_id, offset, raw_length, flags
_PUSH_SUB = struct.Struct("!III")  # reduce_id, window, chunk bytes
_PUSH_NACK = struct.Struct("!B")  # reason code (uda_tpu.net.push)

_JOB_RETIRE = 0x01  # MSG_JOB flags: this is a retire, not a register

_HELLO_WARM = 0x01  # the generation continues a persisted handoff
# HELLO capability bits (old decoders mask only the bits they know —
# decode_hello tests _HELLO_WARM and ignores the rest, so advertising
# new bits is free):
CAP_TRACE = 0x02    # peer decodes the trace-context REQ/SIZE_REQ tail
                    # and serves MSG_STATS (the observability plane)
CAP_TENANT = 0x04   # peer runs the multi-tenant service plane: it
                    # accepts MSG_JOB registration and validates REQs
                    # against its job/epoch registry (uda_tpu/tenant/).
                    # Clients without a tenant binding ignore it; old
                    # clients never see it (decode_hello masks only
                    # the warm bit)
CAP_OBS = 0x08      # peer runs the live-telemetry plane (ISSUE 17):
                    # its MSG_STATS decoder accepts the optional
                    # trailing window/sections block (the _take_trace
                    # length-versioning discipline) and its replies can
                    # carry time-series rollup windows, per-tenant SLI
                    # blocks and the active-anomaly table. Send the
                    # tail ONLY to CAP_OBS peers — an older server
                    # treats trailing bytes as a torn frame
CAP_ELASTIC = 0x10  # peer participates in elastic membership (ISSUE
                    # 18): it may register mid-job (reduce sides fold
                    # a fresh CAP_ELASTIC banner into the candidate
                    # ring via HostRoutingClient.notify_join) and
                    # understands the symmetric drain announcement
CAP_DRAINING = 0x20  # peer is LEAVING: it has announced drain, is
                     # migrating its retained MOFs to the blob tier
                     # (StoreManager.drain) and will refuse no inflight
                     # work but should receive no NEW placements; the
                     # reduce side demotes it in candidate ranking
CAP_PUSH = 0x40     # peer runs the push plane (ISSUE 19): it accepts
                    # MSG_PUSH_SUB subscriptions and will push
                    # committed partitions as MSG_PUSH frames. A
                    # draining supplier stops advertising it so new
                    # conns stay pull-only; clients subscribe ONLY
                    # when the banner carries this bit.

# the optional MSG_STATS request tail: requested rollup-window seconds
# + a section bitmask. Exactly 0 bytes (the PR 11 shape: plain
# snapshot) or exactly _STATS_OPT.size bytes may follow the (empty)
# base payload — the length IS the version.
_STATS_OPT = struct.Struct("!II")
STATS_SEC_TS = 0x01       # timeseries: the rollup-ring window
STATS_SEC_SLI = 0x02      # sli: the per-tenant SLI/SLO book
STATS_SEC_ANOMALY = 0x04  # anomalies: the active-anomaly table
STATS_SEC_ALL = STATS_SEC_TS | STATS_SEC_SLI | STATS_SEC_ANOMALY

_FLAG_LAST = 0x01
_FLAG_CRC = 0x02

# ERR frames carry the error's class name; the decoder re-raises the
# same typed error on the reduce side so recovery paths (Segment retry,
# supplier-admission backoff) see realistic types across the wire.
_ERROR_CLASSES = {cls.__name__: cls for cls in
                  (UdaError, ConfigError, ProtocolError, TransportError,
                   MergeError, StorageError, StoreError, CompressionError,
                   TenantError)}


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        raise ProtocolError(f"string field too long for the wire "
                            f"({len(b)} B > 65535)")
    return struct.pack("!H", len(b)) + b


def _unpack_str(payload, off: int, what: str) -> tuple[str, int]:
    """Buffer-agnostic (bytes OR memoryview: the event-loop cores decode
    straight out of their receive buffers without materializing the
    payload as bytes first)."""
    if off + 2 > len(payload):
        raise TransportError(f"truncated frame: no length for {what}")
    (n,) = struct.unpack_from("!H", payload, off)
    off += 2
    if off + n > len(payload):
        raise TransportError(f"truncated frame: {what} needs {n} B, "
                             f"{len(payload) - off} left")
    return bytes(payload[off:off + n]).decode("utf-8"), off + n


def _done(payload: bytes, off: int, what: str) -> None:
    if off != len(payload):
        raise TransportError(f"malformed {what} frame: "
                             f"{len(payload) - off} trailing bytes")


# -- encode ------------------------------------------------------------------

def encode_frame(msg_type: int, req_id: int, payload: bytes) -> bytes:
    return HEADER.pack(MAGIC, WIRE_VERSION, msg_type, req_id,
                       len(payload)) + payload


def encode_request(req_id: int, req: ShuffleRequest,
                   trace: Optional[tuple] = None) -> bytes:
    """``trace`` is the optional ``(trace_id, parent_span_id)`` pair —
    append it ONLY to peers whose HELLO advertised :data:`CAP_TRACE`
    (an old decoder treats trailing bytes as a torn frame)."""
    payload = (_REQ.pack(req.reduce_id, req.offset, req.chunk_size)
               + _pack_str(req.job_id) + _pack_str(req.map_id))
    if trace is not None:
        payload += _TRACE.pack(trace[0], trace[1])
    return encode_frame(MSG_REQ, req_id, payload)


def encode_result_head(req_id: int, *, raw_length: int, part_length: int,
                       offset: int, last: bool, path: str,
                       crc: Optional[int] = None, data_len: int) -> bytes:
    """Everything of a DATA frame BEFORE the chunk bytes — frame header
    plus the ACK fields — with the payload length accounting for
    ``data_len`` chunk bytes that the caller sends separately (the
    buffer-donating encode: ``sendmsg([head, chunk])`` scatter-gather,
    or ``head`` + ``os.sendfile`` when the chunk is fd-backed). The
    chunk bytes never pass through an encode-side concatenation."""
    flags = (_FLAG_LAST if last else 0) | \
            (_FLAG_CRC if crc is not None else 0)
    meta = _DATA.pack(raw_length, part_length, offset, flags)
    if crc is not None:
        meta += _CRC.pack(crc & 0xFFFFFFFF)
    meta += _pack_str(path)
    return HEADER.pack(MAGIC, WIRE_VERSION, MSG_DATA, req_id,
                       len(meta) + data_len) + meta


def encode_result(req_id: int, res: FetchResult) -> bytes:
    return encode_result_head(
        req_id, raw_length=res.raw_length, part_length=res.part_length,
        offset=res.offset, last=res.last, path=res.path, crc=res.crc,
        data_len=len(res.data)) + res.data


def encode_error(req_id: int, exc: BaseException) -> bytes:
    """Total by construction: the message is diagnostics, so an
    over-long one is truncated to fit the u16 string field rather than
    failing the encode — an ERR frame that cannot be encoded would
    strand the request's credit on the server."""
    message = str(exc)
    if len(message.encode("utf-8")) > 0xFFF0:
        message = message.encode("utf-8")[:0xFFF0].decode("utf-8",
                                                          "ignore")
    payload = _pack_str(type(exc).__name__[:256]) + _pack_str(message)
    return encode_frame(MSG_ERR, req_id, payload)


def encode_size_request(req_id: int, job_id: str, map_ids: Sequence[str],
                        reduce_id: int,
                        trace: Optional[tuple] = None) -> bytes:
    payload = b"".join([_SIZE_REQ.pack(reduce_id, len(map_ids)),
                        _pack_str(job_id),
                        *(_pack_str(mid) for mid in map_ids)])
    if trace is not None:
        payload += _TRACE.pack(trace[0], trace[1])
    return encode_frame(MSG_SIZE_REQ, req_id, payload)


def encode_size(req_id: int, total: Optional[int]) -> bytes:
    return encode_frame(MSG_SIZE, req_id,
                        _SIZE.pack(-1 if total is None else total))


def encode_hello(generation: int, warm: bool,
                 caps: int = CAP_TRACE) -> bytes:
    """The accept banner (req id 0 — it correlates with nothing).
    ``caps`` bits advertise optional capabilities (trace-context
    frames, MSG_STATS); decoders from before a bit existed ignore
    it."""
    flags = (_HELLO_WARM if warm else 0) | (caps & 0xFE)
    return encode_frame(MSG_HELLO, 0,
                        _HELLO.pack(generation & 0xFFFFFFFF, flags))


def decode_hello(payload) -> tuple[int, bool]:
    """-> (server generation, warm). Ignores capability bits it does
    not know — the forward-compat contract that lets new servers
    advertise CAP_TRACE to old clients."""
    generation, warm, _ = decode_hello_ex(payload)
    return generation, warm


def decode_hello_ex(payload) -> tuple[int, bool, int]:
    """-> (server generation, warm, capability bits)."""
    if len(payload) != _HELLO.size:
        raise TransportError(f"malformed HELLO frame ({len(payload)} B)")
    generation, flags = _HELLO.unpack(payload)
    return generation, bool(flags & _HELLO_WARM), flags & 0xFE


def encode_job(req_id: int, tenant_id: str, job_id: str, epoch: int,
               weight: int = 1, token: str = "",
               retire: bool = False) -> bytes:
    """MSG_JOB: bind the connection to (tenant, job, epoch) in the
    daemon's registry. ``token`` is the HMAC authentication string
    (:func:`uda_tpu.tenant.registry.sign_job`; empty when the server
    carries no secret); ``retire`` flips the frame from register/
    heartbeat to the job's retirement. Send only to peers whose HELLO
    advertised :data:`CAP_TENANT` — an older server answers a typed
    ProtocolError ERR, which is a clean refusal but a wasted frame."""
    flags = _JOB_RETIRE if retire else 0
    payload = (_JOB.pack(int(epoch) & 0xFFFFFFFF, flags,
                         max(1, int(weight)) & 0xFFFF)
               + _pack_str(tenant_id) + _pack_str(job_id)
               + _pack_str(token))
    return encode_frame(MSG_JOB, req_id, payload)


def decode_job(payload) -> tuple:
    """-> (tenant_id, job_id, epoch, weight, token, retire)."""
    if len(payload) < _JOB.size:
        raise TransportError(f"truncated JOB frame ({len(payload)} B)")
    epoch, flags, weight = _JOB.unpack_from(payload, 0)
    tenant_id, off = _unpack_str(payload, _JOB.size, "tenant id")
    job_id, off = _unpack_str(payload, off, "job id")
    token, off = _unpack_str(payload, off, "token")
    _done(payload, off, "JOB")
    return (tenant_id, job_id, epoch, weight, token,
            bool(flags & _JOB_RETIRE))


def encode_job_ok(req_id: int, epoch: int) -> bytes:
    """MSG_JOB accepted: the granted epoch, echoed (refusals are typed
    ERR frames on the same req id — TenantError for auth/stale-epoch/
    retired, so the client re-raises the exact registry error)."""
    return encode_frame(MSG_JOB_OK, req_id,
                        _JOB_OK.pack(int(epoch) & 0xFFFFFFFF))


def decode_job_ok(payload) -> int:
    if len(payload) != _JOB_OK.size:
        raise TransportError(f"malformed JOB_OK frame ({len(payload)} B)")
    return _JOB_OK.unpack(bytes(payload))[0]


def encode_stats_request(req_id: int, window_s: Optional[int] = None,
                         sections: int = STATS_SEC_ALL) -> bytes:
    """MSG_STATS: snapshot a remote process's live telemetry. Empty
    payload; uncredited on the server (the HELLO precedent) so an
    introspection poll can never be starved by a full data pipeline.

    ``window_s`` asks a :data:`CAP_OBS` peer to append the requested
    observability ``sections`` (time-series rollups over the trailing
    ``window_s`` seconds, per-tenant SLI blocks, active anomalies) —
    the optional tail rides the same exactly-0-or-exactly-N
    length-versioning as the trace context. Append it ONLY to CAP_OBS
    peers."""
    payload = b""
    if window_s is not None:
        payload = _STATS_OPT.pack(max(0, int(window_s)) & 0xFFFFFFFF,
                                  sections & 0xFFFFFFFF)
    return encode_frame(MSG_STATS, req_id, payload)


def decode_stats_request(payload) -> Optional[tuple]:
    """-> ``(window_s, sections)`` when the CAP_OBS tail is present,
    None for the PR 11 empty-payload shape. Anything else is a torn
    frame (the _take_trace discipline)."""
    if len(payload) == 0:
        return None
    if len(payload) == _STATS_OPT.size:
        return _STATS_OPT.unpack(bytes(payload))
    raise TransportError(f"malformed STATS frame: {len(payload)} "
                         f"trailing bytes")


def encode_stats_reply(req_id: int, snapshot: dict) -> bytes:
    """The introspection snapshot as UTF-8 JSON (the shape is
    ``uda_tpu.utils.stats.introspection_snapshot``)."""
    import json

    return encode_frame(MSG_STATS_REPLY, req_id,
                        json.dumps(snapshot, default=repr).encode("utf-8"))


def decode_stats_reply(payload) -> dict:
    import json

    try:
        return json.loads(bytes(payload).decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise TransportError(f"malformed STATS_REPLY frame: {e}") from e


def encode_push(push_id: int, *, job_id: str, map_id: str, reduce_id: int,
                offset: int, raw_length: int, last: bool,
                data: bytes) -> bytes:
    """MSG_PUSH: one supplier-initiated partition chunk. ``offset`` is
    the chunk's position in the partition's raw on-disk byte stream and
    ``raw_length`` its total — the same coordinates a resumed fetch
    would use, which is what lets the receiver ledger pushed bytes as
    if they were fetched. ``last`` marks the partition's final chunk.

    ``push_id`` is minted by the supplier; PUSH_ACK/PUSH_NACK echo it."""
    payload = (_PUSH.pack(reduce_id & 0xFFFFFFFF, offset, raw_length,
                          _FLAG_LAST if last else 0)
               + _pack_str(job_id) + _pack_str(map_id) + bytes(data))
    return encode_frame(MSG_PUSH, push_id, payload)


def decode_push_take(payload: bytearray) -> tuple:
    """-> ``(job_id, map_id, reduce_id, offset, raw_length, last,
    data)``. Buffer-donating like :func:`decode_result_take`: the chunk
    bytes are carved out of ``payload`` without a second copy of the
    metadata prefix."""
    if len(payload) < _PUSH.size:
        raise TransportError("truncated PUSH frame")
    reduce_id, offset, raw_length, flags = _PUSH.unpack_from(
        bytes(payload[:_PUSH.size]))
    job_id, off = _unpack_str(payload, _PUSH.size, "job id")
    map_id, off = _unpack_str(payload, off, "map id")
    del payload[:off]
    return (job_id, map_id, reduce_id, offset, raw_length,
            bool(flags & _FLAG_LAST), payload)


def encode_push_sub(req_id: int, *, job_id: str, reduce_id: int,
                    window: int, chunk_size: int) -> bytes:
    """MSG_PUSH_SUB: subscribe this connection to (job, reduce) pushes.
    ``window`` is the receiver's un-ACKed-push ceiling and
    ``chunk_size`` its preferred chunk bytes; the supplier takes the
    min with its own knobs. Send only to :data:`CAP_PUSH` peers."""
    payload = (_PUSH_SUB.pack(reduce_id & 0xFFFFFFFF,
                              window & 0xFFFFFFFF,
                              chunk_size & 0xFFFFFFFF)
               + _pack_str(job_id))
    return encode_frame(MSG_PUSH_SUB, req_id, payload)


def decode_push_sub(payload) -> tuple:
    """-> ``(job_id, reduce_id, window, chunk_size)``."""
    if len(payload) < _PUSH_SUB.size:
        raise TransportError("truncated PUSH_SUB frame")
    reduce_id, window, chunk_size = _PUSH_SUB.unpack(
        bytes(payload[:_PUSH_SUB.size]))
    job_id, off = _unpack_str(payload, _PUSH_SUB.size, "job id")
    _done(payload, off, "PUSH_SUB frame")
    return job_id, reduce_id, window, chunk_size


def encode_push_ack(push_id: int) -> bytes:
    """MSG_PUSH_ACK: the chunk landed in staging. Empty payload — the
    push id says it all. Releases one push-window slot."""
    return encode_frame(MSG_PUSH_ACK, push_id, b"")


def encode_push_nack(push_id: int, reason: int) -> bytes:
    """MSG_PUSH_NACK: the chunk was refused (reason codes live in
    ``uda_tpu.net.push``). The supplier marks the partition pull-only;
    the ACKed prefix stays valid."""
    return encode_frame(MSG_PUSH_NACK, push_id,
                        _PUSH_NACK.pack(reason & 0xFF))


def decode_push_nack(payload) -> int:
    """-> reason code."""
    if len(payload) != _PUSH_NACK.size:
        raise TransportError("malformed PUSH_NACK frame")
    return _PUSH_NACK.unpack(bytes(payload))[0]


# -- decode ------------------------------------------------------------------

def decode_header(header: bytes) -> tuple[int, int, int]:
    """Strict header decode -> (msg_type, req_id, payload_len)."""
    if len(header) != HEADER.size:
        raise TransportError(f"truncated frame header "
                             f"({len(header)}/{HEADER.size} B)")
    magic, version, msg_type, req_id, length = HEADER.unpack(header)
    if magic != MAGIC:
        raise TransportError(f"bad frame magic {magic!r} (stream lost "
                             f"frame sync or peer is not a uda_tpu "
                             f"shuffle endpoint)")
    if version != WIRE_VERSION:
        raise TransportError(f"wire version mismatch: peer speaks "
                             f"v{version}, this side v{WIRE_VERSION}")
    if not 1 <= msg_type <= _MAX_TYPE:
        # far outside the reserved range: this is a desynced stream,
        # not a newer peer — in-range unknown types pass here and get
        # a typed ERR from the semantic layer instead of a teardown
        raise TransportError(f"unknown frame type {msg_type}")
    if length > MAX_FRAME:
        raise TransportError(f"frame length {length} exceeds the "
                             f"{MAX_FRAME} B cap (desynced stream?)")
    return msg_type, req_id, length


def _take_trace(payload, off: int, what: str) -> Optional[tuple]:
    """The optional trailing trace-context block: exactly zero or
    exactly ``_TRACE.size`` bytes may remain (the length IS the
    version, the v2-UDIX discipline); anything else is a torn frame."""
    rest = len(payload) - off
    if rest == 0:
        return None
    if rest == _TRACE.size:
        return _TRACE.unpack_from(payload, off)
    raise TransportError(f"malformed {what} frame: {rest} trailing bytes")


def decode_request(payload: bytes) -> ShuffleRequest:
    return decode_request_ex(payload)[0]


def decode_request_ex(payload) -> tuple[ShuffleRequest, Optional[tuple]]:
    """-> (request, optional (trace_id, parent_span_id) wire trace
    context). Old peers send no trace tail; both shapes decode."""
    if len(payload) < _REQ.size:
        raise TransportError(f"truncated REQ frame ({len(payload)} B)")
    reduce_id, offset, chunk_size = _REQ.unpack_from(payload, 0)
    job_id, off = _unpack_str(payload, _REQ.size, "job id")
    map_id, off = _unpack_str(payload, off, "map id")
    trace = _take_trace(payload, off, "REQ")
    return (ShuffleRequest(job_id, map_id, reduce_id, offset, chunk_size),
            trace)


def _decode_result_meta(payload):
    """Parse a DATA payload's meta prefix in place -> (raw_length,
    part_length, offset, last, crc, path, data_start)."""
    if len(payload) < _DATA.size:
        raise TransportError(f"truncated DATA frame ({len(payload)} B)")
    raw_length, part_length, offset, flags = _DATA.unpack_from(payload, 0)
    off = _DATA.size
    crc = None
    if flags & _FLAG_CRC:
        if off + _CRC.size > len(payload):
            raise TransportError("truncated DATA frame: CRC flagged "
                                 "but absent")
        (crc,) = _CRC.unpack_from(payload, off)
        off += _CRC.size
    path, off = _unpack_str(payload, off, "path")
    return (raw_length, part_length, offset, bool(flags & _FLAG_LAST),
            crc, path, off)


def decode_result(payload) -> FetchResult:
    """Accepts bytes or a memoryview (meta fields are parsed in place;
    the single ``bytes()`` of the data region is the only copy)."""
    raw_length, part_length, offset, last, crc, path, off = \
        _decode_result_meta(payload)
    return FetchResult(bytes(payload[off:]), raw_length, part_length,
                       offset, path, last=last, crc=crc)


def decode_result_take(payload: bytearray) -> FetchResult:
    """Buffer-donating decode: ``payload`` is a bytearray the caller
    OWNS (the event-loop client's per-frame receive buffer) — the meta
    fields are parsed in place, the short meta prefix is deleted with
    one memmove, and the SAME bytearray becomes ``FetchResult.data``.
    Zero allocations, zero full-payload copies on the receive path;
    every downstream consumer (record cracking, CRC, decompress,
    ``carry + data`` concatenation) is buffer-agnostic."""
    raw_length, part_length, offset, last, crc, path, off = \
        _decode_result_meta(payload)
    del payload[:off]  # one short memmove; the chunk stays in place
    return FetchResult(payload, raw_length, part_length, offset, path,
                       last=last, crc=crc)


def decode_error(payload: bytes) -> UdaError:
    kind, off = _unpack_str(payload, 0, "error kind")
    message, off = _unpack_str(payload, off, "error message")
    _done(payload, off, "ERR")
    cls = _ERROR_CLASSES.get(kind, TransportError)
    err = cls(f"remote: {message}")
    err.remote_kind = kind
    return err


def decode_size_request(payload: bytes) -> tuple[str, list[str], int]:
    return decode_size_request_ex(payload)[0]


def decode_size_request_ex(payload) -> tuple[tuple, Optional[tuple]]:
    """-> ((job_id, map_ids, reduce_id), optional trace context)."""
    if len(payload) < _SIZE_REQ.size:
        raise TransportError(f"truncated SIZE_REQ frame ({len(payload)} B)")
    reduce_id, n = _SIZE_REQ.unpack_from(payload, 0)
    job_id, off = _unpack_str(payload, _SIZE_REQ.size, "job id")
    mids = []
    for i in range(n):
        mid, off = _unpack_str(payload, off, f"map id {i}")
        mids.append(mid)
    trace = _take_trace(payload, off, "SIZE_REQ")
    return (job_id, mids, reduce_id), trace


def decode_size(payload: bytes) -> Optional[int]:
    if len(payload) != _SIZE.size:
        raise TransportError(f"malformed SIZE frame ({len(payload)} B)")
    (total,) = _SIZE.unpack(payload)
    return None if total < 0 else total


# -- socket helpers ----------------------------------------------------------

def tune_socket(sock, sockbuf_kb: int = 0) -> None:
    """Data-plane socket tuning, applied to EVERY connection on both
    sides and both cores: ``TCP_NODELAY`` always (small REQ/SIZE frames
    must not eat Nagle delays waiting for an ACK that the peer is
    itself delaying), and ``SO_SNDBUF``/``SO_RCVBUF`` sized from the
    ``uda.tpu.net.sockbuf.kb`` knob when non-zero (0 = leave the OS
    autotuned defaults alone)."""
    try:
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
    except OSError:
        pass  # not a TCP socket (socketpair in tests)
    if sockbuf_kb > 0:
        nbytes = int(sockbuf_kb) * 1024
        for opt in (_socket.SO_SNDBUF, _socket.SO_RCVBUF):
            try:
                sock.setsockopt(_socket.SOL_SOCKET, opt, nbytes)
            except OSError:
                pass  # kernel caps (wmem_max) clamp silently anyway


def close_hard(sock) -> None:
    """shutdown() then close(): close() alone neither wakes a thread
    blocked in recv() on the socket nor sends the FIN while that
    thread's syscall pins the file description — the reader (ours or
    the peer's) would block forever on a 'closed' connection. Also the
    only reliable way to wake a thread blocked in accept() on a
    listening socket."""
    try:
        sock.shutdown(_socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _recv_exact(sock, n: int, what: str,
                allow_eof: bool = False) -> Optional[bytes]:
    """Read exactly ``n`` bytes. Clean EOF before the FIRST byte returns
    None when ``allow_eof`` (a peer closing between frames is a normal
    hangup); EOF anywhere else is a mid-frame disconnect ->
    TransportError."""
    parts = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if not parts and allow_eof:
                return None
            raise TransportError(
                f"connection closed mid-frame ({got}/{n} B of {what})")
        parts.append(chunk)
        got += len(chunk)
    return b"".join(parts)


def recv_frame(sock) -> Optional[tuple[int, int, bytes]]:
    """Read one complete frame -> (msg_type, req_id, payload), or None
    on a clean EOF at a frame boundary. Strict: any malformation raises
    TransportError and the caller must drop the connection."""
    header = _recv_exact(sock, HEADER.size, "frame header", allow_eof=True)
    if header is None:
        return None
    msg_type, req_id, length = decode_header(header)
    payload = _recv_exact(sock, length, "frame payload") if length else b""
    return msg_type, req_id, payload
