"""ThreadedShuffleServer: the LEGACY thread-per-connection server core.

PR 4's original shape, kept selectable behind ``uda.tpu.net.core=
threaded`` for exactly one purpose: it is the measured baseline the
event-loop core (``net/server.py``) must beat — ``scripts/net_bench.py``
A/Bs the two on the same host and ``tests/test_net.py`` runs its whole
suite against both, so a semantic divergence between the cores is a
test failure, not a migration surprise. Scheduled for deletion once the
``BENCH_NET_*`` trajectory has a second event-loop-only data point; do
not grow features here.

The TCP stand-in for the reference's RDMAServer (reference
src/DataNet/RDMAServer.cc:537-631): where the reference posted
RDMA-WRITEs into the reduce client's pre-registered memory and completed
them out of order from the AIO completion queue, this server wraps a
:class:`~uda_tpu.mofserver.data_engine.DataEngine` and completes REQ
frames out of order from the engine's futures.

Shape:

- one accept thread (``uda-net-accept``), one reader + one writer
  thread per connection — the per-connection pipeline;
- per-connection credit cap (``mapred.rdma.wqe.per.conn``, the
  reference's WQEs-per-connection bound): the reader blocks before
  handing request N+credit to the engine until an earlier response has
  been WRITTEN back, so a slow or malicious client can hold at most
  ``credit`` engine reads + replies of buffered memory. TCP's own flow
  control then pushes back on the client's send side — credit flow
  without a credit message;
- responses travel reader -> engine future -> per-connection outbound
  queue -> writer, so completion callbacks never block on a slow
  client's socket (the engine pool must keep draining);
- engine errors (missing MOF, admission rejection, injected faults)
  are completed as typed ERR frames, not connection teardown — the
  reduce side's Segment retry machinery decides what to do;
- graceful drain-on-stop: ``stop()`` closes the listener, stops
  READING on every connection, lets in-flight responses flush for up to
  ``uda.tpu.net.drain.s``, then closes (``stop(drain=False)`` is the
  hard variant — mid-stream disconnect, what a killed supplier looks
  like).

Failpoints: ``net.accept`` fires per accepted connection (delay = slow
accept, error = connection dropped at birth); ``net.frame`` fires on
every outbound response frame (truncate = torn frame then disconnect,
error = the send path dying mid-stream).
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from typing import Optional

from uda_tpu.mofserver.data_engine import DataEngine
from uda_tpu.net import wire
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import TransportError, UdaError
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.locks import TrackedLock
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["ThreadedShuffleServer"]

log = get_logger()


class _Conn:
    """One accepted connection: reader pipeline + writer drain."""

    def __init__(self, server: "ThreadedShuffleServer", sock: socket.socket,
                 peer: str):
        self.server = server
        self.sock = sock
        self.peer = peer
        self.credits = threading.Semaphore(server.credit)
        self.outq: "queue.Queue[tuple[bytes, float, bool]]" = queue.Queue()
        self.closed = threading.Event()
        self.draining = threading.Event()
        self._inflight = 0          # requests handed to the engine whose
        self._closing = False       # response is not yet written
        self._lock = TrackedLock("net.conn")
        self.reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"uda-net-read-{peer}")
        self.writer = threading.Thread(
            target=self._write_loop, daemon=True,
            name=f"uda-net-write-{peer}")

    def start(self) -> None:
        self.reader.start()
        self.writer.start()

    # -- inbound ------------------------------------------------------------

    def _read_loop(self) -> None:
        try:
            while not self.closed.is_set() and not self.draining.is_set():
                frame = wire.recv_frame(self.sock)
                if frame is None:
                    break  # clean peer hangup
                msg_type, req_id, payload = frame
                metrics.add("net.bytes.in", wire.HEADER.size + len(payload),
                            role="server")
                if msg_type == wire.MSG_REQ:
                    self._handle_request(req_id, payload)
                elif msg_type == wire.MSG_SIZE_REQ:
                    self._handle_size(req_id, payload)
                else:
                    raise TransportError(
                        f"unexpected frame type {msg_type} on the "
                        f"server side")
        except OSError:
            pass  # socket closed under us (stop path)
        except TransportError as e:
            if not self.closed.is_set():
                log.warn(f"net: dropping connection {self.peer}: {e}")
                metrics.add("net.disconnects", role="server")
        finally:
            # half-close: no new requests; in-flight responses may
            # still flush through the writer until close()
            self.draining.set()
            if self.closed.is_set():
                return
            # no drain pending -> full close now; otherwise the stop
            # path / last completion closes
            if not self.server._stopping.is_set() and self.inflight == 0 \
                    and self.outq.empty():
                self.close()

    def _acquire_credit(self) -> bool:
        """The per-connection credit gate: block READING until a
        response slot frees (the wqe.per.conn bound; EVERY frame that
        produces a response passes through it, so a misbehaving client
        cannot grow the outbound queue without limit). Stop-responsive:
        a closed connection must not leave the reader parked forever.
        Returns False when the connection died while waiting."""
        while not self.credits.acquire(timeout=0.25):
            if self.closed.is_set() or self.draining.is_set():
                return False
        with self._lock:
            self._inflight += 1
        metrics.gauge_add("net.server.inflight", 1)
        return True

    def _release_credit(self) -> None:
        """The single credit-settle point (the inverse of
        _acquire_credit): inflight==0 gates BOTH close paths, so the
        accounting must never fork into hand-synchronized copies."""
        with self._lock:
            self._inflight -= 1
        metrics.gauge_add("net.server.inflight", -1)
        self.credits.release()

    def _handle_request(self, req_id: int, payload: bytes) -> None:
        req = wire.decode_request(payload)
        if not self._acquire_credit():
            return
        metrics.add("net.requests")
        t0 = time.perf_counter()
        span = metrics.start_span("net.serve", map=req.map_id,
                                  reduce=req.reduce_id, offset=req.offset,
                                  peer=self.peer)
        try:
            fut = self.server.engine.submit(req)
        except Exception as e:  # noqa: BLE001 - sync rejection (stopped
            # engine, admission push-back) -> typed ERR completion
            self._complete(req_id, None, e, t0, span)
            return
        fut.add_done_callback(
            lambda f: self._complete(req_id, *(
                (None, f.exception()) if f.exception() is not None
                else (f.result(), None)), t0, span))

    def _complete(self, req_id: int, res, err, t0: float, span) -> None:
        """Engine completion -> encoded response on the outbound queue
        (runs on the engine's worker thread; must never block on the
        socket)."""
        try:
            if err is not None:
                frame = wire.encode_error(req_id, err)
                metrics.add("net.errors")
                span.end(error=type(err).__name__)
            else:
                frame = wire.encode_result(req_id, res)
                span.end(bytes=len(res.data))
        except Exception as e:  # noqa: BLE001 - this runs as a Future
            # done-callback: an escaping exception would be swallowed by
            # the Future machinery WITH the request's credit (the reader
            # eventually wedges at the credit gate). Settle and drop the
            # connection — the client re-fetches on the disconnect.
            log.error(f"net: response encoding for {self.peer} failed: "
                      f"{e}; dropping the connection")
            self._release_credit()
            span.end(error="encode_failed")
            self.close()
            return
        self.outq.put((frame, t0, True))
        if self.closed.is_set():
            # connection died while the engine was reading: the writer
            # is gone, so nobody will pop this frame — settle whatever
            # is stranded in the queue (racing close()'s own drain is
            # fine, the settle helper is idempotent per frame)
            self._settle_abandoned()

    def _handle_size(self, req_id: int, payload: bytes) -> None:
        """Partition size probe (the estimate_partition_bytes channel):
        resolver sums are index-cache lookups, cheap enough to serve
        inline on the reader. Delegates to LocalFetchClient so the
        exact-or-unknown semantics cannot diverge between the wire and
        in-process estimates (the auto merge-approach policy must see
        the same numbers either way)."""
        from uda_tpu.merger.segment import LocalFetchClient

        job_id, mids, reduce_id = wire.decode_size_request(payload)
        if not self._acquire_credit():  # SIZE replies are credited like
            return  # DATA: no frame escapes the wqe.per.conn bound
        total = LocalFetchClient(self.server.engine) \
            .estimate_partition_bytes(job_id, mids, reduce_id)
        self.outq.put((wire.encode_size(req_id, total),
                       time.perf_counter(), True))
        if self.closed.is_set():  # same post-put race as _complete
            self._settle_abandoned()

    # -- outbound -----------------------------------------------------------

    def _write_loop(self) -> None:
        while not self.closed.is_set():
            try:
                frame, t0, credited = self.outq.get(timeout=0.25)
            except queue.Empty:
                if self.draining.is_set() and self.inflight == 0:
                    self.close()
                    break
                continue
            torn = False
            try:
                out = failpoint("net.frame", data=frame, key=self.peer)
                torn = len(out) != len(frame)  # injected truncation
                self.sock.sendall(out)
            except Exception as e:  # noqa: BLE001 - send failure (peer
                # gone, injected error): this connection is over; the
                # client's reader sees the disconnect and fails its
                # in-flight requests into the Segment retry machinery
                if not self.closed.is_set():
                    log.warn(f"net: send to {self.peer} failed: {e}")
                    metrics.add("net.disconnects", role="server")
                self.close()
                break
            finally:
                if credited:
                    self._release_credit()
            metrics.add("net.bytes.out", len(out), role="server")
            if credited:
                metrics.observe("net.frame.latency_ms",
                                (time.perf_counter() - t0) * 1e3,
                                role="server")
            if torn:
                # a truncated frame broke the peer's stream framing:
                # finish the damage deterministically (mid-stream
                # disconnect) instead of feeding it desynced bytes
                log.warn(f"net: frame to {self.peer} torn by failpoint; "
                         f"closing")
                metrics.add("net.disconnects", role="server")
                self.close()
                break

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def drained(self) -> bool:
        return self.inflight == 0 and self.outq.empty()

    def stop_reading(self) -> None:
        self.draining.set()
        try:  # wake a reader blocked in recv
            self.sock.shutdown(socket.SHUT_RD)
        except OSError:
            pass

    def _settle_abandoned(self) -> None:
        """Settle accounting for queued responses that will never be
        written (the connection closed under them). Each frame is
        settled exactly once — whoever pops it from the queue owns its
        credit."""
        while True:
            try:
                _, _, credited = self.outq.get_nowait()
            except queue.Empty:
                return
            if credited:
                self._release_credit()

    def close(self) -> None:
        with self._lock:
            if self._closing:  # atomic test-and-set: a concurrent
                return         # writer-error close and stop() close
            self._closing = True  # must not double-run the body
        self.closed.set()
        wire.close_hard(self.sock)  # shutdown-then-close: wakes blocked
        # readers AND forces the FIN out (see wire.close_hard)
        self._settle_abandoned()
        self.server._forget(self)
        metrics.gauge_add("net.server.connections", -1)


class ThreadedShuffleServer:
    """Serves many concurrent reduce clients over TCP from one
    DataEngine. ``port=0`` binds an ephemeral port (tests); read the
    bound address back from :attr:`address` / :attr:`port`."""

    def __init__(self, engine: DataEngine, config: Optional[Config] = None,
                 host: Optional[str] = None, port: Optional[int] = None):
        cfg = config or Config()
        self.engine = engine
        self.bind_host = host if host is not None \
            else str(cfg.get("uda.tpu.net.bind"))
        self.bind_port = int(port if port is not None
                             else cfg.get("uda.tpu.net.port"))
        self.credit = max(1, int(cfg.get("mapred.rdma.wqe.per.conn")))
        self.drain_s = float(cfg.get("uda.tpu.net.drain.s"))
        self.sockbuf_kb = int(cfg.get("uda.tpu.net.sockbuf.kb"))
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set[_Conn] = set()
        self._lock = TrackedLock("net.server")
        self._stopping = threading.Event()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ThreadedShuffleServer":
        if self._listener is not None:
            raise UdaError("ThreadedShuffleServer already started")
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self.bind_host, self.bind_port))
        ls.listen(128)
        self._listener = ls
        self._stopping.clear()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="uda-net-accept")
        self._accept_thread.start()
        log.info(f"shuffle server listening on {self.address[0]}:"
                 f"{self.address[1]} (credit/conn={self.credit})")
        return self

    @property
    def address(self) -> tuple[str, int]:
        if self._listener is None:
            raise UdaError("ThreadedShuffleServer not started")
        return self._listener.getsockname()[:2]

    @property
    def port(self) -> int:
        return self.address[1]

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                break  # listener closed (stop path)
            peer = f"{addr[0]}:{addr[1]}"
            try:
                # slow-accept / dropped-at-birth injection point
                failpoint("net.accept", key=peer)
            except UdaError as e:
                log.warn(f"net: accept of {peer} rejected: {e}")
                wire.close_hard(sock)
                continue
            wire.tune_socket(sock, self.sockbuf_kb)
            conn = _Conn(self, sock, peer)
            with self._lock:
                if self._stopping.is_set():
                    wire.close_hard(sock)
                    return
                self._conns.add(conn)
            metrics.add("net.accepts")
            metrics.gauge_add("net.server.connections", 1)
            conn.start()

    def _forget(self, conn: _Conn) -> None:
        with self._lock:
            self._conns.discard(conn)

    def stop(self, drain: bool = True) -> None:
        """Stop serving. ``drain=True`` (the default) completes what the
        engine already accepted: stop reading new requests everywhere,
        flush in-flight responses for up to ``uda.tpu.net.drain.s``,
        then close. ``drain=False`` tears connections down mid-stream
        (clients see TransportError — the killed-supplier shape the
        retry/penalty machinery must absorb)."""
        self._stopping.set()
        if self._listener is not None:
            wire.close_hard(self._listener)  # also wakes accept()
        with self._lock:
            conns = list(self._conns)
        if drain:
            for c in conns:
                c.stop_reading()
            deadline = time.monotonic() + self.drain_s
            while time.monotonic() < deadline:
                if all(c.drained() or c.closed.is_set() for c in conns):
                    break
                time.sleep(0.01)
        for c in conns:
            c.close()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
            self._accept_thread = None
        self._listener = None

    def __enter__(self) -> "ThreadedShuffleServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
