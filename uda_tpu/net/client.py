"""RemoteFetchClient: the reduce-side endpoint on the shared event loop.

The reduce side of the data plane rebuilt on the selector core
(:mod:`uda_tpu.net.evloop`): every supplier connection of every client
in the process is multiplexed onto ONE shared loop thread (the
reference ran one completion-channel epoll thread for all QPs,
RDMAClient.cc:498-527 + RDMAComm.cc), replacing PR 4's blocking reader
thread per host. The contract is the threaded client's, exactly:

- ONE multiplexed connection per supplier host, request-id correlation
  table, completions dispatched out of order;
- a dead connection (EOF, torn frame, decode error, send failure)
  fails EVERY in-flight request with ``TransportError`` — each flows
  into its Segment's retry/penalty/fallback machinery independently —
  and the next ``start_fetch`` dials fresh (connection identity is the
  epoch: frames from a dead connection can never complete new
  requests, and request ids are never reused);
- typed ERR frames re-raise the server-side error class;
- ``estimate_partition_bytes`` rides the same connection (SIZE
  frames), best effort, exact-or-unknown.

Receive path: the frame header lands in a REUSABLE per-connection
buffer via ``recv_into``; the payload is then received straight into a
single per-frame bytearray (``recv_into`` a sliced memoryview — no
accumulate-and-join), and :func:`uda_tpu.net.wire.decode_result`
parses meta fields in place so the one ``bytes()`` of the chunk region
is the ONLY reduce-side heap copy per chunk (the threaded core made
three).

Completion upcalls (``on_complete`` — Segment code that may block on
arena admission) run on the loop's dispatcher thread, never the loop
thread itself, so one slow consumer cannot stall the whole process's
fetch plane (UDA008 discipline; the reference's completion-channel
upcall thread).

Failpoints: ``net.connect`` per dial (evaluated on the CALLER thread —
a delay models a slow handshake without stalling the shared loop);
``net.frame`` per outbound request frame, also on the caller thread
(truncation queues the torn bytes and then tears the connection down
deterministically after they flush).
"""

from __future__ import annotations

import selectors
import socket
import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

from uda_tpu.merger.segment import InputClient
from uda_tpu.mofserver.data_engine import ShuffleRequest
from uda_tpu.net import wire
from uda_tpu.net.evloop import EventLoop, loop_callback, shared_client_loop
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import ProtocolError, TransportError, UdaError
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.locks import TrackedLock
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["RemoteFetchClient", "EvLoopFetchClient"]

log = get_logger()

_READ = selectors.EVENT_READ
_WRITE = selectors.EVENT_WRITE

_SIZE_PROBE_TIMEOUT_S = 30.0


class _Waiter:
    """One in-flight request's completion slot."""

    __slots__ = ("on_complete", "span", "t0")

    def __init__(self, on_complete: Callable, span, t0: float):
        self.on_complete = on_complete
        self.span = span
        self.t0 = t0


class _ClientConn:
    """One connection's loop-side state machine (loop thread owns every
    field except ``dead``, which other threads may READ)."""

    def __init__(self, client: "EvLoopFetchClient", loop: EventLoop,
                 sock: socket.socket):
        self.client = client
        self.loop = loop
        self.sock = sock
        self.dead = False
        # write side: any thread may send inline under _wlock (the
        # opportunistic-write fast path — a fetch's REQ frame normally
        # leaves on the ISSUING thread, no loop hop, no wakeup)
        self._wlock = TrackedLock("net.client.write")
        self._outq: "deque" = deque()  # [memoryview, close_after] pairs
        self._poison = False
        self._mask = 0
        # reassembly: reusable header buffer; payload received straight
        # into its own per-frame buffer (no intermediate copies)
        self._hdr = bytearray(wire.HEADER.size)
        self._hdr_got = 0
        self._payload: Optional[bytearray] = None
        self._pay_got = 0
        self._cur = (0, 0)
        # (job, reduce) push subscriptions already SUB'd on THIS
        # connection — per connection by construction, so a reconnect
        # re-subscribes from scratch (the server's tables died with
        # the old socket)
        self.push_subbed: set = set()

    # -- registration --------------------------------------------------------

    @loop_callback
    def register(self) -> None:
        if self.dead:
            return
        self.loop.register(self.sock, _READ, self._on_event)
        self._mask = _READ

    def _update_interest(self) -> None:
        if self.dead:
            return
        mask = _READ | (_WRITE if self._outq else 0)
        if mask != self._mask:
            self.loop.set_events(self.sock, mask)
            self._mask = mask

    @loop_callback
    def _kick(self) -> None:
        self._update_interest()

    # -- outbound (any thread; _wlock serializes writers) --------------------

    def send_frame(self, data: bytes, close_after: bool = False) -> None:
        """Queue one frame and opportunistically write it NOW on the
        calling thread; the loop takes over only a would-block
        residual. Callable from any thread."""
        backlog = False
        with self._wlock:
            if self.dead or self._poison:
                return  # teardown fails this frame's waiter
            self._outq.append([memoryview(data), close_after])
            err = self._drain_locked()
            backlog = bool(self._outq) and not self._poison
        if err is not None:
            self.loop.call_soon(self.die, err)
        elif backlog:
            self.loop.call_soon(self._kick)

    def _drain_locked(self) -> Optional[Exception]:
        """_wlock held: send from the queue head until it would block.
        Returns a fatal error (send failure or a completed torn frame)
        or None."""
        while self._outq and not self._poison:
            ent = self._outq[0]
            try:
                n = self.sock.send(ent[0])
            except (BlockingIOError, InterruptedError):
                return None
            except OSError as e:
                self._poison = True
                return e
            metrics.add("net.bytes.out", n, role="client")
            if n < len(ent[0]):
                ent[0] = ent[0][n:]
                continue
            self._outq.popleft()
            if ent[1]:
                # we knowingly desynced the server's stream (torn
                # net.frame): finish the damage deterministically
                self._poison = True
                return TransportError("request frame torn by failpoint")
        return None

    @loop_callback
    def _flush(self) -> None:
        with self._wlock:
            err = self._drain_locked()
        if err is not None:
            self._die(err)
            return
        self._update_interest()

    # -- inbound -------------------------------------------------------------

    @loop_callback
    def _on_event(self, mask: int) -> None:
        if self.dead:
            return
        if mask & _WRITE:
            self._flush()
        if self.dead:
            return
        if mask & _READ:
            # the transitive recv_into is on THIS loop's non-blocking
            # socket: it returns EWOULDBLOCK instead of parking
            self._do_read()  # udalint: disable=UDA102

    def _do_read(self) -> None:
        # Fill-based recv batching, straight into the final destination
        # (header buffer or the frame's own payload buffer): keep
        # reading only while each recv FILLS what it asked for (more is
        # certainly buffered — a full header is followed by its payload
        # without a select round trip), stop on the first partial
        # return instead of spinning to EAGAIN. On emulated-syscall
        # kernels an empty-handed EAGAIN probe costs as much as a full
        # recv, and stopping early lets bytes batch up in the
        # (sockbuf-sized) kernel buffer between calls — level-triggered
        # epoll re-fires while anything remains.
        while not self.dead:
            if self._payload is None:
                dest = memoryview(self._hdr)[self._hdr_got:]
            else:
                dest = memoryview(self._payload)[self._pay_got:]
            want = len(dest)
            try:
                n = self.sock.recv_into(dest)
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                self._die(e)
                return
            finally:
                # drop the export BEFORE decoding: the buffer-donating
                # decode resizes the payload bytearray in place, which
                # a live memoryview would veto (BufferError)
                dest.release()
            if n == 0:
                self._die(TransportError("supplier closed the connection"))
                return
            metrics.add("net.bytes.in", n, role="client")
            try:
                self._advance(n)
            except TransportError as e:
                self._die(e)
                return
            if n < want:
                return  # kernel buffer drained (or nearly) — back to
                # select; let the next burst accumulate

    def _advance(self, n: int) -> None:
        if self._payload is None:
            self._hdr_got += n
            if self._hdr_got == wire.HEADER.size:
                msg_type, req_id, length = wire.decode_header(
                    bytes(self._hdr))
                self._cur = (msg_type, req_id)
                self._payload = bytearray(length)
                self._pay_got = 0
                if length == 0:
                    self._frame_done()
        else:
            self._pay_got += n
            if self._pay_got == len(self._payload):
                self._frame_done()

    def _frame_done(self) -> None:
        msg_type, req_id = self._cur
        payload = self._payload
        self._payload = None
        self._hdr_got = 0
        if msg_type == wire.MSG_DATA:
            # buffer-donating decode: the per-frame receive buffer
            # BECOMES FetchResult.data (one short memmove for the meta
            # prefix, no chunk-sized allocation or copy)
            result = wire.decode_result_take(payload)
        elif msg_type == wire.MSG_ERR:
            result = wire.decode_error(memoryview(payload))
        elif msg_type == wire.MSG_SIZE:
            result = wire.decode_size(memoryview(payload))
        elif msg_type == wire.MSG_JOB_OK:
            result = wire.decode_job_ok(payload)
        elif msg_type == wire.MSG_STATS_REPLY:
            result = wire.decode_stats_reply(memoryview(payload))
        elif msg_type == wire.MSG_HELLO:
            # the accept banner correlates with no request: record the
            # server generation (warm-restart continuity) and its
            # capability bits (trace-context frames, MSG_STATS), then
            # move on
            generation, warm, caps = wire.decode_hello_ex(bytes(payload))
            self.client._on_hello(generation, warm, caps)
            return
        elif msg_type == wire.MSG_PUSH:
            # supplier-initiated chunk (ISSUE 19): only arrives on
            # connections that PUSH_SUB'd. Admission (budget route,
            # possible spill write) blocks — dispatcher thread, never
            # the loop
            self.client._on_push(self, req_id, payload)
            return
        else:
            raise TransportError(
                f"unexpected frame type {msg_type} on the client side")
        self.client._complete(self, req_id, result, msg_type)

    # -- teardown ------------------------------------------------------------

    def _die(self, cause: Exception) -> None:
        """Loop thread: close this connection and fail everything in
        flight on it (via the client, which owns the table)."""
        if self.dead:
            return
        self.dead = True
        with self._wlock:
            self._poison = True
            self._outq.clear()
        self.loop.unregister(self.sock)
        wire.close_hard(self.sock)
        self.client._on_conn_dead(self, cause)

    @loop_callback
    def die(self, cause: Exception) -> None:
        self._die(cause)

    @loop_callback
    def close_quiet(self) -> None:
        """Stop-path close: the client already settled its own table,
        gauges and waiters — just release the loop/socket resources."""
        if self.dead:
            return
        self.dead = True
        with self._wlock:
            self._poison = True
            self._outq.clear()
        self.loop.unregister(self.sock)
        wire.close_hard(self.sock)


class EvLoopFetchClient(InputClient):
    """Multiplexed fetch client for one supplier host, on the shared
    process-wide event loop."""

    def __init__(self, host: str, port: Optional[int] = None,
                 config: Optional[Config] = None):
        cfg = config or Config()
        self.host = host
        self.port = int(port if port is not None
                        else cfg.get("uda.tpu.net.port"))
        self.connect_timeout_s = float(
            cfg.get("uda.tpu.net.connect.timeout.s"))
        self.sockbuf_kb = int(cfg.get("uda.tpu.net.sockbuf.kb"))
        # lockdep-tracked: PR 4's deadlock class lived exactly here
        self._lock = TrackedLock("net.client")  # table + conn identity
        self._conn: Optional[_ClientConn] = None
        self._pending: dict = {}       # req_id -> _Waiter
        self._next_id = 0              # never reused across connections
        self._stopped = False
        # warm-restart continuity (the HELLO accept banner): the last
        # observed server generation, and whether a resumed offset
        # ledger is still continuous with this supplier's bytes
        self._generation: Optional[int] = None
        self._resumable = True
        # peer capability bits from the HELLO banner (wire.CAP_TRACE:
        # the peer decodes trace-context REQ tails + serves MSG_STATS;
        # wire.CAP_TENANT: the peer runs the tenant registry).
        # 0 until the banner lands — frames sent before it stay
        # un-extended, which is always legal.
        self._peer_caps = 0
        self._hello_seen = threading.Event()
        # multi-tenant binding (uda_tpu/tenant/): when a tenant id is
        # configured, the FIRST fetch of each job on each connection is
        # preceded by an authenticated MSG_JOB frame binding
        # (tenant, job, epoch) in the supplier's registry — TCP
        # ordering makes register-before-fetch a wire guarantee. Empty
        # tenant = the pre-tenancy client, frame for frame.
        self._tenant = str(cfg.get("uda.tpu.tenant.id"))
        self._tenant_epoch = max(1, int(cfg.get("uda.tpu.tenant.epoch")))
        self._tenant_weight = max(1,
                                  int(cfg.get("uda.tpu.tenant.weight")))
        self._tenant_secret = str(cfg.get("uda.tpu.tenant.secret"))
        # jobs MSG_JOB'd on THIS conn: job -> Event set once the bind
        # frame is ON THE WIRE. Register-before-fetch must hold across
        # concurrent first fetches of one job: the loser of the bind
        # race waits for the winner's frame to be posted before its
        # REQ may leave, or the REQ could overtake the MSG_JOB and
        # land unregistered (typed refusal under strict, a silent
        # default-tenant pass otherwise).
        self._bound_jobs: dict = {}
        # push plane (ISSUE 19): (job, reduce) -> PushStaging. The
        # registration OUTLIVES connections — every fresh banner that
        # advertises CAP_PUSH gets the subscriptions re-sent (the
        # per-conn sent-set lives on the connection object).
        self._push_staging: dict = {}
        self._push_window = max(1, int(cfg.get("uda.tpu.push.window")))
        self._push_chunk = int(cfg.get("mapred.rdma.buf.size")) * 1024

    def _on_hello(self, generation: int, warm: bool,
                  caps: int = 0) -> None:
        """Loop thread (first frame of every connection). A CHANGED
        generation is a supplier restart: warm (handoff-continued)
        keeps resume legal, cold revokes it — a cold supplier may hold
        a different attempt's bytes, so retrying segments must restart
        from zero (their raw_length identity check is the backstop
        either way)."""
        with self._lock:
            prev = self._generation
            self._generation = generation
            self._peer_caps = caps
            if prev is not None and generation != prev and not warm:
                # STICKY: a later warm bounce must not re-legalize
                # resume — a segment's offset ledger may predate the
                # cold generation, and the warm flag only certifies
                # continuity with the generation it succeeded. Segments
                # created after this client object are conservative by
                # one refetch; correctness wins.
                self._resumable = False
        self._hello_seen.set()
        if prev is not None and generation != prev:
            metrics.add("net.generation.changes", host=self.host,
                        warm=str(bool(warm)).lower())
            log.warn(f"net: supplier {self.host}:{self.port} restarted "
                     f"(generation {prev} -> {generation}, "
                     f"{'warm' if warm else 'COLD'})")

    def resume_ok(self, host: str = "") -> bool:
        """May a retrying segment keep its offset ledger against this
        supplier? True until a COLD restart is observed (see
        _on_hello); optimistic across an unresolved reconnect — the
        resumed fetch's identity check revalidates on the first
        chunk."""
        with self._lock:
            return self._resumable

    def generation(self, host: str = "") -> Optional[int]:
        """Last HELLO generation observed from this supplier (None until
        the first handshake). Checkpoint manifests record it so a resume
        can tell a same-generation supplier (ledger still valid) from a
        restarted one (drop the ledger, keep the run files)."""
        with self._lock:
            return self._generation

    def peer_caps(self, host: str = "") -> int:
        """Last HELLO capability bits (0 until the first handshake —
        also the correct conservative answer: no advertised cap means
        no optional behavior)."""
        with self._lock:
            return self._peer_caps

    def peer_draining(self, host: str = "") -> bool:
        """Did the last banner carry CAP_DRAINING? A draining supplier
        still serves (in-flight work completes) but the candidate
        ranking demotes it so speculation/replica reads prefer staying
        members (segment.py HostRoutingClient / merge_manager)."""
        with self._lock:
            return bool(self._peer_caps & wire.CAP_DRAINING)

    # -- connection management ----------------------------------------------

    def _ensure_connected(self) -> _ClientConn:
        """The live connection, dialing fresh when there is none. The
        dial itself is blocking WITH a timeout and runs on the caller's
        thread (never the loop); a failed dial raises TransportError and
        the Segment's RetryPolicy paces the reconnects."""
        with self._lock:
            if self._stopped:
                raise TransportError(
                    f"RemoteFetchClient({self.host}) is stopped")
            if self._conn is not None:
                return self._conn
        failpoint("net.connect", key=f"{self.host}:{self.port}")
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_s)
        except OSError as e:
            metrics.add("net.connect.failures", host=self.host)
            raise TransportError(
                f"connect to supplier {self.host}:{self.port} failed: "
                f"{e}") from e
        sock.setblocking(False)
        wire.tune_socket(sock, self.sockbuf_kb)
        loop = shared_client_loop()
        conn = _ClientConn(self, loop, sock)
        with self._lock:
            if self._stopped or self._conn is not None:
                # lost the dial race (or stopped underneath): keep the
                # winner's connection
                wire.close_hard(sock)
                if self._stopped:
                    raise TransportError(
                        f"RemoteFetchClient({self.host}) is stopped")
                return self._conn
            self._conn = conn
        metrics.add("net.connects", host=self.host)
        metrics.gauge_add("net.client.connections", 1)
        loop.call_soon(conn.register)
        # bounded first-banner wait: the HELLO (first frame on every
        # accept) carries the peer's capability bits — without this, a
        # fetch racing the banner would always go un-extended and the
        # FIRST chunk of a trace would predictably lose its supplier
        # spans. Best-effort: timing out just means un-extended frames
        # (always legal), never an error. Reconnects wait too —
        # _on_conn_dead cleared the event and the caps, because the
        # peer behind host:port may have been REPLACED since the last
        # banner (stale CAP_TRACE against an old decoder tears frames).
        self._hello_seen.wait(timeout=min(2.0, self.connect_timeout_s))
        # re-subscribe the push plane on every fresh banner: the
        # server-side tables died with the previous socket (a timed-out
        # banner leaves caps=0 — no SUB, pull-only, always legal)
        self._send_push_subs(conn)
        return conn

    def _trace_of(self, span) -> Optional[tuple]:
        """The wire trace-context tail for one outbound frame: this
        request's OWN span ids (the supplier's serve span becomes its
        child), sent only when the peer's HELLO advertised
        wire.CAP_TRACE — an old decoder would tear on trailing
        bytes."""
        if span is None or span.span_id is None:
            return None  # spans disabled (noop span)
        with self._lock:
            if not self._peer_caps & wire.CAP_TRACE:
                return None
        return span.trace_id, span.span_id

    def _on_conn_dead(self, conn: _ClientConn, cause: Exception) -> None:
        """Loop thread (via _die): fail every request in flight on this
        connection. Requests registered after a reconnect belong to the
        new connection object by construction — the table swaps under
        the same lock as the connection identity."""
        with self._lock:
            if self._conn is not conn:
                return  # the stop path (or an earlier _die) settled it
            self._conn = None
            orphans = list(self._pending.items())
            self._pending.clear()
            # capability state dies with the connection: the NEXT dial
            # may reach a replaced peer (e.g. a pre-CAP_TRACE binary),
            # and a stale trace bit would make every post-reconnect REQ
            # carry the 16-byte tail its strict decoder tears on.
            # Clearing _hello_seen restores the bounded first-banner
            # wait, same as a fresh dial. Generation/resume state is
            # deliberately KEPT — resume legality is judged against the
            # new banner's generation when it lands (_on_hello).
            self._peer_caps = 0
            self._hello_seen.clear()
            # tenant bindings are per connection (the server's registry
            # entry survives; the CONNECTION's binding does not) — the
            # next fetch re-sends MSG_JOB before its REQ
            self._bound_jobs.clear()
        metrics.gauge_add("net.client.connections", -1)
        metrics.add("net.disconnects", role="client")
        err = TransportError(
            f"connection to supplier {self.host}:{self.port} lost "
            f"({type(cause).__name__}: {cause}); "
            f"{len(orphans)} fetches in flight")
        for req_id, waiter in orphans:
            waiter.span.end(error="disconnect")
            # completion upcalls may block (and may re-issue fetches):
            # dispatcher thread, same FIFO as normal completions
            conn.loop.dispatch(self._deliver, req_id, waiter, err)

    def _complete(self, conn: _ClientConn, req_id: int, result,
                  msg_type: int) -> None:
        """Loop thread: correlate one decoded frame to its waiter and
        hand the upcall to the dispatcher (the completing connection's
        own loop — no global-lock rediscovery on the per-frame path)."""
        with self._lock:
            waiter = self._pending.pop(req_id, None)
        if waiter is None:
            # dead-connection leftovers / cancelled probe: count, move on
            metrics.add("net.frames.orphaned")
            return
        if msg_type != wire.MSG_SIZE:
            metrics.observe("net.frame.latency_ms",
                            (time.perf_counter() - waiter.t0) * 1e3,
                            role="client")
        if isinstance(result, Exception):
            waiter.span.end(error=type(result).__name__)
        else:
            waiter.span.end()
        conn.loop.dispatch(self._deliver, req_id, waiter, result)

    def _deliver(self, req_id: int, waiter: _Waiter, result) -> None:
        """Dispatcher thread: the actual upcall."""
        try:
            waiter.on_complete(result)
        except Exception as e:  # noqa: BLE001 - one waiter's bug must
            # not starve every later completion of delivery
            log.warn(f"net: completion callback for req {req_id} "
                     f"raised: {e}")

    # -- the tenant handshake -----------------------------------------------

    def bind_tenant(self, tenant_id: str, epoch: int = 1,
                    weight: int = 1, secret: str = "") -> None:
        """Install (or change) this client's tenant identity — the
        programmatic twin of the ``uda.tpu.tenant.*`` knobs. A changed
        epoch re-binds each job on its next fetch."""
        with self._lock:
            self._tenant = str(tenant_id)
            self._tenant_epoch = max(1, int(epoch))
            self._tenant_weight = max(1, int(weight))
            if secret:
                self._tenant_secret = secret
            self._bound_jobs.clear()

    def _job_frame(self, req_id: int, job_id: str,
                   retire: bool = False) -> bytes:
        from uda_tpu.tenant import sign_job

        return wire.encode_job(
            req_id, self._tenant, job_id, self._tenant_epoch,
            weight=self._tenant_weight,
            token=sign_job(self._tenant_secret, self._tenant, job_id,
                           self._tenant_epoch),
            retire=retire)

    def _maybe_bind(self, conn: _ClientConn, job_id: str) -> None:
        """Send MSG_JOB for ``job_id`` ahead of its first REQ on this
        connection (fire-and-forget: a refusal comes back as a typed
        ERR on the MSG_JOB's req id — logged and counted; the
        subsequent REQs draw their own typed TenantErrors from the
        server's fence, which is what fails the fetch machinery).
        No-op without a configured tenant or a CAP_TENANT peer.
        Concurrent first fetches of one job serialize here: the bind
        race's winner posts the MSG_JOB frame and sets the job's
        event; losers WAIT on it (bounded) so no REQ can overtake the
        registration onto the wire."""
        with self._lock:
            if not self._tenant or self._conn is not conn \
                    or not self._peer_caps & wire.CAP_TENANT:
                return
            posted = self._bound_jobs.get(job_id)
            if posted is None:
                posted = threading.Event()
                self._bound_jobs[job_id] = posted
                self._next_id += 1
                req_id = self._next_id

                def on_bound(result) -> None:
                    if isinstance(result, Exception):
                        metrics.add("tenant.bind.errors")
                        log.warn(f"tenant bind of {self._tenant}/"
                                 f"{job_id} on {self.host} refused: "
                                 f"{result}")

                self._pending[req_id] = _Waiter(
                    on_bound, metrics.start_span("net.job_bind",
                                                 host=self.host),
                    time.perf_counter())
            else:
                req_id = None
        if req_id is None:
            # best-effort bound wait: a timeout degrades to the
            # server-side fence semantics, never an error here
            posted.wait(timeout=min(5.0, self.connect_timeout_s))
            return
        try:
            self._post(conn, self._job_frame(req_id, job_id))
        finally:
            posted.set()

    def _job_roundtrip(self, job_id: str, retire: bool,
                       timeout: float) -> int:
        """Blocking MSG_JOB round trip: returns the granted epoch or
        re-raises the typed registry refusal (tests, embedders that
        want registration confirmed before issuing work)."""
        conn = self._ensure_connected()
        box: list = [None]
        got = threading.Event()

        def on_reply(result) -> None:
            box[0] = result
            got.set()

        posted = threading.Event()
        with self._lock:
            if self._conn is not conn:
                raise TransportError(
                    f"connection to {self.host} lost before the "
                    f"MSG_JOB round trip")
            if not retire:
                self._bound_jobs[job_id] = posted
            self._next_id += 1
            req_id = self._next_id
            self._pending[req_id] = _Waiter(
                on_reply, metrics.start_span("net.job_bind",
                                             host=self.host,
                                             retire=retire),
                time.perf_counter())
        try:
            self._post(conn,
                       self._job_frame(req_id, job_id, retire=retire))
        finally:
            posted.set()
        if not got.wait(timeout=timeout):
            with self._lock:
                self._pending.pop(req_id, None)
            raise TransportError(
                f"MSG_JOB to {self.host} timed out after {timeout:g}s")
        result = box[0]
        if isinstance(result, Exception):
            if not retire:
                with self._lock:
                    self._bound_jobs.pop(job_id, None)
            raise result
        return int(result)

    # -- push plane (ISSUE 19) ----------------------------------------------

    def push_register(self, job_id: str, reduce_id: int, staging,
                      hosts=None) -> None:
        """Arm reduce-side staging for (job, reduce) and subscribe the
        supplier: committed partitions start arriving as MSG_PUSH
        chunks. Dial is eager (pushes need a live connection before
        the first fetch exists) but best-effort — a failed dial just
        leaves the plane pull-only until the next fetch redials, and
        a push-less peer (no CAP_PUSH in its banner) is never sent a
        SUB at all."""
        with self._lock:
            if self._stopped:
                return
            self._push_staging[(job_id, int(reduce_id))] = staging
        try:
            conn = self._ensure_connected()
        except TransportError:
            return
        self._send_push_subs(conn)

    def push_unregister(self, job_id: str, reduce_id: int) -> None:
        """Drop the staging registration. No un-SUB frame exists (nor
        needs to): a late push finds no staging, draws
        PUSH_NACK(UNKNOWN), and the supplier marks the partition
        pull-only — self-healing by the refusal path."""
        with self._lock:
            self._push_staging.pop((job_id, int(reduce_id)), None)

    def _send_push_subs(self, conn: _ClientConn) -> None:
        """Send MSG_PUSH_SUB for every registration not yet SUB'd on
        this connection (idempotent per conn; any thread). Fire and
        forget, the MSG_JOB discipline: a refusal would come back as a
        typed ERR with no waiter — counted as an orphan, and the plane
        simply stays pull-only."""
        frames = []
        with self._lock:
            if self._conn is not conn or not self._push_staging \
                    or not self._peer_caps & wire.CAP_PUSH:
                return
            for key in self._push_staging:
                if key in conn.push_subbed:
                    continue
                conn.push_subbed.add(key)
                self._next_id += 1
                frames.append(wire.encode_push_sub(
                    self._next_id, job_id=key[0], reduce_id=key[1],
                    window=self._push_window,
                    chunk_size=self._push_chunk))
        for frame in frames:
            self._post(conn, frame)

    def _on_push(self, conn: _ClientConn, push_id: int,
                 payload: bytearray) -> None:
        """Loop thread: hand the pushed chunk to the dispatcher —
        admission may write a spill file, and the verdict frame goes
        back inline from there."""
        conn.loop.dispatch(self._handle_push, conn, push_id, payload)

    def _handle_push(self, conn: _ClientConn, push_id: int,
                     payload: bytearray) -> None:
        """Dispatcher thread: decode, run the staging admission
        ladder, answer PUSH_ACK or PUSH_NACK."""
        from uda_tpu.net.push import NACK_UNKNOWN
        try:
            (job_id, map_id, reduce_id, offset, raw_length, last,
             data) = wire.decode_push_take(payload)
        except UdaError as e:
            conn.loop.call_soon(conn.die, e)
            return
        with self._lock:
            staging = self._push_staging.get((job_id, int(reduce_id)))
        if staging is None:
            metrics.add("push.refused", reason="unknown")
            verdict = NACK_UNKNOWN
        else:
            verdict = staging.offer(map_id, offset, raw_length, last,
                                    data)
        frame = (wire.encode_push_ack(push_id) if verdict == 0
                 else wire.encode_push_nack(push_id, verdict))
        self._post(conn, frame)

    def bind_job(self, job_id: str, timeout: float = 10.0) -> int:
        """Register (tenant, job, epoch) with the supplier and wait for
        the grant; raises the typed TenantError on refusal."""
        return self._job_roundtrip(job_id, retire=False, timeout=timeout)

    def retire_job(self, job_id: str, timeout: float = 10.0) -> int:
        """Retire the job in the supplier's registry (the lifecycle's
        final transition; the daemon drains the tenant's obligation
        books and later REQs draw typed errors)."""
        return self._job_roundtrip(job_id, retire=True, timeout=timeout)

    # -- InputClient --------------------------------------------------------

    def start_fetch(self, req: ShuffleRequest, on_complete) -> None:
        """Issue one fetch on the multiplexed connection. Completion
        (FetchResult, typed remote error, or disconnect TransportError)
        arrives on the shared dispatcher thread — the completion-
        channel upcall shape."""
        span = metrics.start_span(
            "net.fetch", host=self.host, map=req.map_id,
            reduce=req.reduce_id, offset=req.offset)
        try:
            conn = self._ensure_connected()
        except TransportError as e:
            span.end(error=type(e).__name__)
            on_complete(e)
            return
        # tenant plane: the job's MSG_JOB precedes its first REQ on
        # this connection (TCP order = registration order)
        self._maybe_bind(conn, req.job_id)
        with self._lock:
            died = self._conn is not conn
            if not died:
                self._next_id += 1
                req_id = self._next_id
                self._pending[req_id] = _Waiter(on_complete, span,
                                                time.perf_counter())
        if died:
            # connection died between dial and registration; complete
            # OUTSIDE the lock — the callback may re-issue immediately
            span.end(error="disconnect")
            on_complete(TransportError(
                f"connection to {self.host}:{self.port} lost before "
                f"the fetch was issued"))
            return
        self._post(conn, wire.encode_request(req_id, req,
                                             trace=self._trace_of(span)))

    def _post(self, conn: _ClientConn, frame: bytes) -> None:
        """Write one frame — inline on this thread when the socket has
        room (the fast path), via the loop for any residual. The
        net.frame failpoint fires HERE, on the caller thread: an
        injected error tears the connection down (failing this request
        with every other in-flight one); a truncation sends the torn
        bytes with a deterministic teardown behind them."""
        try:
            out = failpoint("net.frame", data=frame,
                            key=f"client:{self.host}")
        except Exception as e:  # noqa: BLE001
            conn.loop.call_soon(conn.die, e)
            return
        conn.send_frame(out, len(out) != len(frame))

    def estimate_partition_bytes(self, job_id: str, map_ids: Sequence[str],
                                 reduce_id: int) -> Optional[int]:
        """Partition size probe over the wire (SIZE frames). Best
        effort: any transport trouble or timeout returns None — the
        auto merge-approach policy then takes its bounded-memory
        default, it must never fail a task over a size probe."""
        try:
            conn = self._ensure_connected()
        except TransportError:
            return None
        self._maybe_bind(conn, job_id)
        box: list = [None]
        got = threading.Event()

        def on_size(result) -> None:
            box[0] = result
            got.set()

        span = metrics.start_span("net.size_probe", host=self.host,
                                  reduce=reduce_id, maps=len(map_ids))
        with self._lock:
            if self._conn is not conn:
                span.end(error="disconnect")
                return None
            self._next_id += 1
            req_id = self._next_id
            self._pending[req_id] = _Waiter(on_size, span,
                                            time.perf_counter())
        self._post(conn, wire.encode_size_request(
            req_id, job_id, list(map_ids), reduce_id,
            trace=self._trace_of(span)))
        if not got.wait(timeout=_SIZE_PROBE_TIMEOUT_S):
            with self._lock:
                self._pending.pop(req_id, None)  # late reply -> orphaned
            span.end(error="timeout")
            return None
        result = box[0]
        return None if isinstance(result, Exception) else result

    def fetch_stats(self, timeout: float = _SIZE_PROBE_TIMEOUT_S,
                    window_s: Optional[int] = None) -> Optional[dict]:
        """Snapshot the supplier's live introspection record over the
        multiplexed connection (MSG_STATS — uncredited on the server,
        so it answers even when data holds every credit). Best effort:
        transport trouble, a typed ERR (old peer), or a timeout
        returns None.

        ``window_s`` additionally requests the observability sections
        (rollup window, per-tenant SLIs, active anomalies) — sent only
        when the peer's HELLO advertised :data:`wire.CAP_OBS` (an old
        decoder would tear on the tail); against an older peer the
        plain snapshot is returned instead."""
        try:
            conn = self._ensure_connected()
        except TransportError:
            return None
        box: list = [None]
        got = threading.Event()

        def on_stats(result) -> None:
            box[0] = result
            got.set()

        span = metrics.start_span("net.stats", host=self.host)
        with self._lock:
            if self._conn is not conn:
                span.end(error="disconnect")
                return None
            self._next_id += 1
            req_id = self._next_id
            self._pending[req_id] = _Waiter(on_stats, span,
                                            time.perf_counter())
        if window_s is not None and self._peer_caps & wire.CAP_OBS:
            frame = wire.encode_stats_request(req_id, window_s=window_s)
        else:
            frame = wire.encode_stats_request(req_id)
        self._post(conn, frame)
        if not got.wait(timeout=timeout):
            with self._lock:
                self._pending.pop(req_id, None)
            span.end(error="timeout")
            return None
        result = box[0]
        return result if isinstance(result, dict) else None

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            conn, self._conn = self._conn, None
            orphans = list(self._pending.values())
            self._pending.clear()
        if conn is not None:
            conn.loop.call_soon(conn.close_quiet)
            metrics.gauge_add("net.client.connections", -1)
        err = TransportError(
            f"RemoteFetchClient({self.host}) stopped with "
            f"{len(orphans)} fetches in flight")
        for waiter in orphans:
            waiter.span.end(error="stopped")
            try:
                waiter.on_complete(err)
            except Exception as e:  # noqa: BLE001
                log.warn(f"net: completion callback raised during "
                         f"stop: {e}")


# The shared event loop is THE client core: the legacy thread-per-host
# reader (PR 4) was deleted once BENCH_NET_r07.json recorded the second
# evloop-only point (last A/B: BENCH_NET_r06.json).
RemoteFetchClient = EvLoopFetchClient


def fetch_remote_stats(host: str, port: Optional[int] = None,
                       timeout: float = 5.0,
                       config: Optional[Config] = None,
                       window_s: Optional[int] = None) -> dict:
    """One-shot MSG_STATS poll over a plain blocking socket — the
    scripts/udatop.py / udafleet.py scrape path: no shared loop, no
    client object, one dial per poll (an introspection console must
    work against a process whose client plane it is not part of).
    Consumes the HELLO banner, sends MSG_STATS, returns the decoded
    snapshot dict. Raises TransportError on dial failure/timeout and
    re-raises the typed remote error when the peer answers ERR (an old
    peer's ProtocolError refusal included).

    ``window_s`` requests the CAP_OBS observability sections
    (time-series rollups for the trailing window, per-tenant SLIs,
    active anomalies). The tail is sent only after the peer's HELLO
    advertised :data:`wire.CAP_OBS`; an older peer degrades to the
    plain snapshot — never a torn frame."""
    cfg = config or Config()
    if port is None:
        port = int(cfg.get("uda.tpu.net.port"))
    try:
        sock = socket.create_connection((host, port), timeout=timeout)
    except OSError as e:
        raise TransportError(
            f"stats poll: connect to {host}:{port} failed: {e}") from e
    try:
        sock.settimeout(timeout)
        wire.tune_socket(sock)
        sent = window_s is None  # plain polls need no caps knowledge
        if sent:
            try:
                sock.sendall(wire.encode_stats_request(1))
            except OSError as e:  # peer died between accept and send
                raise TransportError(
                    f"stats poll: send to {host}:{port} failed: "
                    f"{e}") from e
        while True:
            try:
                frame = wire.recv_frame(sock)
            except socket.timeout as e:  # noqa: PERF203 - bounded poll
                raise TransportError(
                    f"stats poll: {host}:{port} did not answer within "
                    f"{timeout:g} s") from e
            except OSError as e:
                # a mid-poll RST/EPIPE must keep the typed contract
                # (udatop's loop catches UdaError only): a raw OSError
                # escaping here crashes the console over one sick peer
                raise TransportError(
                    f"stats poll: {host}:{port} connection lost: "
                    f"{e}") from e
            if frame is None:
                # the peer spoke the wire fine and hung up on the
                # MSG_STATS frame itself: that is an old decoder
                # refusing an unknown type, not a dead endpoint —
                # ProtocolError so consoles render "unsupported", not
                # "down" (udatop branches on the TYPE, UDA005)
                raise ProtocolError(
                    f"stats poll: {host}:{port} closed the connection "
                    f"on MSG_STATS (pre-observability peer)")
            msg_type, _req_id, payload = frame
            if msg_type == wire.MSG_HELLO:
                if not sent:
                    # windowed polls hold the request until the banner
                    # tells us the peer's capabilities: the _STATS_OPT
                    # tail would tear an old decoder's framing, so a
                    # pre-CAP_OBS peer gets the plain request instead
                    # (degrade to the PR 11 snapshot, never a torn
                    # frame)
                    _gen, _warm, caps = wire.decode_hello_ex(payload)
                    if caps & wire.CAP_OBS:
                        req = wire.encode_stats_request(
                            1, window_s=window_s)
                    else:
                        req = wire.encode_stats_request(1)
                    try:
                        sock.sendall(req)
                    except OSError as e:
                        raise TransportError(
                            f"stats poll: send to {host}:{port} "
                            f"failed: {e}") from e
                    sent = True
                continue  # the banner precedes every reply
            if msg_type == wire.MSG_STATS_REPLY:
                return wire.decode_stats_reply(payload)
            if msg_type == wire.MSG_ERR:
                raise wire.decode_error(payload)
            raise TransportError(
                f"stats poll: unexpected frame type {msg_type} from "
                f"{host}:{port}")
    finally:
        wire.close_hard(sock)
