"""LZO1X block codec for the compressed fetch path.

Equivalent of the reference's LzoDecompressor (reference
src/Merger/LzoDecompressor.cc:83-127): ``liblzo2.so`` is dlopen'd at
runtime, initialised through ``__lzo_init_v2`` and driven through
``lzo1x_decompress_safe`` / ``lzo1x_1_compress``; absence of the
library is a runtime condition, not a build dependency.

Because liblzo2 is often NOT installed (it is optional in Hadoop
deployments too), this module also carries a pure-Python LZO1X
implementation of the same stream format:

- ``lzo1x_decompress_py`` decodes the full LZO1X token grammar
  (literal runs, M1-M4 matches, the 0x11 00 00 end marker), so streams
  produced by real liblzo2 decode without the native library;
- ``lzo1x_compress_py`` emits valid LZO1X streams using literal runs
  only (one initial/extended run + end marker) — decodable by any
  conforming decoder including liblzo2 itself. Compression ratio is
  ~1.0 (this is a compatibility encoder, not an optimizer); when
  liblzo2 is present the native lzo1x_1 compressor is used instead.

The codec registers under Hadoop's LZO codec class names (the
createInputClient dispatch of reference src/Merger/reducer.cc:412-450).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import threading

from uda_tpu.utils.errors import CompressionError

__all__ = ["lzo_codec", "lzo1x_compress_py", "lzo1x_decompress_py",
           "native_lzo_available", "native_lzo_source"]

_EOS = b"\x11\x00\x00"  # M4 token with distance 0: the end-of-stream marker


# --------------------------------------------------------------------------
# pure-Python LZO1X
# --------------------------------------------------------------------------

def lzo1x_compress_py(data: bytes) -> bytes:
    """Encode ``data`` as a literal-only LZO1X stream (format-conformant,
    ratio ~1.0; see module docstring)."""
    data = bytes(data)
    n = len(data)
    out = bytearray()
    if n == 0:
        return bytes(_EOS)
    if n <= 238:
        # first-byte form: byte > 17 means an initial literal run of
        # (byte - 17) bytes (for < 4 the decoder takes the match_next
        # path, which is equally valid)
        out.append(17 + n)
        out += data
    else:
        # in-loop literal run with extended length: token 0, then
        # zero-bytes each worth 255, then a final nonzero byte; run
        # length = 15 + 255*zeros + final + 3
        t = n - 3
        x = t - 15
        zeros, final = divmod(x, 255)
        if final == 0:
            zeros -= 1
            final = 255
        out.append(0)
        out += b"\x00" * zeros
        out.append(final)
        out += data
    out += _EOS
    return bytes(out)


def lzo1x_decompress_py(src: bytes, expected_len: int) -> bytes:
    """Decode a full LZO1X stream (safe: all reads bounds-checked)."""
    src = bytes(src)
    n = len(src)
    out = bytearray()
    ip = 0

    def byte() -> int:
        nonlocal ip
        if ip >= n:
            raise CompressionError("truncated LZO stream")
        b = src[ip]
        ip += 1
        return b

    def copy_literals(count: int) -> None:
        nonlocal ip
        if ip + count > n:
            raise CompressionError("truncated LZO literal run")
        if len(out) + count > expected_len:
            # the "safe" output bound (reference lzo1x_decompress_safe's
            # NEED_OP): fail fast instead of decoding past the block's
            # declared size on corrupt input
            raise CompressionError("LZO output exceeds declared length")
        out.extend(src[ip:ip + count])
        ip += count

    def copy_match(m_pos: int, count: int) -> None:
        if m_pos < 0:
            raise CompressionError("LZO lookbehind underrun")
        if len(out) + count > expected_len:
            raise CompressionError("LZO output exceeds declared length")
        for _ in range(count):  # byte-wise: overlapping matches replicate
            out.append(out[m_pos])
            m_pos += 1

    def extended(t: int, base: int) -> int:
        nonlocal ip
        while True:
            b = byte()
            if b == 0:
                t += 255
                if t > (1 << 30):
                    raise CompressionError("LZO run length overflow")
            else:
                return t + base + b

    # ---- initial byte ----
    mode = "loop"       # next action: read a literal-run token
    t = 0
    if n and src[0] > 17:
        ip = 1
        t = src[0] - 17
        if t < 4:
            copy_literals(t)
            t = byte()
            mode = "match"      # token after short run is a match token
        else:
            copy_literals(t)
            t = byte()
            mode = "first"      # first_literal_run semantics

    while True:
        if mode == "loop":
            t = byte()
            if t < 16:
                if t == 0:
                    t = extended(0, 15)
                copy_literals(t + 3)
                t = byte()
                mode = "first"
                continue
            mode = "match"
            continue

        if mode == "first":
            # token right after a literal run: t < 16 is the special
            # 3-byte M1 match with the M2-offset bias
            if t < 16:
                m_pos = len(out) - (1 + 0x800) - (t >> 2) - (byte() << 2)
                copy_match(m_pos, 3)
                state = src[ip - 2] & 3
                mode = "done"
                continue
            mode = "match"
            continue

        if mode == "match":
            if t >= 64:          # M2: 3..8 byte match, 1-byte distance
                m_pos = len(out) - 1 - ((t >> 2) & 7) - (byte() << 3)
                count = (t >> 5) - 1 + 2
                copy_match(m_pos, count)
                state = src[ip - 2] & 3
            elif t >= 32:        # M3: distance <= 0x4000, 2-byte LE field
                t &= 31
                if t == 0:
                    t = extended(0, 31)
                d0, d1 = byte(), byte()
                m_pos = len(out) - 1 - ((d0 >> 2) + (d1 << 6))
                copy_match(m_pos, t + 2)
                state = d0 & 3
            elif t >= 16:        # M4: distance 0x4000..0xBFFF, or EOS
                m_base = len(out) - ((t & 8) << 11)
                t &= 7
                if t == 0:
                    t = extended(0, 7)
                d0, d1 = byte(), byte()
                m_pos = m_base - ((d0 >> 2) + (d1 << 6))
                if m_pos == len(out):
                    if t != 1:
                        raise CompressionError("malformed LZO end marker")
                    break        # end of stream
                copy_match(m_pos - 0x4000, t + 2)
                state = d0 & 3
            else:                # M1 inside the match loop: 2-byte match
                m_pos = len(out) - 1 - (t >> 2) - (byte() << 2)
                copy_match(m_pos, 2)
                state = src[ip - 2] & 3
            mode = "done"
            continue

        # mode == "done": state = trailing literal count from the match
        # token's low 2 bits
        if state == 0:
            mode = "loop"
        else:
            copy_literals(state)
            t = byte()
            mode = "match"

    if ip != n:
        raise CompressionError(
            f"{n - ip} trailing bytes after LZO end marker")
    if len(out) != expected_len:
        raise CompressionError(
            f"LZO length mismatch: {len(out)} != {expected_len}")
    return bytes(out)


# --------------------------------------------------------------------------
# native liblzo2 via dlopen (the reference's loading strategy)
# --------------------------------------------------------------------------

_lzo_lock = threading.Lock()
_lzo_lib = None
_lzo_missing = False  # negative probe cached: find_library spawns
                      # ldconfig — never re-probe per shuffle block
_LZO1X_1_MEM_COMPRESS = 16384 * 8  # lzo_uint is 64-bit on lp64


def _load_lzo2():
    """dlopen/dlsym liblzo2 and run __lzo_init_v2, like the reference
    (LzoDecompressor.cc:83-127); raises CompressionError if absent."""
    global _lzo_lib, _lzo_missing
    with _lzo_lock:
        if _lzo_lib is not None:
            return _lzo_lib
        if _lzo_missing:
            raise CompressionError("liblzo2.so not found")
        path = ctypes.util.find_library("lzo2")
        if not path:
            _lzo_missing = True
            raise CompressionError("liblzo2.so not found")
        lib = ctypes.CDLL(path)
        init = lib.__lzo_init_v2
        init.restype = ctypes.c_int
        # (version, sizeof(short), sizeof(int), sizeof(long),
        #  sizeof(lzo_uint32), sizeof(lzo_uint), sizeof(dict), sizeof(char*),
        #  sizeof(lzo_voidp), sizeof(lzo_callback_t)); -1 skips a check
        rc = init(1, 2, 4, 8, 4, 8, -1, 8, 8, -1)
        if rc != 0:
            raise CompressionError(f"__lzo_init_v2 failed: {rc}")
        for name in ("lzo1x_decompress_safe", "lzo1x_1_compress"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
                           ctypes.POINTER(ctypes.c_size_t), ctypes.c_void_p]
        _lzo_lib = lib
        return lib


def _load_builtin():
    """The in-tree C++ LZO1X codec (uda_tpu/native/lzo.cc): same stream
    format, uda_-prefixed symbols. liblzo2 being optional in the image
    is a runtime condition the reference also had — the builtin makes
    the NATIVE path testable everywhere (VERDICT r4 missing #5)."""
    from uda_tpu import native as native_mod
    from uda_tpu.utils.ifile import native_enabled

    if not native_enabled() or not native_mod.build():
        raise CompressionError("builtin native LZO unavailable "
                               "(native library not built)")
    return native_mod._load()


def native_lzo_source() -> str:
    """Which native LZO implementation serves: "liblzo2" (the
    reference's dlopen target), "builtin" (uda_tpu/native/lzo.cc), or
    "" (pure Python only)."""
    try:
        _load_lzo2()
        return "liblzo2"
    except CompressionError:
        pass
    try:
        _load_builtin()
        return "builtin"
    except CompressionError:
        return ""


def native_lzo_available() -> bool:
    return bool(native_lzo_source())


def _native_compress(data: bytes) -> bytes:
    try:
        lib = _load_lzo2()
    except CompressionError:
        return _builtin_compress(data)
    out = ctypes.create_string_buffer(len(data) + len(data) // 16 + 67)
    out_len = ctypes.c_size_t(len(out))
    wrk = ctypes.create_string_buffer(_LZO1X_1_MEM_COMPRESS)
    rc = lib.lzo1x_1_compress(data, len(data), out, ctypes.byref(out_len),
                              wrk)
    if rc != 0:
        raise CompressionError(f"lzo1x_1_compress failed: {rc}")
    return out.raw[: out_len.value]


def _native_decompress(data: bytes, uncompressed_len: int) -> bytes:
    try:
        lib = _load_lzo2()
    except CompressionError:
        return _builtin_decompress(data, uncompressed_len)
    out = ctypes.create_string_buffer(max(uncompressed_len, 1))
    out_len = ctypes.c_size_t(uncompressed_len)
    rc = lib.lzo1x_decompress_safe(data, len(data), out,
                                   ctypes.byref(out_len), None)
    if rc != 0:
        raise CompressionError(f"lzo1x_decompress_safe failed: {rc}")
    if out_len.value != uncompressed_len:
        raise CompressionError(
            f"lzo length mismatch: {out_len.value} != {uncompressed_len}")
    return out.raw[: out_len.value]


def _builtin_compress(data: bytes) -> bytes:
    lib = _load_builtin()
    cap = len(data) + len(data) // 16 + 67 + 3
    out = ctypes.create_string_buffer(cap)
    out_len = ctypes.c_size_t(cap)
    rc = lib.uda_lzo1x_1_compress(
        ctypes.cast(ctypes.c_char_p(data),
                    ctypes.POINTER(ctypes.c_uint8)), len(data),
        ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
        ctypes.byref(out_len))
    if rc != 0:
        raise CompressionError(f"builtin lzo compress failed: {rc}")
    return out.raw[: out_len.value]


def _builtin_decompress(data: bytes, uncompressed_len: int) -> bytes:
    lib = _load_builtin()
    out = ctypes.create_string_buffer(max(uncompressed_len, 1))
    out_len = ctypes.c_size_t(uncompressed_len)
    rc = lib.uda_lzo1x_decompress_safe(
        ctypes.cast(ctypes.c_char_p(data),
                    ctypes.POINTER(ctypes.c_uint8)), len(data),
        ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
        ctypes.byref(out_len))
    if rc != 0:
        raise CompressionError(f"builtin lzo decompress failed: {rc}")
    if out_len.value != uncompressed_len:
        raise CompressionError(
            f"lzo length mismatch: {out_len.value} != {uncompressed_len}")
    return out.raw[: out_len.value]


def lzo_codec():
    """Codec factory: native liblzo2 when loadable, else the in-tree
    C++ codec, else the pure-Python LZO1X implementation (same stream
    format in all three). The implementation pair is bound ONCE here —
    per-block calls never re-probe for liblzo2."""
    from uda_tpu.compress import Codec

    source = native_lzo_source()
    if source == "liblzo2":
        return Codec("lzo", _native_compress, _native_decompress)
    if source == "builtin":
        return Codec("lzo", _builtin_compress, _builtin_decompress)
    return Codec("lzo",
                 lzo1x_compress_py,
                 lambda data, length: lzo1x_decompress_py(data, length))
