"""Compression path: block codecs + decompressing fetch client.

Equivalent of the reference's decompression input clients (reference
src/Merger/DecompressorWrapper.cc, LzoDecompressor.cc,
SnappyDecompressor.cc): map outputs may be block-compressed; the fetch
path pulls *compressed* bytes and decompresses on the fly in front of
the merge, behind the same InputClient interface the plain transport
implements (DecompressorWrapper.cc:80-114). Codec shared objects are
loaded at runtime with dlopen/dlsym exactly like the reference
(LzoDecompressor.cc:83-127 ``liblzo2.so``; SnappyDecompressor.cc:42-51
``libsnappy.so``), and gated on availability; zlib (Hadoop's
DefaultCodec) is always available through Python's zlib.

Block framing: each block is ``[4B BE uncompressed_len][4B BE
compressed_len][compressed bytes]`` — the (compressedLen,
uncompressedLen) block-header shape the reference's ``doDecompress``
consumes (DecompressorWrapper.cc:168-197). A segment's ``raw_length``
(index) is the total uncompressed size, ``part_length`` the on-disk
compressed size, matching Hadoop's spill index semantics.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import struct
import threading
import zlib
from typing import Callable, Dict, Optional

from uda_tpu.merger.segment import InputClient
from uda_tpu.mofserver.data_engine import FetchResult, ShuffleRequest
from uda_tpu.utils.errors import CompressionError, StorageError
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["Codec", "get_codec", "register_codec", "compress_block_stream",
           "decompress_block_stream", "DecompressingClient",
           "BLOCK_HEADER"]

log = get_logger()

BLOCK_HEADER = struct.Struct(">II")  # (uncompressed_len, compressed_len)


class Codec:
    def __init__(self, name: str,
                 compress: Callable[[bytes], bytes],
                 decompress: Callable[[bytes, int], bytes]):
        self.name = name
        self.compress = compress
        self.decompress = decompress  # (data, uncompressed_len) -> bytes


def _zlib_codec() -> Codec:
    def decompress(data: bytes, uncompressed_len: int) -> bytes:
        out = zlib.decompress(data)
        # enforce the block header's length claim like the snappy codec
        # does — a corrupt header must fail at the block, not surface
        # later as a confusing record-framing error
        if len(out) != uncompressed_len:
            raise CompressionError(
                f"zlib length mismatch: {len(out)} != {uncompressed_len}")
        return out

    return Codec("zlib", lambda b: zlib.compress(b, 6), decompress)


_snappy_lock = threading.Lock()
_snappy_lib = None


def _load_snappy():
    """dlopen/dlsym libsnappy like the reference (SnappyDecompressor.cc:
    42-51); raises CompressionError when the library is absent."""
    global _snappy_lib
    with _snappy_lock:
        if _snappy_lib is not None:
            return _snappy_lib
        path = ctypes.util.find_library("snappy")
        if not path:
            raise CompressionError("libsnappy.so not found")
        lib = ctypes.CDLL(path)
        lib.snappy_compress.restype = ctypes.c_int
        lib.snappy_compress.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                        ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_size_t)]
        lib.snappy_uncompress.restype = ctypes.c_int
        lib.snappy_uncompress.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                          ctypes.c_char_p,
                                          ctypes.POINTER(ctypes.c_size_t)]
        lib.snappy_max_compressed_length.restype = ctypes.c_size_t
        lib.snappy_max_compressed_length.argtypes = [ctypes.c_size_t]
        _snappy_lib = lib
        return lib


def _snappy_codec() -> Codec:
    lib = _load_snappy()

    def compress(data: bytes) -> bytes:
        out_len = ctypes.c_size_t(lib.snappy_max_compressed_length(len(data)))
        out = ctypes.create_string_buffer(out_len.value)
        rc = lib.snappy_compress(data, len(data), out, ctypes.byref(out_len))
        if rc != 0:
            raise CompressionError(f"snappy_compress failed: {rc}")
        return out.raw[: out_len.value]

    def decompress(data: bytes, uncompressed_len: int) -> bytes:
        out_len = ctypes.c_size_t(uncompressed_len)
        out = ctypes.create_string_buffer(max(uncompressed_len, 1))
        rc = lib.snappy_uncompress(data, len(data), out, ctypes.byref(out_len))
        if rc != 0:
            raise CompressionError(f"snappy_uncompress failed: {rc}")
        if out_len.value != uncompressed_len:
            raise CompressionError(
                f"snappy length mismatch: {out_len.value} != {uncompressed_len}")
        return out.raw[: out_len.value]

    return Codec("snappy", compress, decompress)


def _lzo_codec() -> Codec:
    from uda_tpu.compress.lzo import lzo_codec

    return lzo_codec()


# codec class-name registry: the createInputClient dispatch of reference
# reducer.cc:412-450 (Lzo/Snappy by Java class name; Default = zlib)
_REGISTRY: Dict[str, Callable[[], Codec]] = {
    "org.apache.hadoop.io.compress.DefaultCodec": _zlib_codec,
    "zlib": _zlib_codec,
    "org.apache.hadoop.io.compress.SnappyCodec": _snappy_codec,
    "snappy": _snappy_codec,
    "com.hadoop.compression.lzo.LzoCodec": _lzo_codec,
    "com.hadoop.compression.lzo.LzopCodec": _lzo_codec,
    "lzo": _lzo_codec,
}


def register_codec(class_name: str, factory: Callable[[], Codec]) -> None:
    _REGISTRY[class_name] = factory


def get_codec(class_name: str) -> Codec:
    factory = _REGISTRY.get(class_name)
    if factory is None:
        raise CompressionError(
            f"unsupported codec class for native merge: {class_name}")
    return factory()


def compress_block_stream(data: bytes, codec: Codec,
                          block_size: int = 256 * 1024) -> bytes:
    """Frame ``data`` as compressed blocks (see module docstring)."""
    out = bytearray()
    for start in range(0, len(data), block_size):
        raw = data[start:start + block_size]
        comp = codec.compress(raw)
        out += BLOCK_HEADER.pack(len(raw), len(comp))
        out += comp
    return bytes(out)


def decompress_block_stream(data: bytes, codec: Codec) -> bytes:
    """Inverse of compress_block_stream (whole-buffer convenience)."""
    out = bytearray()
    pos = 0
    while pos < len(data):
        if pos + BLOCK_HEADER.size > len(data):
            raise CompressionError("truncated block header")
        raw_len, comp_len = BLOCK_HEADER.unpack_from(data, pos)
        pos += BLOCK_HEADER.size
        if pos + comp_len > len(data):
            raise CompressionError("truncated block body")
        out += codec.decompress(bytes(data[pos:pos + comp_len]), raw_len)
        pos += comp_len
    return bytes(out)


class _StreamState:
    """Sequential decompression state for one partition fetch.

    ``mu`` serializes attempt issue and chunk ingest per stream;
    ``token`` identifies the stream's CURRENT fetch attempt, so a
    completion from a superseded attempt (the segment's per-attempt
    timeout fired and it re-issued) can never mutate state the new
    attempt depends on."""

    __slots__ = ("comp_offset", "carry", "delivered", "part_length",
                 "mu", "token")

    def __init__(self) -> None:
        self.comp_offset = 0
        self.carry = b""
        self.delivered = 0
        self.part_length: Optional[int] = None
        self.mu = threading.Lock()
        self.token: Optional[object] = None


class DecompressingClient(InputClient):
    """Wraps a transport, decompressing block streams on the fly —
    the DecompressorWrapper contract (same InputClient interface in
    front of the merge, compressed bytes on the wire).

    Segments fetch sequentially from offset 0; requests carry
    *uncompressed-domain* offsets while the inner fetches advance in the
    compressed domain; a partial trailing block is carried to the next
    chunk (the reference's handleNextRdmaFetch memmove of the partial
    block tail, DecompressorWrapper.cc:199-235).
    """

    def __init__(self, inner: InputClient, codec: Codec,
                 comp_chunk_size: Optional[int] = None):
        """``comp_chunk_size``: size of the compressed-domain inner
        fetches — the `ratio` share of each buffer pair that the
        reference dedicates to wire-compressed bytes (calculateMemPool,
        reducer.cc:453-496, conf mapred.rdma.compression.buffer.ratio).
        Defaults to the caller's uncompressed chunk size."""
        self.inner = inner
        self.codec = codec
        self.comp_chunk_size = comp_chunk_size
        self._streams: dict[tuple, _StreamState] = {}
        self._lock = threading.Lock()

    def estimate_partition_bytes(self, job_id: str, map_ids,
                                 reduce_id: int):
        """Forward to the wrapped transport: its estimate sums the
        spill index's raw_length (uncompressed record bytes), which is
        the domain this client delivers in — so the auto merge-approach
        policy sees real sizes for compressed jobs too."""
        return self.inner.estimate_partition_bytes(job_id, map_ids,
                                                   reduce_id)

    def resume_ok(self, host: str = "") -> bool:
        """Never resumable: an inner transport error pops the stream
        state (clean slate), so a mid-partition continuation would hit
        the non-sequential guard — the whole-segment restart IS this
        wrapper's recovery contract."""
        return False

    def speculate_ok(self) -> bool:
        """Never duplicate-safe: start_fetch claims the partition's
        sequential stream token, so a concurrent duplicate for the
        same (job, map, reduce) would steal it and fail the healthy
        attempt's completion as stale — fabricating a fault against a
        supplier that was merely slow."""
        return False

    def recover_partition(self, req, ctx, on_complete) -> bool:
        """k-of-n reconstruction BELOW the decompression (the stripe
        codes the on-disk/compressed bytes — uda_tpu.coding's
        byte-agnostic contract): delegate to the inner transport and
        decompress the rebuilt partition on the way up, delivering the
        same uncompressed domain a fetched stream would."""
        def _done(res) -> None:
            if not isinstance(res, Exception):
                try:
                    out = decompress_block_stream(bytes(res.data),
                                                  self.codec)
                    metrics.add("decompress.bytes", len(out))
                    res = FetchResult(out, len(out), res.part_length,
                                      0, res.path, last=True)
                except Exception as e:  # noqa: BLE001 - a corrupt
                    # reconstruction must surface as the segment's
                    # terminal error, not crash the recovery thread
                    res = e
            on_complete(res)

        return self.inner.recover_partition(req, ctx, _done)

    def start_fetch(self, req: ShuffleRequest, on_complete) -> None:
        key = (req.job_id, req.map_id, req.reduce_id)
        tok = object()
        with self._lock:
            st = self._streams.get(key)
            # new stream, or a restart after progress (a retrying
            # segment); NOT a continuation at offset 0 that simply
            # hasn't produced a complete block yet
            if st is None or (req.offset == 0 and st.delivered != 0):
                st = _StreamState()
                self._streams[key] = st
        with st.mu:
            # claim the stream for THIS attempt; any still-in-flight
            # older attempt's completion is now stale by token. The
            # ordering is safe either way: if that completion wins the
            # mutex first it ingests (it was still the owner) and this
            # attempt sees the advanced state below; if this claim wins,
            # the old completion is dropped without touching the state.
            st.token = tok
            err = None
            if req.offset != st.delivered:
                err = CompressionError(
                    f"non-sequential compressed fetch at {req.offset} "
                    f"(expected {st.delivered})")
            comp_offset = st.comp_offset
        if err is not None:
            on_complete(err)  # outside st.mu: the segment may re-issue
            return            # from this callback (same thread)
        inner_req = ShuffleRequest(req.job_id, req.map_id, req.reduce_id,
                                   comp_offset,
                                   self.comp_chunk_size or req.chunk_size,
                                   host=req.host)

        def _done(res) -> None:
            # decide + mutate under st.mu, deliver after releasing it
            # (the segment chains its next fetch from this callback on
            # the same thread — holding st.mu across it would deadlock)
            with st.mu:
                with self._lock:
                    stale = (st.token is not tok
                             or self._streams.get(key) is not st)
                if stale:
                    # a superseded attempt must neither mutate nor pop
                    # the current owner's state; the segment's epoch
                    # guard drops this delivery as stale
                    res = CompressionError(
                        "stale compressed fetch completion "
                        "(attempt superseded)")
                elif isinstance(res, Exception):
                    with self._lock:
                        self._streams.pop(key, None)  # clean slate
                else:
                    crc = getattr(res, "crc", None)
                    if crc is not None and \
                            zlib.crc32(res.data) & 0xFFFFFFFF != crc:
                        # wire-domain integrity (uda.tpu.fetch.crc): the
                        # CRC covers the COMPRESSED chunk, so it must be
                        # validated here, not on the decompressed result;
                        # the segment recovers via whole-segment retry,
                        # which resets this stream cleanly
                        with self._lock:
                            self._streams.pop(key, None)
                        res = StorageError(
                            f"compressed chunk CRC mismatch at "
                            f"{req.map_id}:{res.offset}")
                    else:
                        try:
                            res = self._ingest(key, st, req, res)
                        except Exception as e:  # noqa: BLE001 - to segment
                            with self._lock:
                                self._streams.pop(key, None)
                            res = e
            on_complete(res)

        self.inner.start_fetch(inner_req, _done)

    def _ingest(self, key, st: _StreamState, req: ShuffleRequest,
                res: FetchResult) -> FetchResult:
        st.part_length = res.part_length
        st.comp_offset = res.offset + len(res.data)
        data = st.carry + res.data
        out = bytearray()
        pos = 0
        while pos + BLOCK_HEADER.size <= len(data):
            raw_len, comp_len = BLOCK_HEADER.unpack_from(data, pos)
            if pos + BLOCK_HEADER.size + comp_len > len(data):
                break
            body = bytes(data[pos + BLOCK_HEADER.size:
                              pos + BLOCK_HEADER.size + comp_len])
            # injectable per decoded block (keyed "<map>@<wire offset>"):
            # a decompress fault mid-pipeline must surface as this
            # stream's terminal error and drain the stage pool cleanly
            failpoint("decompress.block",
                      key=f"{req.map_id}@{res.offset}")
            out += self.codec.decompress(body, raw_len)
            pos += BLOCK_HEADER.size + comp_len
        st.carry = bytes(data[pos:])
        if out:
            metrics.add("decompress.bytes", len(out))
        comp_done = st.comp_offset >= (st.part_length or 0)
        if comp_done and st.carry:
            raise CompressionError(
                f"{len(st.carry)} trailing bytes after last block")
        offset = st.delivered
        st.delivered += len(out)
        # uncompressed raw_length: exact once the compressed stream ends,
        # otherwise "more than delivered" so is_last stays False
        raw_length = st.delivered if comp_done else st.delivered + 1
        if comp_done:
            with self._lock:
                self._streams.pop(key, None)
        return FetchResult(bytes(out), raw_length, res.part_length,
                           offset, res.path, last=comp_done)

    def stop(self) -> None:
        self.inner.stop()
