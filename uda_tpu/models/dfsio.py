"""DFSIO: storage/serving throughput gate (the TestDFSIO analogue).

The reference ladder runs Hadoop's TestDFSIO (reference
scripts/regression/namesConf.sh:20-35) to gate the durable tier's
write/read MB/s independently of shuffle logic. This framework's
durable tier is the MOF layout (IFile segments + spill index) served
by the DataEngine chunk path, so the analogue measures:

- write: MOFWriter streaming ``num_files`` map outputs to disk
  (IFile framing + index triples);
- read: the full serving stack — DirIndexResolver, refcounted fd
  cache, chunked ShuffleRequest/FetchResult loop, and the native
  ReadPool when ``libuda_tpu_native.so`` is built (reference
  src/MOFServer/AIOHandler.cc's role).

Validity is byte-exact: every fetched partition is re-parsed with
IFileReader and compared record-for-record against what was written.
"""

from __future__ import annotations

import io
import os
import struct
import tempfile
import time
from typing import Optional

from uda_tpu.mofserver import DataEngine, DirIndexResolver
from uda_tpu.mofserver.data_engine import ShuffleRequest
from uda_tpu.mofserver.writer import MOFWriter
from uda_tpu.utils.config import Config
from uda_tpu.utils.ifile import IFileReader

__all__ = ["run_dfsio"]


def _records(file_idx: int, total_bytes: int, value_bytes: int):
    """Deterministic fixed-stride records summing to ~total_bytes."""
    n = max(1, total_bytes // (value_bytes + 12))
    for i in range(n):
        # value: cheap deterministic fill, unique per (file, record)
        seed = (file_idx * 1_000_003 + i) & 0xFFFFFFFF
        yield (b"%010d" % i,
               struct.pack(">I", seed) * (value_bytes // 4))


def run_dfsio(num_files: int = 4, bytes_per_file: int = 1 << 20,
              chunk_size: int = 1 << 16, value_bytes: int = 4096,
              config: Optional[Config] = None,
              work_dir: Optional[str] = None) -> dict:
    """Write ``num_files`` single-partition MOFs then read them back
    through the chunked serving path. Returns throughput + validity
    stats: {"write_mb_s", "read_mb_s", "bytes", "files", "chunks"}."""
    own_dir = work_dir is None
    root = work_dir or tempfile.mkdtemp(prefix="uda_dfsio_")
    try:
        return _run(root, num_files, bytes_per_file, chunk_size,
                    value_bytes, config)
    finally:
        if own_dir:
            import shutil
            shutil.rmtree(root, ignore_errors=True)


def _run(root: str, num_files: int, bytes_per_file: int, chunk_size: int,
         value_bytes: int, config: Optional[Config]) -> dict:
    job = "dfsio"
    writer = MOFWriter(root, job)

    t0 = time.perf_counter()
    for f in range(num_files):
        writer.write(f"attempt_dfsio_m_{f:06d}_0",
                     [_records(f, bytes_per_file, value_bytes)])
    write_s = time.perf_counter() - t0

    total = sum(
        os.path.getsize(os.path.join(writer.map_dir(m), "file.out"))
        for m in writer.map_ids)

    engine = DataEngine(DirIndexResolver(root), config)
    chunks = 0
    t0 = time.perf_counter()
    fetched: dict[str, bytes] = {}
    try:
        for m in writer.map_ids:
            buf = io.BytesIO()
            offset = 0
            while True:
                res = engine.fetch(ShuffleRequest(job, m, 0, offset,
                                                  chunk_size))
                buf.write(res.data)
                offset += len(res.data)
                chunks += 1
                if res.is_last:
                    break
            fetched[m] = buf.getvalue()
    finally:
        engine.stop()
    read_s = time.perf_counter() - t0

    # validity: byte-exact record round trip per file
    for f, m in enumerate(writer.map_ids):
        got = list(IFileReader(io.BytesIO(fetched[m])))
        want = list(_records(f, bytes_per_file, value_bytes))
        if got != want:
            raise AssertionError(f"DFSIO round-trip mismatch in {m}: "
                                 f"{len(got)} vs {len(want)} records")

    mb = total / 1e6
    return {"write_mb_s": round(mb / write_s, 2),
            "read_mb_s": round(mb / read_s, 2),
            "bytes": total, "files": num_files, "chunks": chunks}
