"""SecondarySort (BASELINE config 3): composite keys on the device sort.

Hadoop's secondary-sort pattern: the key is (primary, secondary); the
partitioner and grouping use only the primary, while the comparator
orders by the full composite — so each reduce group sees its values in
secondary order. Exercises exactly the RawComparator machinery the
reference dispatches per key class (reference src/Merger/CompareFunc.cc)
with a key type the reference does NOT support natively — demonstrating
the registry extension point (register_key_type).
"""

from __future__ import annotations

import struct
from typing import Iterable, Optional

import numpy as np

from uda_tpu.models.pipeline import MapReduceJob, Record
from uda_tpu.utils.comparators import KeyType, register_key_type
from uda_tpu.utils.config import Config

__all__ = ["composite_key", "split_key", "run_secondary_sort"]

# composite key: 8-byte big-endian primary | 4-byte big-endian secondary;
# memcmp over the 12 bytes == (primary, secondary) lexicographic order
KEY_CLASS = "uda.tpu.examples.CompositeKey"
register_key_type(KEY_CLASS, KeyType("composite", lambda b: bytes(b),
                                     fixed_width=12))


def composite_key(primary: int, secondary: int) -> bytes:
    return struct.pack(">QI", primary, secondary)


def split_key(key: bytes) -> tuple[int, int]:
    return struct.unpack(">QI", key)


def _partitioner(key: bytes, num_reducers: int) -> int:
    primary, _ = split_key(key)
    return primary % num_reducers


def _mapper(split) -> Iterable[Record]:
    for primary, secondary, payload in split:
        yield composite_key(primary, secondary), payload


def _identity_reducer(key: bytes, values: list[bytes]) -> Iterable[Record]:
    for v in values:
        yield key, v


def run_secondary_sort(num_groups: int = 20, per_group: int = 50,
                       num_maps: int = 4, num_reducers: int = 2,
                       seed: int = 0, config: Optional[Config] = None,
                       work_dir: Optional[str] = None):
    """Generate (primary, secondary, payload) tuples, run the job, and
    return per-reducer outputs. Validity: within each reducer's stream,
    records group by primary and each group's secondaries ascend."""
    rng = np.random.default_rng(seed)
    rows = [(int(rng.integers(0, num_groups)), int(rng.integers(0, 2**31)),
             rng.bytes(8)) for _ in range(num_groups * per_group)]
    splits = [rows[i::num_maps] for i in range(num_maps)]
    job = MapReduceJob("secsort", _mapper, _identity_reducer,
                       key_type=KEY_CLASS, num_reducers=num_reducers,
                       partitioner=_partitioner, config=config,
                       work_dir=work_dir)
    outputs = job.run(splits)
    # validity gate
    for r, recs in outputs.items():
        keys = [split_key(k) for k, _ in recs]
        assert keys == sorted(keys), f"reducer {r}: composite order broken"
        for primary, _ in keys:
            assert primary % num_reducers == r, "partitioner violated"
    return outputs
