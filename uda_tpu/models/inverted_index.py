"""InvertedIndex (BASELINE config 4a): skewed reduce partitions.

Builds term -> sorted posting lists. Term frequencies are Zipfian, so a
handful of reducers receive most of the data — the skew case the
reference handled with its backlog/credit machinery (reference
src/DataNet/RDMAComm.cc:707-752) and that the TPU exchange handles with
multi-round windowing (uda_tpu.parallel.exchange).
"""

from __future__ import annotations

import struct
from typing import Iterable, Optional

import numpy as np

from uda_tpu.models.pipeline import MapReduceJob, Record
from uda_tpu.models.wordcount import parse_text_key, text_key
from uda_tpu.utils.config import Config

__all__ = ["run_inverted_index", "zipf_corpus"]


def zipf_corpus(num_docs: int, words_per_doc: int, vocab: int = 1000,
                a: float = 1.5, seed: int = 0) -> list[tuple[int, list[bytes]]]:
    """Synthetic Zipf-distributed corpus: [(doc_id, [terms...])]."""
    rng = np.random.default_rng(seed)
    docs = []
    for d in range(num_docs):
        ids = np.minimum(rng.zipf(a, size=words_per_doc), vocab) - 1
        docs.append((d, [b"term%05d" % i for i in ids]))
    return docs


def _mapper(split) -> Iterable[Record]:
    for doc_id, terms in split:
        for pos, term in enumerate(terms):
            yield text_key(term), struct.pack(">II", doc_id, pos)


def _reducer(key: bytes, values: list[bytes]) -> Iterable[Record]:
    postings = sorted(struct.unpack(">II", v) for v in values)
    yield key, b"".join(struct.pack(">II", d, p) for d, p in postings)


def run_inverted_index(num_docs: int = 40, words_per_doc: int = 100,
                       num_maps: int = 4, num_reducers: int = 4,
                       seed: int = 0, config: Optional[Config] = None,
                       work_dir: Optional[str] = None
                       ) -> dict[bytes, list[tuple[int, int]]]:
    """Build the index; returns {term: [(doc, pos)...]} with each posting
    list sorted. Validity is checked against a direct computation."""
    corpus = zipf_corpus(num_docs, words_per_doc, seed=seed)
    splits = [corpus[i::num_maps] for i in range(num_maps)]
    job = MapReduceJob("invidx", _mapper, _reducer,
                       key_type="org.apache.hadoop.io.Text",
                       num_reducers=num_reducers, config=config,
                       work_dir=work_dir)
    outputs = job.run(splits)
    index: dict[bytes, list[tuple[int, int]]] = {}
    for recs in outputs.values():
        for k, v in recs:
            postings = [struct.unpack_from(">II", v, i)
                        for i in range(0, len(v), 8)]
            index[parse_text_key(k)] = postings
    # validity: recompute directly
    want: dict[bytes, list[tuple[int, int]]] = {}
    for doc_id, terms in corpus:
        for pos, term in enumerate(terms):
            want.setdefault(term, []).append((doc_id, pos))
    for term, postings in want.items():
        assert index.get(term) == sorted(postings), f"bad postings for {term!r}"
    return index
