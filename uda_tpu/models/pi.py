"""Pi estimator: the ladder's map-compute workload.

The reference regression ladder runs Hadoop's "pi" example (reference
scripts/regression/namesConf.sh:20-35) — QuasiMonteCarlo: each mapper
samples Halton-sequence points in the unit square and counts hits
inside the inscribed quarter circle; one reducer sums the counts. The
shuffle is tiny (two keys), so the workload gates the control path —
job bring-up, map fan-out, grouped reduce — rather than the data
plane, exactly the role it played in the reference suite.

Keys are BooleanWritable (inside/outside), values LongWritable counts,
matching the Hadoop example's writable types.
"""

from __future__ import annotations

import struct
from typing import Iterable, Optional, Tuple

from uda_tpu.models.pipeline import MapReduceJob, Record
from uda_tpu.utils.config import Config

__all__ = ["halton", "run_pi"]


def halton(index: int, base: int) -> float:
    """The radical-inverse (Halton) low-discrepancy sequence — the same
    generator Hadoop's QuasiMonteCarlo uses for reproducible sampling."""
    f, r = 1.0, 0.0
    i = index
    while i > 0:
        f /= base
        r += f * (i % base)
        i //= base
    return r


def _mapper(split: Tuple[int, int]) -> Iterable[Record]:
    offset, count = split
    inside = 0
    for i in range(offset, offset + count):
        x = halton(i + 1, 2) - 0.5
        y = halton(i + 1, 3) - 0.5
        if x * x + y * y <= 0.25:
            inside += 1
    yield b"\x01", struct.pack(">q", inside)
    yield b"\x00", struct.pack(">q", count - inside)


def _reducer(key: bytes, values: list[bytes]) -> Iterable[Record]:
    total = sum(struct.unpack(">q", v)[0] for v in values)
    yield key, struct.pack(">q", total)


def run_pi(num_maps: int = 4, points_per_map: int = 2000,
           config: Optional[Config] = None,
           work_dir: Optional[str] = None) -> dict:
    """Estimate pi with ``num_maps`` mappers x ``points_per_map`` Halton
    points. Returns {"estimate", "inside", "outside", "points"}; exact
    point conservation is asserted (a lost or duplicated map output
    would break it)."""
    splits = [(m * points_per_map, points_per_map) for m in range(num_maps)]
    job = MapReduceJob("pi", _mapper, _reducer,
                       key_type="org.apache.hadoop.io.BooleanWritable",
                       num_reducers=1, config=config, work_dir=work_dir)
    outputs = job.run(splits)
    counts = {k: struct.unpack(">q", v)[0] for k, v in outputs[0]}
    inside = counts.get(b"\x01", 0)
    outside = counts.get(b"\x00", 0)
    points = num_maps * points_per_map
    if inside + outside != points:
        raise AssertionError(
            f"point count not conserved: {inside}+{outside} != {points}")
    return {"estimate": 4.0 * inside / points, "inside": inside,
            "outside": outside, "points": points}
