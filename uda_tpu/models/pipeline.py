"""MapReduce pipeline driver: the workload harness over the framework.

Runs a complete map -> shuffle -> merge -> reduce job through the same
components a Hadoop deployment would use (MOFWriter supplier layout,
DataEngine chunk serving, MergeManager device merge, framed emission),
so every workload in uda_tpu.models is an end-to-end exercise of the
engine — the role the reference's cluster regression workloads played
(reference scripts/regression/namesConf.sh:20-35: TeraSort, sort,
wordcount, TestDFSIO, pi).

The reduce side consumes the merged stream through ``grouped_reduce``,
which implements Hadoop's grouping contract: consecutive equal keys form
one reduce call (valid because the merged stream is comparator-sorted).
"""

from __future__ import annotations

import functools
import io
import os
import tempfile
from typing import Callable, Iterable, Iterator, Optional, Sequence, Tuple

from uda_tpu.merger import LocalFetchClient, MergeManager
from uda_tpu.mofserver import DataEngine, DirIndexResolver
from uda_tpu.mofserver.writer import MOFWriter
from uda_tpu.utils.comparators import KeyType, get_key_type
from uda_tpu.utils.config import Config
from uda_tpu.utils.ifile import IFileReader
from uda_tpu.utils.metrics import metrics

__all__ = ["MapReduceJob", "grouped_reduce", "hash_partitioner"]

Record = Tuple[bytes, bytes]


def hash_partitioner(key: bytes, num_reducers: int) -> int:
    """Default partitioner (Hadoop HashPartitioner shape)."""
    import zlib
    return zlib.crc32(key) % num_reducers


def grouped_reduce(records: Iterable[Record],
                   reducer: Callable[[bytes, list[bytes]], Iterable[Record]],
                   key_content: Callable[[bytes], bytes] = lambda k: k
                   ) -> Iterator[Record]:
    """Group consecutive equal keys (by comparator content) and apply the
    reducer — Hadoop's reduce() contract over a sorted stream."""
    cur_key: Optional[bytes] = None
    cur_content: Optional[bytes] = None
    values: list[bytes] = []
    for k, v in records:
        c = key_content(k)
        if cur_content is not None and c != cur_content:
            yield from reducer(cur_key, values)
            values = []
        cur_key, cur_content = k, c
        values.append(v)
    if cur_content is not None:
        yield from reducer(cur_key, values)


class MapReduceJob:
    """One job: map inputs to records, shuffle+merge, reduce.

    ``mapper(input) -> iterable of (key, value)`` serialized records;
    ``reducer(key, values) -> iterable of (key, value)`` outputs.
    """

    def __init__(self, job_id: str,
                 mapper: Callable[[object], Iterable[Record]],
                 reducer: Callable[[bytes, list[bytes]], Iterable[Record]],
                 key_type: KeyType | str = "uda.tpu.RawBytes",
                 num_reducers: int = 2,
                 partitioner: Callable[[bytes, int], int] = hash_partitioner,
                 config: Optional[Config] = None,
                 work_dir: Optional[str] = None,
                 supplier_roots: Optional[Sequence[str]] = None):
        self.job_id = job_id
        self.mapper = mapper
        self.reducer = reducer
        self.key_type = (get_key_type(key_type) if isinstance(key_type, str)
                         else key_type)
        self.num_reducers = num_reducers
        self.partitioner = partitioner
        self.cfg = config or Config()
        self.work_dir = work_dir or tempfile.mkdtemp(prefix=f"uda_{job_id}_")
        # erasure-coded deployments: the job's supplier roots
        # (write_striped_map_output fans stripe chunks across them);
        # default = the single work_dir (parity section only, no
        # fan-out). Placement is derived INDEPENDENTLY by writer and
        # reducer from the canonical order — "sorted unique" is that
        # order (uda_tpu.coding), so the list is canonicalized here:
        # an arbitrary caller order would place shards where the
        # reduce-side stripe_host never looks, failing exactly at the
        # k-th-loss reconstruction this layout exists for. The reduce
        # side reads work_dir, so the primary MUST be among the roots
        # — a list that omits it would silently land the full MOF
        # elsewhere and the job would merge nothing; fail loudly.
        self.supplier_roots = sorted(set(supplier_roots or []))
        if self.supplier_roots and self.work_dir not in self.supplier_roots:
            from uda_tpu.utils.errors import ConfigError

            raise ConfigError(
                f"supplier_roots must include work_dir "
                f"{self.work_dir!r} (the primary MOF root the reduce "
                f"side reads); got {sorted(supplier_roots)}")

    # -- map phase ----------------------------------------------------------

    def _codec(self):
        if not self.cfg.get("mapred.compress.map.output"):
            return None
        from uda_tpu.compress import get_codec
        return get_codec(self.cfg.get("mapred.map.output.compression.codec")
                         or "zlib")

    def run_maps(self, inputs: Sequence[object]) -> MOFWriter:
        """Run the mapper over each input split; write sorted partitioned
        MOFs (what Hadoop's map-side sort+spill produces). With
        ``uda.tpu.coding.scheme`` set the map phase writes the CODED
        layout — parity section + v2 index always, and the cross-
        supplier stripe fan-out (write_striped_map_output, failure-
        domain placement per ``uda.tpu.coding.domains``) when the job
        carries >1 supplier root — so coded jobs ride every workload's
        full map->shuffle->reduce path, not just the chaos rung."""
        from uda_tpu.coding import parse_domains, parse_scheme

        scheme = parse_scheme(str(self.cfg.get("uda.tpu.coding.scheme")))
        writer = MOFWriter(
            self.work_dir, self.job_id, codec=self._codec(),
            scheme=scheme, supplier_roots=self.supplier_roots,
            supplier_index=(self.supplier_roots.index(self.work_dir)
                            if self.supplier_roots else 0),
            domains=parse_domains(
                str(self.cfg.get("uda.tpu.coding.domains"))))
        cmp = self.key_type.compare
        sort_key = functools.cmp_to_key(cmp)
        with metrics.timer("map_phase"):
            for m, split in enumerate(inputs):
                parts: list[list[Record]] = [[] for _ in range(self.num_reducers)]
                for k, v in self.mapper(split):
                    parts[self.partitioner(k, self.num_reducers)].append((k, v))
                for p in parts:
                    p.sort(key=lambda kv: sort_key(kv[0]))
                writer.write(f"attempt_{self.job_id}_m_{m:06d}_0", parts)
        if scheme is not None:
            # low-priority insurance: kick the background stripe scrub
            # when the interval knob arms it (non-blocking, one in
            # flight per process — uda_tpu.coding.scrub)
            from uda_tpu.coding.scrub import maybe_scrub

            maybe_scrub(self.cfg, self.supplier_roots or [self.work_dir])
        return writer

    # -- reduce phase -------------------------------------------------------

    def _reduce_all(self, writer: MOFWriter,
                    make_client: Callable[[int], object]
                    ) -> dict[int, list[Record]]:
        """The per-reducer merge+reduce loop shared by every transport:
        ``make_client(r)`` builds reducer r's raw InputClient; the codec
        wrap, MergeManager run, framed-block reassembly, and grouped
        reduce are identical whichever wire the bytes crossed."""
        codec = self._codec()
        outputs: dict[int, list[Record]] = {}
        for r in range(self.num_reducers):
            client = make_client(r)
            if codec is not None:
                from uda_tpu.compress import DecompressingClient
                client = DecompressingClient(client, codec)
            mm = MergeManager(client, self.key_type, self.cfg)
            blocks: list[bytes] = []
            mm.run(self.job_id, writer.map_ids, r,
                   lambda b: blocks.append(bytes(b)))
            merged = IFileReader(io.BytesIO(b"".join(blocks)))
            with metrics.timer("reduce_phase"):
                outputs[r] = list(grouped_reduce(
                    merged, self.reducer, self.key_type.content))
        return outputs

    def run_reduces(self, writer: MOFWriter) -> dict[int, list[Record]]:
        """Shuffle+merge each partition through the engine, apply the
        reducer over the grouped sorted stream."""
        engine = DataEngine(DirIndexResolver(self.work_dir), self.cfg)
        try:
            return self._reduce_all(writer,
                                    lambda r: LocalFetchClient(engine))
        finally:
            engine.stop()

    def run_reduces_mesh(self, writer: MOFWriter, mesh,
                         axis: str = "shuffle") -> dict[int, list[Record]]:
        """Shuffle the map-output partitions ACROSS THE DEVICE MESH and
        merge per reducer — the cluster deployment shape with the mesh
        as the wire (the role the reference's RDMA fabric plays between
        MOFSupplier and NetMerger hosts): map m's outputs live on
        supplier device ``m % P``, reducer r is served on device
        ``r % P``, and the on-disk partition bytes (compressed or not)
        cross via parallel.bytes_exchange. Per-(src, dst) blob order is
        the deterministic (map, reducer) send order, so delivered blobs
        map back to their (map, reducer) pair positionally. Output is
        byte-identical to run_reduces.
        """
        from uda_tpu.mofserver.index import read_index_file
        from uda_tpu.parallel.bytes_exchange import (ExchangeFetchClient,
                                                     exchange_blobs,
                                                     exchange_group_size)

        axes = (axis,) if isinstance(axis, str) else tuple(axis)
        missing = [a for a in axes if a not in mesh.shape]
        if missing:
            raise ValueError(
                f"mesh has axes {tuple(mesh.shape)}, not {missing}; pass "
                f"axis= matching the mesh (e.g. run(..., axis="
                f"{next(iter(mesh.shape))!r}))")
        p = exchange_group_size(mesh, axis)
        blobs: list[list] = [[] for _ in range(p)]
        meta: list[list] = [[] for _ in range(p)]  # (map_id, r, raw_len)
        for m, map_id in enumerate(writer.map_ids):
            d = writer.map_dir(map_id)
            recs = read_index_file(os.path.join(d, "file.out.index"),
                                   os.path.join(d, "file.out"))
            with open(os.path.join(d, "file.out"), "rb") as f:
                mof = f.read()
            src = m % p
            for r in range(self.num_reducers):
                ir = recs[r]
                blobs[src].append((r % p,
                                   mof[ir.start_offset:ir.start_offset
                                       + ir.part_length]))
                meta[src].append((map_id, r, ir.raw_length))
        with metrics.timer("mesh_shuffle"):
            delivered = exchange_blobs(blobs, mesh, axis)
        # regroup positionally: delivered[dst][src][k] is the k-th blob
        # that src addressed to dst, in send order
        per_reduce: dict[int, dict[str, bytes]] = {
            r: {} for r in range(self.num_reducers)}
        raw_lens: dict[int, dict[str, int]] = {
            r: {} for r in range(self.num_reducers)}
        for src in range(p):
            cursors = {d: 0 for d in range(p)}
            for (map_id, r, raw), (dstdev, _) in zip(meta[src],
                                                     blobs[src]):
                k = cursors[dstdev]
                cursors[dstdev] += 1
                per_reduce[r][map_id] = delivered[dstdev][src][k]
                raw_lens[r][map_id] = raw
        return self._reduce_all(
            writer, lambda r: ExchangeFetchClient(per_reduce[r],
                                                  raw_lengths=raw_lens[r]))

    def run(self, inputs: Sequence[object], mesh=None,
            axis: str = "shuffle") -> dict[int, list[Record]]:
        """Full job. With ``mesh``, the shuffle crosses the device mesh
        (run_reduces_mesh, over ``axis``); otherwise it stays on the
        local DataEngine."""
        writer = self.run_maps(inputs)
        if mesh is not None:
            return self.run_reduces_mesh(writer, mesh, axis=axis)
        return self.run_reduces(writer)
