"""Grep (BASELINE config 4b): Hadoop's distributed grep.

Two chained jobs like Hadoop's Grep example: (1) match lines against a
regex and count matches per matched string; (2) swap (count, match) and
sort descending by count — the second job's single-reducer sort runs
through the engine with the numeric-order key variant (sign-flip
normalization, uda.tpu.LongNumeric-style) on a descending key encoding.
"""

from __future__ import annotations

import re
import struct
from typing import Iterable, Optional

from uda_tpu.models.pipeline import MapReduceJob, Record
from uda_tpu.models.wordcount import parse_text_key, text_key
from uda_tpu.utils.config import Config

__all__ = ["run_grep"]


def _count_mapper_factory(pattern: bytes):
    rx = re.compile(pattern)

    def _mapper(split: bytes) -> Iterable[Record]:
        for line in split.splitlines():
            for m in rx.finditer(line):
                yield text_key(m.group(0)), struct.pack(">q", 1)

    return _mapper


def _sum_reducer(key: bytes, values: list[bytes]) -> Iterable[Record]:
    yield key, struct.pack(">q", sum(struct.unpack(">q", v)[0] for v in values))


def _swap_mapper(split) -> Iterable[Record]:
    for match_key, count_val in split:
        (count,) = struct.unpack(">q", count_val)
        # descending numeric order == ascending memcmp of ~count (big-endian)
        yield struct.pack(">Q", (1 << 64) - 1 - count), match_key

    return


def _identity_reducer(key: bytes, values: list[bytes]) -> Iterable[Record]:
    for v in values:
        yield key, v


def run_grep(text: bytes, pattern: bytes, num_maps: int = 4,
             num_reducers: int = 2, config: Optional[Config] = None,
             work_dir: Optional[str] = None) -> list[tuple[bytes, int]]:
    """Returns [(match, count)] sorted by count descending (ties by
    arrival, like Hadoop's grep-sort)."""
    n = len(text)
    step = max(1, n // num_maps)
    splits = []
    start = 0
    while start < n:
        end = min(n, start + step)
        while end < n and text[end:end + 1] != b"\n":
            end += 1
        splits.append(text[start:end])
        start = end + 1
    job1 = MapReduceJob("grep1", _count_mapper_factory(pattern), _sum_reducer,
                        key_type="org.apache.hadoop.io.Text",
                        num_reducers=num_reducers, config=config,
                        work_dir=work_dir)
    counts: list[Record] = []
    for recs in job1.run(splits).values():
        counts.extend(recs)

    job2 = MapReduceJob("grep2", _swap_mapper, _identity_reducer,
                        key_type="uda.tpu.RawBytes", num_reducers=1,
                        config=config, work_dir=work_dir)
    outputs = job2.run([counts])
    result = []
    for k, v in outputs[0]:
        (inv,) = struct.unpack(">Q", k)
        result.append((parse_text_key(v), (1 << 64) - 1 - inv))
    return result
