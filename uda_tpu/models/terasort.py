"""TeraSort: the flagship workload (BASELINE.json configs 2 and 5).

The reference's headline benchmark is TeraSort on a Hadoop+UDA cluster
(reference scripts/regression/executeTerasort.sh, analizeTerasort.sh):
10-byte keys, 90-byte values, shuffle+merge dominated. Here the whole
shuffle+merge is device-resident:

- records live as uint32[n, 26] rows: columns 0-2 the big-endian packed
  key (10 bytes + 2 constant pad bytes), columns 3-25 the 90-byte value
  (last 2 bytes pad);
- single-chip "merge": one stable lexicographic sort over the 3 key
  columns (uda_tpu.ops.sort semantics, fixed-width keys need no
  length/rank columns);
- multi-chip: the fused partition -> all_to_all -> local-sort step
  (uda_tpu.parallel.distributed), whose concatenated shards are the
  globally sorted dataset.

TeraGen-equivalent data is generated ON DEVICE (jax PRNG) — the host
never touches record bytes, mirroring how the real deployment stages
records into HBM once and keeps them there.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from uda_tpu.ops import pallas_sort
from uda_tpu.ops.sort import route_engine
from uda_tpu.parallel.distributed import (DistributedSortResult,
                                          distributed_sort_step,
                                          uniform_splitters)
from uda_tpu.parallel.mesh import SHUFFLE_AXIS

__all__ = ["KEY_WORDS", "RECORD_WORDS", "RECORD_BYTES", "teragen",
           "teragen_lanes", "single_chip_sort", "sort_lanes_keys8",
           "distributed_terasort", "validate_sorted"]

KEY_WORDS = 3        # 10 key bytes -> 3 BE words (2 pad bytes, constant 0)
VALUE_WORDS = 23     # 90 value bytes -> 23 words (2 pad bytes)
RECORD_WORDS = KEY_WORDS + VALUE_WORDS
RECORD_BYTES = 100   # logical TeraSort record size


@partial(jax.jit, static_argnames=("n",))
def teragen(key: jax.Array, n: int) -> jax.Array:
    """Generate n TeraSort-shaped records on device.

    Keys are uniform random (the TeraGen keyspace); the 2 pad bytes of
    word 2 are zeroed so fixed-width memcmp order == 3-word lexicographic
    order. Values carry random payload bits.
    """
    kk, kv = jax.random.split(key)
    keys = jax.random.bits(kk, (n, KEY_WORDS), dtype=jnp.uint32)
    keys = keys.at[:, 2].set(keys[:, 2] & jnp.uint32(0xFFFF0000))
    vals = jax.random.bits(kv, (n, VALUE_WORDS), dtype=jnp.uint32)
    return jnp.concatenate([keys, vals], axis=1)


@partial(jax.jit, static_argnames=("n",))
def teragen_lanes(key: jax.Array, n: int) -> jax.Array:
    """Generate n TeraSort-shaped records directly in the lanes layout
    (uint32[pallas_sort.ROWS, n]): rows 0-2 the big-endian key words
    (pad bytes of row 2 zeroed), rows 3-25 the value words, rows 26-31
    zero (row 31 becomes the sort's stability tie-break). Generating in
    lanes form means the flagship path never pays a transpose."""
    kk, kv = jax.random.split(key)
    keys = jax.random.bits(kk, (KEY_WORDS, n), dtype=jnp.uint32)
    keys = keys.at[2].set(keys[2] & jnp.uint32(0xFFFF0000))
    vals = jax.random.bits(kv, (VALUE_WORDS, n), dtype=jnp.uint32)
    pad = jnp.zeros((pallas_sort.ROWS - RECORD_WORDS, n), jnp.uint32)
    return jnp.concatenate([keys, vals, pad], axis=0)


def _sort_record_cols(cols: tuple, path: str) -> tuple:
    """Stable lexicographic sort of SoA record columns by the first
    KEY_WORDS columns — the single source of truth for every lax.sort
    payload strategy (see bench_step for the trade-offs): "carry" rides
    all columns through the network; the rest compute a narrow-sort
    permutation and apply it with per-column gathers ("gather"), one
    minor-dim gather on the stacked value columns ("gather2"), or
    chunked carry sorts ("carrychunk")."""
    if path == "carry":
        return lax.sort(cols, num_keys=KEY_WORDS, is_stable=True)
    iota = lax.iota(jnp.int32, cols[0].shape[0])
    *sk, perm = lax.sort((*cols[:KEY_WORDS], iota),
                         num_keys=KEY_WORDS, is_stable=True)
    vals = cols[KEY_WORDS:]
    if path == "gather2":
        pay = jnp.take(jnp.stack(vals, axis=0), perm, axis=1,
                       unique_indices=True, mode="clip")
        return (*sk, *(pay[i] for i in range(len(vals))))
    if path == "carrychunk":
        from uda_tpu.ops.sort import apply_perm_chunked

        return (*sk, *apply_perm_chunked(perm, list(vals)))
    return (*sk, *(jnp.take(c, perm, axis=0) for c in vals))


@partial(jax.jit, static_argnames=("path",))
def _single_chip_sort(words: jax.Array, path: str) -> jax.Array:
    cols = tuple(words[:, i] for i in range(words.shape[1]))
    return jnp.stack(_sort_record_cols(cols, path), axis=1)


@partial(jax.jit, static_argnames=("path", "tile", "interpret"))
def _single_chip_sort_lanes(words: jax.Array, path: str, tile: int,
                            interpret: bool) -> jax.Array:
    """Lanes-engine body of single_chip_sort: pad the record count to a
    power-of-two multiple of ``tile`` with +inf-key lanes and run the
    Pallas pipeline. Padding lanes sit PAST every real lane, so even a
    real record whose keys are all 0xFFFFFFFF sorts before them (the
    tile-sort kernel's arrival-index tie-break is the lane index, and
    padding occupies the highest lanes); truncating to n drops exactly
    the padding."""
    n, w = words.shape
    m, tile = pallas_sort.pad_pow2(n, tile)
    if path in ("keys8", "keys8f"):
        # keys-only cascade (shared core: pallas_sort.keys8_sort_perm;
        # "keys8f" = the folded half-width variant); sorted keys come
        # back from the cascade, so only the 23 value rows cross the
        # permutation gather
        keyr = jnp.full((KEY_WORDS, m), np.uint32(0xFFFFFFFF), jnp.uint32)
        keyr = lax.dynamic_update_slice(
            keyr, words[:, :KEY_WORDS].T.astype(jnp.uint32), (0, 0))
        sk, perm = pallas_sort.keys8_sort_perm(keyr, tile=tile,
                                               interpret=interpret,
                                               folded=path == "keys8f")
        pay = jnp.take(words[:, KEY_WORDS:].T, perm[:n], axis=1,
                       unique_indices=True, mode="clip")
        return jnp.concatenate([sk[:, :n], pay], axis=0).T
    mat = jnp.full((pallas_sort.ROWS, m), np.uint32(0xFFFFFFFF),
                   jnp.uint32)
    mat = lax.dynamic_update_slice(mat, words.T.astype(jnp.uint32), (0, 0))
    out = pallas_sort.sort_lanes(mat, num_keys=KEY_WORDS, tile=tile,
                                 interpret=interpret,
                                 two_phase=path == "lanes2")
    return pallas_sort.lanes_to_rows(out, w)[:n]


def single_chip_sort(words: jax.Array, path: str = "auto",
                     tile: int = 1024,
                     interpret: bool = False) -> jax.Array:
    """The single-chip shuffle+merge: stable lexicographic sort of whole
    records by their 3 key words (the device replacement of the
    reference's k-way PQ merge, src/Merger/MergeQueue.h:276-427).

    Payload-movement strategy (see bench_step for the full trade-off):
    the lanes engines ("lanes"/"lanes2"/"keys8") run the Pallas
    bitonic pipeline with bounded compile; "carry" rides the 23 value
    words through a ``lax.sort`` network (fast at runtime, pathological
    compile on TPU remote-compile backends — the CPU default);
    "gather"/"gather2"/"carrychunk" apply a narrow-sort permutation
    (per-column gathers / one minor-dim gather / chunked carry sorts —
    "carrychunk" is the TPU default via "auto": measured fly-off
    champion, BENCH_HW_r05.json). "auto" resolves per the ambient
    backend — and the deployed UDA_TPU_SORT_PATH winner — at call time,
    with small batches steered off gather-bound engines
    (ops.sort.route_engine).
    """
    path = route_engine(int(words.shape[0]), path, lanes_ok=True)
    if path in ("lanes", "lanes2", "keys8", "keys8f"):
        if int(words.shape[0]) == 0:
            return jnp.asarray(words, jnp.uint32)
        return _single_chip_sort_lanes(jnp.asarray(words, jnp.uint32),
                                       path, tile, interpret)
    return _single_chip_sort(words, path)


def _keys8_parts(x: jax.Array, tile: int, interpret: bool,
                 folded: bool = False):
    """The keys8 engine: run the ENTIRE bitonic cascade on an 8-row
    keys-only array (one sublane tile: 3 key rows, 4 zero rows, the
    tie-break row) and move the 23 payload rows ONCE with a global
    XLA lane gather by the resulting permutation.

    Rationale (v5e stage profile, scripts/profile_lanes.py): the 32-row
    cascade is VPU-bound — every compare-exchange rolls/selects all 32
    rows, and every merge pass sweeps the full 128 B/record through HBM.
    The keys view cuts both by 4x; the single payload gather is the only
    full-width pass besides generation. Unlike the in-kernel two-phase
    gather (two_phase=True), the global gather is an XLA op — it lowers
    on every backend (scripts/probe_gather.py: no dynamic lane-gather
    formulation lowers in Mosaic on v5e).

    Returns (sorted [KEY_WORDS, n] key rows, gathered [VALUE_WORDS, n]
    payload, int32 permutation). Stability: the tie-break row holds the
    arrival index, so the permutation lists equal keys in arrival order.
    """
    sk, perm = pallas_sort.keys8_sort_perm(x[:KEY_WORDS], tile=tile,
                                           interpret=interpret,
                                           folded=folded)
    payload = jnp.take(x[KEY_WORDS:RECORD_WORDS], perm, axis=1,
                       unique_indices=True, mode="clip")
    return sk, payload, perm


def sort_lanes_keys8(x: jax.Array, tile: int = 1024,
                     interpret: bool = False,
                     folded: bool = False) -> jax.Array:
    """Stable TeraSort record sort in lanes layout via the keys8 engine.

    Drop-in equal to ``pallas_sort.sort_lanes(x, num_keys=KEY_WORDS,
    tile=tile)`` on teragen_lanes-shaped input (layout pad rows zero):
    same [ROWS, n] output, byte-identical including the arrival-index
    row — but the payload crosses HBM once instead of riding every
    compare-exchange stage. ``folded`` selects the half-width cascade
    (ops.pallas_fold; the keys8f engine).
    """
    sk, payload, perm = _keys8_parts(jnp.asarray(x, jnp.uint32), tile,
                                     interpret, folded=folded)
    n = x.shape[1]
    pad = jnp.zeros((pallas_sort.ROWS - RECORD_WORDS - 1, n), jnp.uint32)
    return jnp.concatenate(
        [sk, payload, pad, perm[None, :].astype(jnp.uint32)], axis=0)


def distributed_terasort(words, mesh: Mesh, axis: str = SHUFFLE_AXIS,
                         capacity: Optional[int] = None
                         ) -> DistributedSortResult:
    """Multi-chip TeraSort step over the mesh (BASELINE config 5 shape).

    ``capacity`` defaults to 2x the balanced per-(src,dst) share —
    uniform keys stay far under it; heavy skew should use
    parallel.exchange.shuffle_exchange's multi-round path instead.
    """
    p = int(np.prod(list(mesh.shape.values())))
    n = int(words.shape[0])
    if capacity is None:
        capacity = max(1, (2 * n) // (p * p))
    return distributed_sort_step(words, uniform_splitters(p), mesh, axis,
                                 capacity=capacity, num_keys=KEY_WORDS)


def _checksum_cols(cols) -> jax.Array:
    """Column-tuple form of the multiset fingerprint: distinct odd
    multiplier per column couples words within a row; the outer sum is
    permutation-invariant. Stays in SoA form (no [n, W] materialization
    — keeps the compiled program small)."""
    rec = None
    for c, col in enumerate(cols):
        m = col.astype(jnp.uint32) * jnp.uint32((2 * c + 1) * 2654435761 & 0xFFFFFFFF)
        rec = m if rec is None else rec + m
    return jnp.sum(rec ^ jnp.uint32(0x9E3779B9))


def _violations_cols(k0, k1, k2) -> jax.Array:
    gt = ((k0[:-1] > k0[1:])
          | ((k0[:-1] == k0[1:]) & (k1[:-1] > k1[1:]))
          | ((k0[:-1] == k0[1:]) & (k1[:-1] == k1[1:]) & (k2[:-1] > k2[1:])))
    return jnp.sum(gt.astype(jnp.int32))


@partial(jax.jit, static_argnames=("n", "k", "path", "tile", "interpret",
                                   "chunk_cols"))
def bench_step(seed: jax.Array, n: int, k: int, path: str = "lanes",
               tile: int = 1024, interpret: bool = False,
               chunk_cols: int | None = None):
    """Sustained-throughput benchmark kernel: k independent
    teragen->sort->validate rounds inside ONE device program (one host
    dispatch), so per-call host/RPC latency amortizes away and the
    result reflects device shuffle+merge throughput.

    Nothing ever materializes an [n, 26] row matrix — on TPU, XLA
    lane-pads the minor dimension to 128 words (5x HBM footprint and
    bandwidth). Records are either 26 separate [n] columns (SoA) or the
    [32, n] lanes layout.

    Four device strategies:

    - ``path="lanes"`` (flagship): records live in the lanes layout and
      the full sort runs in the Pallas bitonic pipeline
      (pallas_sort.sort_lanes). Payload rides every compare-exchange as
      lane moves of the 32-row tile — streaming HBM access, no gathers
      — and compile cost is BOUNDED (two Mosaic kernels total,
      regardless of n and record width).
    - ``path="lanes2"``: the two-phase variant — each network runs on
      an 8-row keys view and the payload moves with one in-kernel lane
      gather (sort_lanes two_phase=True). Faster where Mosaic lowers
      the dynamic gather well; bench.py decides by a measured fly-off.
    - ``path="keys8"``: the whole cascade runs on an 8-row keys-only
      array (4x less VPU and HBM work than the 32-row pipeline) and the
      payload moves ONCE via a global XLA lane gather (_keys8_parts) —
      the gather that Mosaic cannot lower in-kernel, hoisted to where
      XLA can.
    - ``path="gather2"``: keys8 with the permutation from the narrow
      4-operand ``lax.sort`` instead of the Pallas cascade (same single
      payload gather). Bounded compile; whichever permutation engine is
      faster on the ambient backend wins bench.py's fly-off.
    - ``path="carrychunk"``: gather-free — the permutation is inverted
      with a 2-operand sort and applied with ceil(23/6) narrow carry
      sorts. Payload moves through sort networks like "carry" but every
      sort stays far below the operand count where compile blows up.
    - ``path="carry"``: the payload rides the ``lax.sort`` network as
      extra operands. Fast at runtime (~12 GB/s, CPU-backend
      measurement) but XLA's
      variadic-sort compile time grows superlinearly in operand count —
      on remote-compile backends the 26-operand program can take hours
      to compile ONCE (it persists in the compile cache afterwards).
    - ``path="gather"``: a 4-operand sort (3 key words + iota) computes
      the permutation, then per-column gathers apply it. Compiles in
      ~1 min cold; runtime is gather-bound: 0.30 GB/s measured on the
      v5e chip at the full bench shape (BENCH_r02) — TPU random
      per-element gathers are the slowest payload mover by far, which
      is what motivated the lanes pipeline.

    bench.py probes which path is compilable within its time budget and
    picks the fastest (see bench.py --probe).

    Returns (total order violations, input checksum, output checksum):
    consuming the sorted output in-graph keeps XLA from eliminating any
    round, and the caller asserts violations == 0 and checksum equality.
    """
    from uda_tpu.ops.sort import ALL_SORT_PATHS

    if path not in ALL_SORT_PATHS:
        raise ValueError(f"unknown bench path {path!r}")

    def body_keys8(i, acc):
        viol, ck_in, ck_out = acc
        x = teragen_lanes(jax.random.fold_in(seed, i), n)
        ck_in = ck_in + _checksum_cols(tuple(x[r]
                                             for r in range(RECORD_WORDS)))
        s8, payload, _ = _keys8_parts(x, tile, interpret,
                                      folded=path == "keys8f")
        out_cols = (*(s8[r] for r in range(KEY_WORDS)),
                    *(payload[r] for r in range(VALUE_WORDS)))
        ck_out = ck_out + _checksum_cols(out_cols)
        viol = viol + _violations_cols(s8[0], s8[1], s8[2])
        return (viol, ck_in, ck_out)

    def body_carrychunk(i, acc):
        # gather-free payload move (ops.sort.apply_perm_chunked):
        # payload crosses sort networks like "carry", compile stays
        # bounded
        from uda_tpu.ops.sort import apply_perm_chunked

        viol, ck_in, ck_out = acc
        x = teragen_lanes(jax.random.fold_in(seed, i), n)
        ck_in = ck_in + _checksum_cols(tuple(x[r]
                                             for r in range(RECORD_WORDS)))
        iota = lax.iota(jnp.int32, n)
        k0, k1, k2, perm = lax.sort((x[0], x[1], x[2], iota),
                                    num_keys=KEY_WORDS, is_stable=True)
        cols = apply_perm_chunked(
            perm, [x[r] for r in range(KEY_WORDS, RECORD_WORDS)],
            chunk_cols=chunk_cols)
        out_cols = (k0, k1, k2, *cols)
        ck_out = ck_out + _checksum_cols(out_cols)
        viol = viol + _violations_cols(k0, k1, k2)
        return (viol, ck_in, ck_out)

    def body_gather2(i, acc):
        # keys8's XLA-native twin: permutation from the narrow 4-operand
        # lax.sort (XLA's tuned on-chip sort), payload via the same
        # single minor-dim gather — no Pallas in the program at all
        viol, ck_in, ck_out = acc
        x = teragen_lanes(jax.random.fold_in(seed, i), n)
        ck_in = ck_in + _checksum_cols(tuple(x[r]
                                             for r in range(RECORD_WORDS)))
        iota = lax.iota(jnp.int32, n)
        k0, k1, k2, perm = lax.sort((x[0], x[1], x[2], iota),
                                    num_keys=KEY_WORDS, is_stable=True)
        payload = jnp.take(x[KEY_WORDS:RECORD_WORDS], perm, axis=1,
                           unique_indices=True, mode="clip")
        out_cols = (k0, k1, k2,
                    *(payload[r] for r in range(VALUE_WORDS)))
        ck_out = ck_out + _checksum_cols(out_cols)
        viol = viol + _violations_cols(k0, k1, k2)
        return (viol, ck_in, ck_out)

    def body_lanes(i, acc):
        viol, ck_in, ck_out = acc
        x = teragen_lanes(jax.random.fold_in(seed, i), n)
        ck_in = ck_in + _checksum_cols(tuple(x[r]
                                             for r in range(RECORD_WORDS)))
        out = pallas_sort.sort_lanes(x, num_keys=KEY_WORDS, tile=tile,
                                     interpret=interpret,
                                     two_phase=path == "lanes2")
        ck_out = ck_out + _checksum_cols(tuple(out[r]
                                               for r in range(RECORD_WORDS)))
        viol = viol + _violations_cols(out[0], out[1], out[2])
        return (viol, ck_in, ck_out)

    def body_cols(i, acc):
        viol, ck_in, ck_out = acc
        w = teragen(jax.random.fold_in(seed, i), n)
        cols = tuple(w[:, c] for c in range(RECORD_WORDS))
        ck_in = ck_in + _checksum_cols(cols)
        out = _sort_record_cols(cols, path)
        ck_out = ck_out + _checksum_cols(out)
        viol = viol + _violations_cols(out[0], out[1], out[2])
        return (viol, ck_in, ck_out)

    zero = jnp.uint32(0)
    body = {"lanes": body_lanes, "lanes2": body_lanes,
            "keys8": body_keys8, "keys8f": body_keys8,
            "gather2": body_gather2,
            "carrychunk": body_carrychunk}.get(path, body_cols)
    return lax.fori_loop(0, k, body, (jnp.int32(0), zero, zero))


@jax.jit
def _order_violations(words: jax.Array) -> jax.Array:
    """Count adjacent out-of-order key pairs on device (0 == sorted)."""
    a = words[:-1, :KEY_WORDS]
    b = words[1:, :KEY_WORDS]
    gt = ((a[:, 0] > b[:, 0])
          | ((a[:, 0] == b[:, 0]) & (a[:, 1] > b[:, 1]))
          | ((a[:, 0] == b[:, 0]) & (a[:, 1] == b[:, 1])
             & (a[:, 2] > b[:, 2])))
    return jnp.sum(gt.astype(jnp.int32))


@jax.jit
def _checksum(words: jax.Array) -> jax.Array:
    """Order-independent multiset fingerprint over row-matrix records —
    the same formula as _checksum_cols (a DISTINCT odd multiplier per
    column couples a word to its column position, so torn records and
    column swaps change the sum; the outer sum over records is
    permutation-invariant), so validate_sorted and bench_step agree."""
    return _checksum_cols(tuple(words[:, c] for c in range(words.shape[1])))


def validate_sorted(sorted_words, input_words=None,
                    valid_count: Optional[int] = None) -> None:
    """Sort-validity gate (the TeraSort validity check of the reference's
    regression harness, scripts/regression/terasortAnallizer.sh):
    order violations == 0, and when the input is given, the record
    multiset is preserved (device checksum)."""
    sw = sorted_words if valid_count is None else sorted_words[:valid_count]
    violations = int(_order_violations(sw))
    if violations:
        raise AssertionError(f"{violations} adjacent order violations")
    if input_words is not None:
        if int(_checksum(sw)) != int(_checksum(input_words)):
            raise AssertionError("record multiset changed during sort")
