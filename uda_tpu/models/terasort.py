"""TeraSort: the flagship workload (BASELINE.json configs 2 and 5).

The reference's headline benchmark is TeraSort on a Hadoop+UDA cluster
(reference scripts/regression/executeTerasort.sh, analizeTerasort.sh):
10-byte keys, 90-byte values, shuffle+merge dominated. Here the whole
shuffle+merge is device-resident:

- records live as uint32[n, 26] rows: columns 0-2 the big-endian packed
  key (10 bytes + 2 constant pad bytes), columns 3-25 the 90-byte value
  (last 2 bytes pad);
- single-chip "merge": one stable lexicographic sort over the 3 key
  columns (uda_tpu.ops.sort semantics, fixed-width keys need no
  length/rank columns);
- multi-chip: the fused partition -> all_to_all -> local-sort step
  (uda_tpu.parallel.distributed), whose concatenated shards are the
  globally sorted dataset.

TeraGen-equivalent data is generated ON DEVICE (jax PRNG) — the host
never touches record bytes, mirroring how the real deployment stages
records into HBM once and keeps them there.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from uda_tpu.parallel.distributed import (DistributedSortResult,
                                          distributed_sort_step,
                                          uniform_splitters)
from uda_tpu.parallel.mesh import SHUFFLE_AXIS

__all__ = ["KEY_WORDS", "RECORD_WORDS", "RECORD_BYTES", "teragen",
           "single_chip_sort", "distributed_terasort", "validate_sorted"]

KEY_WORDS = 3        # 10 key bytes -> 3 BE words (2 pad bytes, constant 0)
VALUE_WORDS = 23     # 90 value bytes -> 23 words (2 pad bytes)
RECORD_WORDS = KEY_WORDS + VALUE_WORDS
RECORD_BYTES = 100   # logical TeraSort record size


@partial(jax.jit, static_argnames=("n",))
def teragen(key: jax.Array, n: int) -> jax.Array:
    """Generate n TeraSort-shaped records on device.

    Keys are uniform random (the TeraGen keyspace); the 2 pad bytes of
    word 2 are zeroed so fixed-width memcmp order == 3-word lexicographic
    order. Values carry random payload bits.
    """
    kk, kv = jax.random.split(key)
    keys = jax.random.bits(kk, (n, KEY_WORDS), dtype=jnp.uint32)
    keys = keys.at[:, 2].set(keys[:, 2] & jnp.uint32(0xFFFF0000))
    vals = jax.random.bits(kv, (n, VALUE_WORDS), dtype=jnp.uint32)
    return jnp.concatenate([keys, vals], axis=1)


@jax.jit
def single_chip_sort(words: jax.Array) -> jax.Array:
    """The single-chip shuffle+merge: stable lexicographic sort of whole
    records by their 3 key words (the device replacement of the
    reference's k-way PQ merge, src/Merger/MergeQueue.h:276-427).

    The 23 value words ride through the sort network as extra operands
    instead of being gathered by the output permutation afterwards: on
    TPU a row gather of [n, 26] runs at ~2.3 GB/s while the
    operand-carried sort sustains ~12 GB/s (the gather's random HBM
    access pattern is the bottleneck, not the compare-exchange work).
    """
    cols = tuple(words[:, i] for i in range(words.shape[1]))
    out = lax.sort(cols, num_keys=KEY_WORDS, is_stable=True)
    return jnp.stack(out, axis=1)


def distributed_terasort(words, mesh: Mesh, axis: str = SHUFFLE_AXIS,
                         capacity: Optional[int] = None
                         ) -> DistributedSortResult:
    """Multi-chip TeraSort step over the mesh (BASELINE config 5 shape).

    ``capacity`` defaults to 2x the balanced per-(src,dst) share —
    uniform keys stay far under it; heavy skew should use
    parallel.exchange.shuffle_exchange's multi-round path instead.
    """
    p = int(np.prod(list(mesh.shape.values())))
    n = int(words.shape[0])
    if capacity is None:
        capacity = max(1, (2 * n) // (p * p))
    return distributed_sort_step(words, uniform_splitters(p), mesh, axis,
                                 capacity=capacity, num_keys=KEY_WORDS)


def _checksum_cols(cols) -> jax.Array:
    """Column-tuple form of the multiset fingerprint: distinct odd
    multiplier per column couples words within a row; the outer sum is
    permutation-invariant. Stays in SoA form (no [n, W] materialization
    — keeps the compiled program small)."""
    rec = None
    for c, col in enumerate(cols):
        m = col.astype(jnp.uint32) * jnp.uint32((2 * c + 1) * 2654435761 & 0xFFFFFFFF)
        rec = m if rec is None else rec + m
    return jnp.sum(rec ^ jnp.uint32(0x9E3779B9))


def _violations_cols(k0, k1, k2) -> jax.Array:
    gt = ((k0[:-1] > k0[1:])
          | ((k0[:-1] == k0[1:]) & (k1[:-1] > k1[1:]))
          | ((k0[:-1] == k0[1:]) & (k1[:-1] == k1[1:]) & (k2[:-1] > k2[1:])))
    return jnp.sum(gt.astype(jnp.int32))


@partial(jax.jit, static_argnames=("n", "k"))
def bench_step(seed: jax.Array, n: int, k: int):
    """Sustained-throughput benchmark kernel: k independent
    teragen->sort->validate rounds inside ONE device program (one host
    dispatch), so per-call host/RPC latency amortizes away and the
    result reflects device shuffle+merge throughput.

    Everything stays in column (SoA) form — the payload rides the sort
    network as operands and validation consumes the sorted columns
    directly, with no [n, 26] row materialization.

    Returns (total order violations, input checksum, output checksum):
    consuming the sorted output in-graph keeps XLA from eliminating any
    round, and the caller asserts violations == 0 and checksum equality.
    """

    def body(i, acc):
        viol, ck_in, ck_out = acc
        w = teragen(jax.random.fold_in(seed, i), n)
        cols = tuple(w[:, c] for c in range(RECORD_WORDS))
        ck_in = ck_in + _checksum_cols(cols)
        out = lax.sort(cols, num_keys=KEY_WORDS, is_stable=True)
        ck_out = ck_out + _checksum_cols(out)
        viol = viol + _violations_cols(out[0], out[1], out[2])
        return (viol, ck_in, ck_out)

    zero = jnp.uint32(0)
    return lax.fori_loop(0, k, body, (jnp.int32(0), zero, zero))


@jax.jit
def _order_violations(words: jax.Array) -> jax.Array:
    """Count adjacent out-of-order key pairs on device (0 == sorted)."""
    a = words[:-1, :KEY_WORDS]
    b = words[1:, :KEY_WORDS]
    gt = ((a[:, 0] > b[:, 0])
          | ((a[:, 0] == b[:, 0]) & (a[:, 1] > b[:, 1]))
          | ((a[:, 0] == b[:, 0]) & (a[:, 1] == b[:, 1])
             & (a[:, 2] > b[:, 2])))
    return jnp.sum(gt.astype(jnp.int32))


@jax.jit
def _checksum(words: jax.Array) -> jax.Array:
    """Order-independent multiset fingerprint: per-record mix (couples
    the words WITHIN a row, so torn records change the sum) summed over
    records (so permutations don't). One formula, shared by
    validate_sorted and bench_step."""
    x = words.astype(jnp.uint32)
    mix = x * jnp.uint32(2654435761)
    rec = jnp.sum(mix, axis=1) ^ jnp.uint32(0x9E3779B9)
    return jnp.sum(rec.astype(jnp.uint32))


def validate_sorted(sorted_words, input_words=None,
                    valid_count: Optional[int] = None) -> None:
    """Sort-validity gate (the TeraSort validity check of the reference's
    regression harness, scripts/regression/terasortAnallizer.sh):
    order violations == 0, and when the input is given, the record
    multiset is preserved (device checksum)."""
    sw = sorted_words if valid_count is None else sorted_words[:valid_count]
    violations = int(_order_violations(sw))
    if violations:
        raise AssertionError(f"{violations} adjacent order violations")
    if input_words is not None:
        if int(_checksum(sw)) != int(_checksum(input_words)):
            raise AssertionError("record multiset changed during sort")
