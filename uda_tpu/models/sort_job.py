"""Sort: the Hadoop Sort example through the full pipeline.

The reference regression ladder's pure shuffle+merge workload
(reference scripts/regression/namesConf.sh:20-35 lists "sort" beside
TeraSort/wordcount): identity map and identity reduce over
BytesWritable keys, so the job measures nothing but the engine —
partitioned spill, chunked fetch, comparator merge, framed emission.
Exercises variable-length binary keys through the byte-exact
comparator path (4-byte length skip + memcmp, reference
src/Merger/CompareFunc.cc:60-75).
"""

from __future__ import annotations

import struct
from typing import Iterable, Optional, Sequence, Tuple

from uda_tpu.models.pipeline import MapReduceJob, Record
from uda_tpu.utils.config import Config

__all__ = ["bytes_key", "parse_bytes_key", "run_sort"]


def bytes_key(content: bytes) -> bytes:
    """Serialize like org.apache.hadoop.io.BytesWritable (4-byte BE
    length + bytes)."""
    return struct.pack(">i", len(content)) + content


def parse_bytes_key(key: bytes) -> bytes:
    (n,) = struct.unpack(">i", key[:4])
    return key[4:4 + n]


def _mapper(split: Sequence[Tuple[bytes, bytes]]) -> Iterable[Record]:
    for content, value in split:
        yield bytes_key(content), value


def _reducer(key: bytes, values: list[bytes]) -> Iterable[Record]:
    for v in values:          # identity: duplicates preserved
        yield key, v


def run_sort(records: Sequence[Tuple[bytes, bytes]], num_maps: int = 4,
             num_reducers: int = 3, config: Optional[Config] = None,
             work_dir: Optional[str] = None,
             supplier_roots: Optional[Sequence[str]] = None
             ) -> dict[int, list[Tuple[bytes, bytes]]]:
    """Run the identity sort job over ``records`` ((key content, value)
    pairs). Returns {reducer: [(key content, value), ...]} where each
    reducer's list is comparator-sorted — the Hadoop Sort contract
    (per-reducer total order under the default hash partitioner; global
    order is TeraSort's splitter-partitioned variant)."""
    splits = [list(records[m::num_maps]) for m in range(num_maps)]
    job = MapReduceJob("sortjob", _mapper, _reducer,
                       key_type="org.apache.hadoop.io.BytesWritable",
                       num_reducers=num_reducers, config=config,
                       work_dir=work_dir, supplier_roots=supplier_roots)
    outputs = job.run(splits)
    return {r: [(parse_bytes_key(k), v) for k, v in recs]
            for r, recs in outputs.items()}
