"""WordCount (BASELINE config 1): Text keys through the byte-exact path.

The classic first workload of the reference's regression suite
(reference scripts/regression/namesConf.sh:20-35). Exercises Text-key
comparator semantics (VInt-prefixed keys, reference CompareFunc.cc:82-86)
and the full supplier->merger pipeline. Input: any text (enwik8 when
available).
"""

from __future__ import annotations

import re
import struct
from typing import Iterable, Optional

from uda_tpu.models.pipeline import MapReduceJob, Record
from uda_tpu.utils import vint
from uda_tpu.utils.config import Config

__all__ = ["text_key", "parse_text_key", "run_wordcount"]

_TOKEN = re.compile(rb"[A-Za-z0-9]+")


def text_key(word: bytes) -> bytes:
    """Serialize like org.apache.hadoop.io.Text (VInt length + bytes)."""
    return vint.encode_vlong(len(word)) + word


def parse_text_key(key: bytes) -> bytes:
    n, off = vint.decode_vlong(key, 0)
    return key[off:off + n]


def _mapper(split: bytes) -> Iterable[Record]:
    one = struct.pack(">q", 1)  # LongWritable(1)
    for m in _TOKEN.finditer(split):
        yield text_key(m.group(0).lower()), one


def _reducer(key: bytes, values: list[bytes]) -> Iterable[Record]:
    total = sum(struct.unpack(">q", v)[0] for v in values)
    yield key, struct.pack(">q", total)


def run_wordcount(text: bytes, num_maps: int = 4, num_reducers: int = 2,
                  config: Optional[Config] = None,
                  work_dir: Optional[str] = None,
                  mesh=None) -> dict[bytes, int]:
    """Run WordCount over ``text`` split into ``num_maps`` chunks; returns
    {word: count} merged across reducers. With ``mesh``, the shuffle
    crosses the device mesh (MapReduceJob.run_reduces_mesh)."""
    n = len(text)
    step = max(1, n // num_maps)
    splits = []
    start = 0
    # split on whitespace boundaries so tokens are never cut
    while start < n:
        end = min(n, start + step)
        while end < n and text[end:end + 1] not in b" \t\r\n":
            end += 1
        splits.append(text[start:end])
        start = end
    job = MapReduceJob("wordcount", _mapper, _reducer,
                       key_type="org.apache.hadoop.io.Text",
                       num_reducers=num_reducers, config=config,
                       work_dir=work_dir)
    outputs = job.run(splits, mesh=mesh)
    result: dict[bytes, int] = {}
    for recs in outputs.values():
        for k, v in recs:
            result[parse_text_key(k)] = struct.unpack(">q", v)[0]
    return result
