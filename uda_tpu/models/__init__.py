"""Workloads (the benchmark ladder of BASELINE.json and the reference
regression suite, scripts/regression/namesConf.sh:20-35): TeraSort,
Sort, WordCount, SecondarySort, InvertedIndex, Grep, Pi, DFSIO."""

from uda_tpu.models import (dfsio, grep, inverted_index, pi, pipeline,
                            secondary_sort, sort_job, terasort, wordcount)

__all__ = ["dfsio", "grep", "inverted_index", "pi", "pipeline",
           "secondary_sort", "sort_job", "terasort", "wordcount"]
