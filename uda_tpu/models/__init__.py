"""Workloads (the benchmark ladder of BASELINE.json): TeraSort,
WordCount, SecondarySort, InvertedIndex, Grep."""

from uda_tpu.models import (grep, inverted_index, pipeline, secondary_sort,
                            terasort, wordcount)

__all__ = ["grep", "inverted_index", "pipeline", "secondary_sort",
           "terasort", "wordcount"]
