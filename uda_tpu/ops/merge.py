"""Record-level merge APIs + host fallback.

``merge_batches`` is the framework's equivalent of the reference's
network-levitated merge core (MergeManager's PQ over Segments, reference
src/Merger/MergeManager.cc:155-182 + MergeQueue.h:276-427): take k sorted
segments, produce the globally sorted record stream. Here the comparator
work happens on device (uda_tpu.ops.sort); the host only packs columns
and gathers bytes at emission.

``merge_batches_host`` is the pure-host fallback, kept (a) as the
correctness oracle the device path is diffed against, and (b) as the
actual merge path when no accelerator is present — mirroring the
reference's fallback-to-vanilla philosophy (SURVEY §5) inside the engine.
"""

from __future__ import annotations

import functools
import heapq
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from uda_tpu.ops import packing, sort
from uda_tpu.utils.comparators import KeyType
from uda_tpu.utils.ifile import RecordBatch
from uda_tpu.utils.metrics import metrics

__all__ = ["merge_batches", "merge_batches_host", "merge_iter_host",
           "merge_record_streams", "sorted_batch_order"]


def sorted_batch_order(batch: RecordBatch, kt: KeyType, width: int) -> np.ndarray:
    """Device-computed stable sort permutation for one batch."""
    with metrics.timer("pack"):
        packed = packing.pack_keys(batch, kt, width)
    with metrics.timer("device_sort"):
        return sort.sort_permutation(packed)


def merge_batches(batches: Sequence[RecordBatch], kt: KeyType,
                  width: int) -> RecordBatch:
    """Merge k sorted (or unsorted — the sort is total) segments on device.

    Overflow ranks are computed across the *concatenation* so they are
    globally consistent (see merge_runs caveat in uda_tpu.ops.sort).
    """
    cat = RecordBatch.concat(list(batches))
    order = sorted_batch_order(cat, kt, width)
    return cat.take(order)


def merge_batches_host(batches: Sequence[RecordBatch], kt: KeyType) -> RecordBatch:
    """Host oracle: stable sort of the concatenation by comparator order.

    Equal keys keep (segment, record) arrival order — the same contract
    the device path's stable sort provides.
    """
    cat = RecordBatch.concat(list(batches))
    idx = list(range(cat.num_records))
    keys = [cat.key(i) for i in idx]
    cmp = kt.compare
    order = sorted(idx, key=functools.cmp_to_key(
        lambda i, j: cmp(keys[i], keys[j])))
    return cat.take(np.asarray(order, dtype=np.int64))


def merge_record_streams(streams: Sequence[Iterator[Tuple[bytes, bytes]]],
                         kt: KeyType) -> Iterator[Tuple[bytes, bytes]]:
    """Streaming k-way heap merge over record iterators — the literal
    analogue of the reference's MergeQueue::next (MergeQueue.h:276-427).
    Memory held = one record per stream, so file-backed runs (the RPQ
    phase over SuperSegments) merge with bounded memory."""

    cmp = kt.compare

    class _Cursor:
        __slots__ = ("it", "seq", "head")

        def __init__(self, it: Iterator[Tuple[bytes, bytes]], seq: int):
            self.it = it
            self.seq = seq
            self.head: Optional[Tuple[bytes, bytes]] = next(it, None)

        def advance(self) -> None:
            self.head = next(self.it, None)

        def __lt__(self, other: "_Cursor") -> bool:
            c = cmp(self.head[0], other.head[0])
            if c != 0:
                return c < 0
            return self.seq < other.seq  # stable by segment order

    heap = [c for c in (_Cursor(iter(s), i) for i, s in enumerate(streams))
            if c.head is not None]
    heapq.heapify(heap)
    while heap:
        cur = heap[0]
        yield cur.head
        cur.advance()
        if cur.head is not None:
            heapq.heapreplace(heap, cur)
        else:
            heapq.heappop(heap)


def merge_iter_host(batches: Sequence[RecordBatch],
                    kt: KeyType) -> Iterator[Tuple[bytes, bytes]]:
    """merge_record_streams over in-memory batches."""
    return merge_record_streams([b.iter_records() for b in batches], kt)
