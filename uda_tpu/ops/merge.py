"""Record-level merge APIs + host fallback.

``merge_batches`` is the framework's equivalent of the reference's
network-levitated merge core (MergeManager's PQ over Segments, reference
src/Merger/MergeManager.cc:155-182 + MergeQueue.h:276-427): take k sorted
segments, produce the globally sorted record stream. Here the comparator
work happens on device (uda_tpu.ops.sort); the host only packs columns
and gathers bytes at emission.

``merge_batches_host`` is the pure-host fallback, kept (a) as the
correctness oracle the device path is diffed against, and (b) as the
actual merge path when no accelerator is present — mirroring the
reference's fallback-to-vanilla philosophy (SURVEY §5) inside the engine.

``merge_batches_two_phase`` is the TopSort-shaped alternative
(arXiv:2205.07991: structure the sorter around HBM bandwidth, not
compute): instead of re-sorting the concatenation of k sorted runs —
O(n log n) compare-exchange over the whole shuffle — each run is
partially sorted on its own (usually just the monotonicity check: Hadoop
map outputs arrive comparator-sorted) and the runs then fold through an
HBM-resident pairwise merge tree (the O(n log k) merge-path kernel /
native linear merge), so every record moves through at most log2(k)
merges and the gather-bound small-batch regime never pays a whole-
shuffle re-sort. The row-building helpers here are shared with the
overlapped merger (uda_tpu.merger.overlap), which is the same merge
tree fed online.
"""

from __future__ import annotations

import functools
import heapq
import threading
from typing import Iterator, Optional, Sequence, Tuple

import jax
import numpy as np

from uda_tpu.ops import packing, sort
from uda_tpu.ops.pallas_merge import merge_sorted_pair
from uda_tpu.utils.comparators import KeyType
from uda_tpu.utils.ifile import RecordBatch
from uda_tpu.utils.metrics import metrics
from uda_tpu.utils.resledger import resledger


def _buf_key(flat: np.ndarray) -> int:
    """Ledger identity of a pool buffer: its base data pointer (stable
    across the lease's view reshapes; cheap on both sides)."""
    return int(flat.__array_interface__["data"][0])

__all__ = ["merge_batches", "merge_batches_host", "merge_iter_host",
           "merge_record_streams", "sorted_batch_order",
           "merge_batches_two_phase", "resolve_merge_mode",
           "resolve_run_engine", "resolve_native_rows_merge",
           "lex_cols_sorted", "run_row_order", "fill_run_rows",
           "merge_row_pair", "merge_split_point", "merge_rows_split_into",
           "RowBufferPool", "next_run_capacity", "pad_rows_to",
           "PAD_WORD", "MIN_RUN_CAPACITY", "ROW_EXTRA_COLS"]

# -- shared run-row machinery (the overlap forest + two-phase merge) --------

# Padding word for device runs: all-0xFFFFFFFF rows sort strictly after
# every real row (a real row's length column is a content length < 2^31),
# so valid rows stay a prefix through any merge.
PAD_WORD = np.uint32(0xFFFFFFFF)

MIN_RUN_CAPACITY = 512  # smallest padded run (= default merge tile)

# composite-key columns appended after the key words:
# (content length, segment index, row index)
ROW_EXTRA_COLS = 3


def next_run_capacity(n: int) -> int:
    """Smallest power-of-two run capacity >= n (>= MIN_RUN_CAPACITY):
    bounds the set of pallas merge-kernel shapes to O(log) per job."""
    p = MIN_RUN_CAPACITY
    while p < n:
        p *= 2
    return p


def resolve_run_engine(engine: str) -> str:
    """Resolve the pairwise run-merge backend: "pallas" (the device
    merge-path kernel), "host" (vectorized numpy/native merge — the
    correctness twin, and the fast choice on the XLA CPU backend), or
    "auto" (host on CPU, pallas elsewhere)."""
    if engine == "auto":
        return "host" if jax.default_backend() == "cpu" else "pallas"
    if engine not in ("host", "pallas"):
        from uda_tpu.utils.errors import MergeError

        raise MergeError(f"unknown run merge engine {engine!r}")
    return engine


def resolve_native_rows_merge():
    """The native linear two-pointer row merge when built, else None.
    Resolved ONCE per consumer so a cold .so compiles before any merge
    runs under a forest lock (a make inside the lock would stall the
    whole staging pool)."""
    from uda_tpu import native
    from uda_tpu.utils.ifile import native_enabled

    if native_enabled() and native.build():
        return native.merge_rows_native
    return None


def merge_split_point(a_rows: np.ndarray, b_rows: np.ndarray,
                      m: int) -> int:
    """Merge-path partition with the ties-to-``a`` rule: the unique
    ``ia`` (with ``ib = m - ia``) such that the first ``m`` rows of the
    stable merge are exactly ``merge(a[:ia], b[:ib])`` — i.e.
    ``a[ia-1] <= b[ib]`` (a tie sends the ``a`` row first, so equality
    keeps it in the prefix) and ``b[ib-1] < a[ia]`` (an equal ``a`` row
    would precede, so the ``b`` prefix row must be strictly smaller).
    O(log n) full-row lexicographic compares; used to split one large
    pairwise merge across threads without breaking stability."""
    na, nb = int(a_rows.shape[0]), int(b_rows.shape[0])
    lo, hi = max(0, m - nb), min(na, m)
    while lo < hi:
        ia = (lo + hi) // 2
        ib = m - ia
        # a[ia] <= b[ib-1]: that a row ties-or-precedes the b prefix
        # row, so it belongs in the prefix too -> ia is too small
        if ia < na and ib > 0 and tuple(a_rows[ia]) <= tuple(b_rows[ib - 1]):
            lo = ia + 1
        else:
            hi = ia
    return lo


def merge_rows_split_into(a_rows: np.ndarray, b_rows: np.ndarray,
                          out: np.ndarray, parts: int = 2) -> bool:
    """Native linear merge of two sorted row runs into a caller-owned
    ``out`` buffer, split across ``parts`` threads at merge-path
    partition points (each part is an independent contiguous-slice
    merge; the native call releases the GIL, so parts genuinely run in
    parallel). Stability (ties to ``a``) is preserved by construction —
    see :func:`merge_split_point`. Returns False when the native
    library isn't built (caller falls back); single-part calls degrade
    to one plain native merge."""
    from uda_tpu import native

    na, nb = int(a_rows.shape[0]), int(b_rows.shape[0])
    total = na + nb
    parts = max(1, min(int(parts), max(1, total)))
    if parts == 1:
        return native.merge_rows_native_into(a_rows, b_rows, out)
    if not native.available():
        return False
    cuts_a = [0]
    for p in range(1, parts):
        cuts_a.append(merge_split_point(a_rows, b_rows, total * p // parts))
    cuts_a.append(na)
    # every part reports into ok: a part whose native call refuses
    # (e.g. the .so momentarily unloaded by a concurrent rebuild) left
    # stale pool-lease bytes in its out slice — the caller MUST fall
    # back, so a single False fails the whole split
    ok = [False] * parts

    def _part(idx: int, a: np.ndarray, b: np.ndarray, o: np.ndarray):
        ok[idx] = bool(native.merge_rows_native_into(a, b, o))

    threads = []
    for p in range(parts):
        mlo = total * p // parts if p else 0
        mhi = total * (p + 1) // parts if p < parts - 1 else total
        alo, ahi = cuts_a[p], cuts_a[p + 1]
        blo, bhi = mlo - alo, mhi - ahi
        args = (p, a_rows[alo:ahi], b_rows[blo:bhi], out[mlo:mhi])
        if p < parts - 1:
            t = threading.Thread(target=_part, args=args, daemon=True)
            t.start()
            threads.append(t)
        else:
            _part(*args)  # last part inline
    for t in threads:
        t.join()
    return all(ok)


class RowBufferPool:
    """Reusable pre-allocated host uint32 row buffers.

    Two hot paths lease from it: stage workers building device-bound
    row matrices (recycled once the jax.device_put transfer completes)
    and the host-engine pipeline's merge outputs (recycled when the run
    merges into a larger one) — the forest's merge traffic is
    k*log2(k) segment-loads, and a fresh np.empty per merge would
    page-fault every output byte (the PR 6 large-alloc lesson).
    Buffers are flat uint32 arrays reshaped per lease, so one big
    early buffer serves every later exact-size request; the free list
    is bounded so a pathological size spread cannot hoard host
    memory."""

    MAX_FREE = 8

    def __init__(self, lock_class: str = "stage.bufpool"):
        from uda_tpu.utils.locks import TrackedLock

        self._lock = TrackedLock(lock_class)
        self._free: list[np.ndarray] = []

    def lease(self, rows: int, cols: int) -> np.ndarray:
        need = rows * cols
        got = None
        with self._lock:
            for i, buf in enumerate(self._free):
                if buf.size >= need:
                    got = self._free.pop(i)
                    metrics.add("stage.buffer.reuses")
                    break
        if got is None:
            got = np.empty(need, np.uint32)
        # ledger key = the base buffer's data pointer: release() walks
        # any view back to the same base, so both sides reproduce it
        resledger.acquire("pool.lease", key=_buf_key(got),
                          owner=id(self), amount=need * 4)
        return got[:need].reshape(rows, cols)

    def release(self, view: Optional[np.ndarray]) -> None:
        if view is None:
            return
        base = view
        while base.base is not None:
            base = base.base
        flat = np.asarray(base, np.uint32).reshape(-1)
        resledger.settle("pool.lease", key=_buf_key(flat), owner=id(self))
        with self._lock:
            self._free.append(flat)
            self._free.sort(key=lambda b: b.size)
            del self._free[self.MAX_FREE:]


def lex_cols_sorted(cols: Sequence[np.ndarray]) -> bool:
    """Vectorized lexicographic monotonicity over parallel uint columns:
    True when every adjacent pair is non-decreasing under first-column
    priority (O(n·k) — the already-sorted fast path that replaces an
    O(n log n) lexsort for Hadoop's map-side-sorted segments)."""
    n = cols[0].shape[0]
    if n < 2:
        return True
    lt = cols[0][:-1] < cols[0][1:]
    eq = cols[0][:-1] == cols[0][1:]
    for c in cols[1:]:
        lt = lt | (eq & (c[:-1] < c[1:]))
        eq = eq & (c[:-1] == c[1:])
    return bool(np.all(lt | eq))


def run_row_order(packed: packing.PackedKeys) -> Optional[np.ndarray]:
    """Per-run sort order under (words, len) — which equals comparator
    order for within-width keys. Returns None when the run is already
    sorted (identity order; the map-side sort contract the reference's
    merge leaned on — it never re-sorted segments, MergeManager.cc:
    47-63), else the int64 lexsort permutation. Stable: equal keys keep
    arrival order."""
    kw = packed.key_words.shape[1]
    cols = [packed.key_words[:, c] for c in range(kw)] \
        + [packed.key_lens.astype(np.uint32)]
    if lex_cols_sorted(cols):
        return None
    # np.lexsort: LAST key is primary -> reversed column priority
    return np.lexsort(tuple(reversed(cols))).astype(np.int64)


def fill_run_rows(rows: np.ndarray, packed: packing.PackedKeys,
                  order: Optional[np.ndarray], seg_index: int) -> None:
    """Fill a (cap >= n, kw+3) uint32 row matrix with the sorted
    composite-key rows (words..., content length, segment index,
    ORIGINAL row index) and PAD_WORD tail. Writes the sorted rows
    directly (no build-then-permute copy); ``order=None`` = identity."""
    n = packed.num_records
    kw = packed.key_words.shape[1]
    if order is None:
        rows[:n, :kw] = packed.key_words
        rows[:n, kw] = packed.key_lens.astype(np.uint32)
        rows[:n, kw + 2] = np.arange(n, dtype=np.uint32)
    else:
        rows[:n, :kw] = packed.key_words[order]
        rows[:n, kw] = packed.key_lens[order].astype(np.uint32)
        rows[:n, kw + 2] = order.astype(np.uint32)
    rows[:n, kw + 1] = np.uint32(seg_index)
    if rows.shape[0] > n:
        rows[n:] = PAD_WORD


def merge_row_pair(a_rows, b_rows, a_valid: int, b_valid: int,
                   engine: str, interpret: bool = False,
                   native_merge=None):
    """Merge two sorted composite-key row runs into one. Host engine:
    linear two-pointer native merge when built (ties to ``a`` = the
    earlier run, preserving the composite-key stability); lexsort of
    the concatenation otherwise. Pallas engine: the O(n) merge-path
    kernel — every column is part of the composite key (words, len,
    seg, row), rows are totally ordered, so the kernel's internal
    tie-break never decides anything."""
    if engine == "host":
        if native_merge is not None:
            merged = native_merge(np.asarray(a_rows[:a_valid]),
                                  np.asarray(b_rows[:b_valid]))
            if merged is not None:
                return merged
        rows = np.concatenate([a_rows[:a_valid], b_rows[:b_valid]])
        order = np.lexsort(tuple(rows[:, c]
                                 for c in range(rows.shape[1] - 1, -1, -1)))
        return rows[order]
    return merge_sorted_pair(a_rows, b_rows,
                             num_keys=int(a_rows.shape[1]),
                             interpret=interpret)


def sorted_batch_order(batch: RecordBatch, kt: KeyType, width: int) -> np.ndarray:
    """Device-computed stable sort permutation for one batch."""
    with metrics.timer("pack"):
        packed = packing.pack_keys(batch, kt, width)
    with metrics.timer("device_sort"):
        return sort.sort_permutation(packed)


def merge_batches(batches: Sequence[RecordBatch], kt: KeyType,
                  width: int) -> RecordBatch:
    """Merge k sorted (or unsorted — the sort is total) segments on device.

    Overflow ranks are computed across the *concatenation* so they are
    globally consistent (see merge_runs caveat in uda_tpu.ops.sort).
    """
    cat = RecordBatch.concat(list(batches))
    order = sorted_batch_order(cat, kt, width)
    return cat.take(order)


def merge_batches_host(batches: Sequence[RecordBatch], kt: KeyType) -> RecordBatch:
    """Host oracle: stable sort of the concatenation by comparator order.

    Equal keys keep (segment, record) arrival order — the same contract
    the device path's stable sort provides.
    """
    cat = RecordBatch.concat(list(batches))
    idx = list(range(cat.num_records))
    keys = [cat.key(i) for i in idx]
    cmp = kt.compare
    order = sorted(idx, key=functools.cmp_to_key(
        lambda i, j: cmp(keys[i], keys[j])))
    return cat.take(np.asarray(order, dtype=np.int64))


def merge_record_streams(streams: Sequence[Iterator[Tuple[bytes, bytes]]],
                         kt: KeyType) -> Iterator[Tuple[bytes, bytes]]:
    """Streaming k-way heap merge over record iterators — the literal
    analogue of the reference's MergeQueue::next (MergeQueue.h:276-427).
    Memory held = one record per stream, so file-backed runs (the RPQ
    phase over SuperSegments) merge with bounded memory."""

    cmp = kt.compare

    class _Cursor:
        __slots__ = ("it", "seq", "head")

        def __init__(self, it: Iterator[Tuple[bytes, bytes]], seq: int):
            self.it = it
            self.seq = seq
            self.head: Optional[Tuple[bytes, bytes]] = next(it, None)

        def advance(self) -> None:
            self.head = next(self.it, None)

        def __lt__(self, other: "_Cursor") -> bool:
            c = cmp(self.head[0], other.head[0])
            if c != 0:
                return c < 0
            return self.seq < other.seq  # stable by segment order

    heap = [c for c in (_Cursor(iter(s), i) for i, s in enumerate(streams))
            if c.head is not None]
    heapq.heapify(heap)
    while heap:
        cur = heap[0]
        yield cur.head
        cur.advance()
        if cur.head is not None:
            heapq.heapreplace(heap, cur)
        else:
            heapq.heappop(heap)


def merge_iter_host(batches: Sequence[RecordBatch],
                    kt: KeyType) -> Iterator[Tuple[bytes, bytes]]:
    """merge_record_streams over in-memory batches."""
    return merge_record_streams([b.iter_records() for b in batches], kt)


# -- two-phase device sort ---------------------------------------------------

def resolve_merge_mode(mode: str, num_batches: int) -> str:
    """Batch-count/backend-aware routing between the whole-shuffle
    re-sort ("resort") and the two-phase partial-sort + HBM merge tree
    ("two_phase"). "auto" takes two-phase on real accelerators (the
    re-sort's final permutation gather is the small-batch bottleneck
    the take-ramp exposed: 0.15 GB/s at 2^16 rows, BENCH_NOTES_r05) and
    keeps the re-sort on the XLA CPU backend, where one lexsort-shaped
    sort beats Python-orchestrated pairwise folds. Resolution is EAGER,
    never inside a jitted trace."""
    if mode not in ("auto", "on", "off"):
        from uda_tpu.utils.errors import MergeError

        raise MergeError(f"unknown merge two-phase mode {mode!r}")
    if num_batches < 2:
        return "resort"
    if mode == "on":
        return "two_phase"
    if mode == "off":
        return "resort"
    return "two_phase" if jax.default_backend() == "tpu" else "resort"


def merge_batches_two_phase(batches: Sequence[RecordBatch], kt: KeyType,
                            width: int, engine: str = "auto",
                            interpret: Optional[bool] = None) -> RecordBatch:
    """Two-phase merge of k segments: per-run partial sort (usually just
    the monotonicity check) + pairwise HBM-resident merge tree, instead
    of re-sorting the concatenation (see module docstring).

    Byte-identical to :func:`merge_batches` by construction: the rows
    carry (words, len, segment, row) as a total composite key, so equal
    comparator keys order by original (segment, row) arrival — exactly
    the stable-sort contract. Overflow keys (content wider than the
    carried width) need a globally consistent rank column, which only
    the concatenation view can provide — those fall back to
    :func:`merge_batches` (correctness never depends on the fast path
    applying)."""
    # the concatenation is only needed for the final take — defer it so
    # the fallback paths (which concat inside merge_batches) never hold
    # two transient copies of a multi-GB shuffle
    if sum(b.num_records for b in batches) == 0 or len(batches) < 2:
        return merge_batches(batches, kt, width)
    engine = resolve_run_engine(engine)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    native_merge = resolve_native_rows_merge() if engine == "host" else None
    runs: list[tuple] = []  # (rows, valid) per non-empty segment
    kw = width // 4
    for seg_index, b in enumerate(batches):
        n = b.num_records
        if n == 0:
            continue
        packed = packing.pack_keys(b, kt, width)
        if int(np.max(packed.key_lens, initial=0)) > width:
            return merge_batches(batches, kt, width)  # overflow fallback
        cap = next_run_capacity(n) if engine == "pallas" else n
        rows = np.empty((cap, kw + ROW_EXTRA_COLS), np.uint32)
        fill_run_rows(rows, packed, run_row_order(packed), seg_index)
        if engine == "pallas":
            rows = jax.device_put(rows)
        runs.append((rows, n))
    if not runs:  # unreachable given the record-count early-out; guard
        return merge_batches(batches, kt, width)
    metrics.add("merge.pipeline.two_phase")
    rows, valid = _fold_runs(runs, engine, interpret, native_merge)
    rows = np.asarray(rows)[:valid]
    seg_col = rows[:, kw + 1].astype(np.int64)
    row_col = rows[:, kw + 2].astype(np.int64)
    sizes = np.asarray([b.num_records for b in batches], np.int64)
    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    cat = RecordBatch.concat(list(batches))
    return cat.take(offsets[seg_col] + row_col)


def pad_rows_to(rows, capacity: int):
    """Pad a device run up to ``capacity`` rows with PAD_WORD rows.
    Padding rows sort strictly last, so the validity prefix is
    preserved; capacities stay powers of two, keeping pallas kernel
    shapes in the O(log) compiled set. The ONE implementation of the
    pad-up invariant — shared by :func:`_fold_runs` and the overlap
    forest's leftover merge (merger.overlap), which encode the same
    binary-counter fold over different run carriers."""
    cur = int(rows.shape[0])
    if cur >= capacity:
        return rows
    pad = np.full((capacity - cur, int(rows.shape[1])), PAD_WORD,
                  np.uint32)
    return jax.numpy.concatenate([rows, jax.device_put(pad)], axis=0)


def _fold_runs(runs: list, engine: str, interpret: bool, native_merge):
    """Binary-counter fold of sorted (rows, valid) runs: equal
    capacity classes merge immediately, leftovers merge smallest-first
    (pallas runs pad the smaller operand up to the larger capacity —
    :func:`pad_rows_to`). Same fold shape as the overlap forest's
    _insert/_merge_leftovers (merger.overlap), which carries _Run
    objects with locks and pool leases instead of bare (rows, valid)
    tuples — a semantic change here must land there too."""
    forest: dict[int, tuple] = {}  # bucket -> (rows, valid)
    for rows, valid in runs:
        bucket = next_run_capacity(valid)
        while bucket in forest:
            o_rows, o_valid = forest.pop(bucket)
            rows = merge_row_pair(o_rows, rows, o_valid, valid, engine,
                                  interpret, native_merge)
            valid += o_valid
            bucket *= 2
        forest[bucket] = (rows, valid)
    acc_rows, acc_valid = None, 0
    for bucket in sorted(forest):
        rows, valid = forest[bucket]
        if acc_rows is None:
            acc_rows, acc_valid = rows, valid
            continue
        if engine == "pallas" and acc_rows.shape[0] < rows.shape[0]:
            acc_rows = pad_rows_to(acc_rows, int(rows.shape[0]))
        acc_rows = merge_row_pair(acc_rows, rows, acc_valid, valid, engine,
                                  interpret, native_merge)
        acc_valid += valid
    return acc_rows, acc_valid
