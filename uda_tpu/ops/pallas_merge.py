"""Pallas merge: pairwise merge of sorted runs on the lanes engine.

The device-native replacement for the reference's network-levitated
incremental merge (reference src/Merger/MergeQueue.h:276-427: as each
segment lands it joins the k-way heap). Whole-run ``lax.sort`` is
O(n log n) and re-does all comparison work every time a new run lands;
merging two already-sorted runs is O(n).

Implementation: one merge PASS of the lanes bitonic pipeline
(uda_tpu.ops.pallas_sort). The two runs are packed into the
``uint32[32, 2L]`` lanes layout exactly the way the pipeline's tile
sort would have left them — A ascending in lanes [0, L), B stored
DESCENDING in lanes [L, 2L) (so the pair is bitonic as stored), with
the arrival index in the tie-break row and +inf-key padding lanes on
the ascending tail / descending front. ``_pass_splits`` +
``_merge_pass`` then merge them like any other pass. This reuses the
ONE merge kernel that is validated on real TPU hardware; the earlier
row-matrix merge kernel variant was unloadable under Mosaic (minor-dim
slices of a [tile, W] block violate the 128-lane tiling rule — the
same layout problem that motivated the lanes design in the first
place).

Rows travel as uint32[n, W] with the first ``num_keys`` columns the
big-endian key words (the uda_tpu.ops.packing layout); W <= 31.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from uda_tpu.ops import pallas_sort
from uda_tpu.ops.pallas_sort import _lex_lt, _merge_pass, _pass_splits

__all__ = ["merge_sorted_pair", "merge_splits"]

_INF = np.uint32(0xFFFFFFFF)

# lexicographic a < b over tuples of uint32 arrays — shared with the
# lanes kernels (single implementation of the compare semantics)
_key_less = _lex_lt


@partial(jax.jit, static_argnames=("tile", "num_keys"))
def merge_splits(a, b, tile: int, num_keys: int):
    """For each output tile boundary d = t*tile, the number of A rows in
    the first d merged rows (merge-path diagonal intersection). Returns
    int32[num_tiles]. Vectorized binary search, 32 fixed iterations.

    (Host-callable analysis utility; the kernel path computes its
    windows with pallas_sort._pass_splits instead.)"""
    na, nb = a.shape[0], b.shape[0]
    num_tiles = (na + nb + tile - 1) // tile
    d = jnp.arange(num_tiles, dtype=jnp.int32) * tile

    def key_at(arr, idx):
        idx = jnp.clip(idx, 0, arr.shape[0] - 1)
        return tuple(arr[idx, c] for c in range(num_keys))

    lo = jnp.maximum(0, d - nb)
    hi = jnp.minimum(d, na)

    def body(_, carry):
        lo, hi = carry
        mid = (lo + hi + 1) // 2  # candidate i: rows of A taken
        j = d - mid               # rows of B taken
        # valid split needs A[mid-1] <= B[j]  (A wins ties)
        a_key = key_at(a, mid - 1)
        b_key = key_at(b, jnp.clip(j, 0, nb - 1))
        a_le_b = ~_key_less(b_key, a_key)      # A[mid-1] <= B[j]
        ok = (mid <= 0) | (j >= nb) | a_le_b
        lo = jnp.where(ok, mid, lo)
        hi = jnp.where(ok, hi, mid - 1)
        return lo, hi

    lo, hi = lax.fori_loop(0, 32, body, (lo, hi))
    return lo.astype(jnp.int32)


def _pack_bitonic_pair(a, b, ncols: int, nrows: int, tb: int, L: int):
    """Two [n, W] sorted runs -> one [nrows, 2L] bitonic-as-stored lanes
    pair: the leading ``ncols`` columns of each run in rows [0, ncols),
    the GLOBAL arrival index (= row id into concat(a, b)) in row ``tb``,
    +inf keys/tie-break in the L-n padding lanes (payload rows of
    padding lanes are never read). B is stored DESCENDING (flip) so the
    concatenation is bitonic as stored and padding sits at its front."""
    na, nb = a.shape[0], b.shape[0]

    def run_lanes(r, n, base, descending):
        lanes = jnp.full((nrows, L), _INF, jnp.uint32)
        lanes = lax.dynamic_update_slice(
            lanes, r[:, :ncols].T.astype(jnp.uint32), (0, 0))
        idx = jnp.arange(L, dtype=jnp.uint32)
        lanes = lanes.at[tb].set(jnp.where(idx < n, base + idx, _INF))
        return jnp.flip(lanes, axis=1) if descending else lanes

    return jnp.concatenate([run_lanes(a, na, 0, False),
                            run_lanes(b, nb, na, True)], axis=1)


def _ceil_runs(na: int, nb: int, tile: int) -> int:
    # a single merge pass only needs L % tile == 0 (sort_lanes' pass
    # CASCADE is what needs powers of two), so ceil-to-tile padding
    # avoids up-to-2x wasted lanes on the overlapped merger's hot path
    return max(tile, -(-max(na, nb) // tile) * tile)


@partial(jax.jit, static_argnames=("num_keys", "tile", "interpret"))
def _merge_sorted_pair_keys8(a, b, num_keys: int, tile: int,
                             interpret: bool):
    """keys8 variant: the merge network runs on an 8-row keys-only pair
    (key words + the arrival-index tie-break, which doubles as the
    GLOBAL ROW INDEX into concat(a, b)), and the full-width rows move
    once via an XLA gather by the merged tie-break row. 4x less VPU and
    HBM work in the kernel than the 32-row pass; requires
    num_keys <= 7 (key rows + tie-break fit one 8-row sublane tile)."""
    na, nb = a.shape[0], b.shape[0]
    tb = 7
    L = _ceil_runs(na, nb, tile)
    x8 = _pack_bitonic_pair(a, b, num_keys, 8, tb, L)
    splits = _pass_splits(x8, jnp.int32(L), jnp.bool_(True), tile,
                          num_keys, tb)
    out8 = _merge_pass(x8, splits, tile, num_keys, tb,
                       interpret=interpret)
    perm = out8[tb, :na + nb].astype(jnp.int32)
    cat = jnp.concatenate([a, b], axis=0)
    return jnp.take(cat.T, perm, axis=1,
                    unique_indices=True, mode="clip").T


@partial(jax.jit, static_argnames=("num_keys", "tile", "interpret",
                                   "two_phase"))
def _merge_sorted_pair_jit(a, b, num_keys: int, tile: int, interpret: bool,
                           two_phase: bool):
    """Shape-specialized core: jit so repeat calls at the same (na, nb)
    hit the executable cache instead of re-tracing the pallas_call
    (the overlapped merger calls this many times per job)."""
    na, nb, wcols = a.shape[0], b.shape[0], a.shape[1]
    tb = pallas_sort.TB_ROW_DEFAULT
    L = _ceil_runs(na, nb, tile)
    x = _pack_bitonic_pair(a, b, wcols, pallas_sort.ROWS, tb, L)
    splits = _pass_splits(x, jnp.int32(L), jnp.bool_(True), tile,
                          num_keys, tb)
    out = _merge_pass(x, splits, tile, num_keys, tb, interpret=interpret,
                      two_phase=two_phase)
    return out[:wcols, :na + nb].T


def merge_sorted_pair(a, b, num_keys: int, tile: int = 512,
                      interpret: bool = False, two_phase: bool = False,
                      keys8: bool = False):
    """Merge two key-sorted row matrices into one (stable: A's rows
    precede B's on equal keys). ``a``/``b``: uint32[n, W] with key words
    in the leading ``num_keys`` columns, W <= 31. The output has
    a.shape[0]+b.shape[0] rows. ``two_phase`` selects the keys-view +
    in-kernel payload-gather kernel variant (see
    pallas_sort.sort_lanes); ``keys8`` runs the network on an 8-row
    keys-only pair and moves full rows once via an XLA gather
    (num_keys <= 7; record width unconstrained by the lanes layout)."""
    if tile <= 0 or (tile & (tile - 1)) != 0 or tile % 128:
        raise ValueError(f"tile must be a power of two multiple of 128, "
                         f"got {tile} (the lanes merge kernel requires "
                         "it)")
    if two_phase and keys8:
        raise ValueError("two_phase and keys8 are mutually exclusive")
    a = jnp.asarray(a, jnp.uint32)
    b = jnp.asarray(b, jnp.uint32)
    if keys8 and num_keys > 7:
        raise ValueError(f"keys8 needs num_keys <= 7, got {num_keys}")
    if not keys8 and a.shape[1] > pallas_sort.TB_ROW_DEFAULT:
        raise ValueError(f"{a.shape[1]} record words do not fit the "
                         f"{pallas_sort.ROWS}-row lanes layout")
    if a.shape[0] == 0:
        return b
    if b.shape[0] == 0:
        return a
    if keys8:
        return _merge_sorted_pair_keys8(a, b, num_keys, tile, interpret)
    return _merge_sorted_pair_jit(a, b, num_keys, tile, interpret,
                                  two_phase)
