"""Pallas merge kernel: pairwise merge of sorted runs.

The device-native replacement for the reference's network-levitated
incremental merge (reference src/Merger/MergeQueue.h:276-427: as each
segment lands it joins the k-way heap). Whole-run ``lax.sort`` is
O(n log n) and re-does all comparison work every time a new run lands;
merging two already-sorted runs is O(n). This kernel implements the
classic merge-path algorithm, TPU-style:

1. XLA side (``_merge_splits``): a vectorized binary search finds, for
   each output tile of T rows, the (i, j) split of the merge diagonal —
   how many rows of A and of B precede the tile. Multi-word lexicographic
   key comparison, with A-before-B on ties (stability by arrival).
2. Pallas side (``_merge_tile_kernel``): each grid step DMAs its A and B
   slices from HBM (dynamic offsets from the prefetched splits), pads
   them to T with +inf keys, concatenates A with *reversed* B — a
   bitonic sequence — and runs a vectorized bitonic-merge network
   (log2(2T) compare-exchange stages over whole rows) whose smallest T
   rows are exactly the tile's output.

Rows travel as uint32[*, W] with the first ``num_keys`` columns the
big-endian key words (the uda_tpu.ops.packing layout). A tie-break
column (global arrival index) is appended internally so the bitonic
network — unstable by itself — reproduces stable merge order.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["merge_sorted_pair", "merge_splits"]


def _key_less(a_cols, b_cols):
    """Lexicographic a < b over tuples of uint32 column arrays."""
    lt = jnp.zeros(a_cols[0].shape, jnp.bool_)
    eq = jnp.ones(a_cols[0].shape, jnp.bool_)
    for a, b in zip(a_cols, b_cols):
        lt = lt | (eq & (a < b))
        eq = eq & (a == b)
    return lt


@partial(jax.jit, static_argnames=("tile", "num_keys"))
def merge_splits(a, b, tile: int, num_keys: int):
    """For each output tile boundary d = t*tile, the number of A rows in
    the first d merged rows (merge-path diagonal intersection). Returns
    int32[num_tiles]. Vectorized binary search, 32 fixed iterations."""
    na, nb = a.shape[0], b.shape[0]
    num_tiles = (na + nb + tile - 1) // tile
    d = jnp.arange(num_tiles, dtype=jnp.int32) * tile

    def key_at(arr, idx):
        idx = jnp.clip(idx, 0, arr.shape[0] - 1)
        return tuple(arr[idx, c] for c in range(num_keys))

    lo = jnp.maximum(0, d - nb)
    hi = jnp.minimum(d, na)

    def body(_, carry):
        lo, hi = carry
        mid = (lo + hi + 1) // 2  # candidate i: rows of A taken
        j = d - mid               # rows of B taken
        # valid split needs A[mid-1] <= B[j]  (A wins ties)
        a_key = key_at(a, mid - 1)
        b_key = key_at(b, jnp.clip(j, 0, nb - 1))
        a_le_b = ~_key_less(b_key, a_key)      # A[mid-1] <= B[j]
        ok = (mid <= 0) | (j >= nb) | a_le_b
        lo = jnp.where(ok, mid, lo)
        hi = jnp.where(ok, hi, mid - 1)
        return lo, hi

    lo, hi = lax.fori_loop(0, 32, body, (lo, hi))
    return lo.astype(jnp.int32)


def _bitonic_merge_rows(rows, num_keys, total_cols):
    """Vectorized bitonic merge of a bitonic sequence of rows.

    ``rows``: [L, C] uint32 where columns [0, num_keys) are key words and
    column C-1 is the tie-break index; L is a power of two. Returns rows
    sorted ascending by (keys, tie-break).
    """
    L = rows.shape[0]
    stride = L // 2
    while stride >= 1:
        x = rows.reshape(L // (2 * stride), 2, stride, total_cols)
        lo, hi = x[:, 0], x[:, 1]
        lo_keys = tuple(lo[..., c] for c in range(num_keys)) + (lo[..., total_cols - 1],)
        hi_keys = tuple(hi[..., c] for c in range(num_keys)) + (hi[..., total_cols - 1],)
        swap = _key_less(hi_keys, lo_keys)[..., None]
        new_lo = jnp.where(swap, hi, lo)
        new_hi = jnp.where(swap, lo, hi)
        rows = jnp.stack([new_lo, new_hi], axis=1).reshape(L, total_cols)
        stride //= 2
    return rows


def _merge_tile_kernel(splits_ref, a_hbm, brev_hbm, out_ref, scratch_a,
                       scratch_b, sem_a, sem_b, *, tile, num_keys,
                       na, nb, cols):
    # Mosaic has no in-kernel `rev`: B arrives PRE-REVERSED (brev_hbm =
    # flip of the tail-padded B, done in XLA before pallas_call), and the
    # window is addressed from the end so it is already descending.
    t = pl.program_id(0)
    d = t * tile
    i0 = splits_ref[t]
    j0 = d - i0
    # A window [i0, i0+tile): tail-padded input keeps the DMA in bounds
    # (0 <= i0 <= na); invalid rows sit at the ASCENDING tail.
    # B window: brev rows [nb - j0, nb - j0 + tile) correspond to
    # original rows gb = j0 + tile - 1 - r (descending); rows past B's
    # end (gb >= nb) sit at the DESCENDING front. +inf masking at tail /
    # front respectively keeps the concatenation bitonic.
    cp_a = pltpu.make_async_copy(a_hbm.at[pl.ds(i0, tile)], scratch_a, sem_a)
    cp_b = pltpu.make_async_copy(brev_hbm.at[pl.ds(nb - j0, tile)],
                                 scratch_b, sem_b)
    cp_a.start()
    cp_b.start()
    cp_a.wait()
    cp_b.wait()

    inf = jnp.uint32(0xFFFFFFFF)
    ridx = lax.broadcasted_iota(jnp.int32, (tile, 1), 0)
    ga = ridx + i0
    gb = j0 + (tile - 1) - ridx
    a_rows = scratch_a[...]
    b_rows = scratch_b[...]
    a_valid = (ga >= i0) & (ga < na)
    b_valid = (gb >= j0) & (gb < nb)
    # append tie-break column: global arrival index (A first on ties)
    a_tb = jnp.where(a_valid, ga, jnp.int32(-1)).astype(jnp.uint32)
    b_tb = jnp.where(b_valid, gb + na, jnp.int32(-1)).astype(jnp.uint32)
    a_aug = jnp.concatenate([a_rows, a_tb], axis=1)
    b_aug = jnp.concatenate([b_rows, b_tb], axis=1)
    # invalid rows: key -> +inf so they sort last
    def mask(rows_aug, valid):
        key_mask = jnp.where(valid, rows_aug[:, :num_keys],
                             jnp.full((tile, num_keys), inf))
        return jnp.concatenate([key_mask, rows_aug[:, num_keys:]], axis=1)

    a_aug = mask(a_aug, a_valid)
    b_aug = mask(b_aug, b_valid)
    # ascending A ++ descending B = bitonic sequence
    seq = jnp.concatenate([a_aug, b_aug], axis=0)
    merged = _bitonic_merge_rows(seq, num_keys, cols + 1)
    out_ref[...] = merged[:tile, :cols]


@partial(jax.jit, static_argnames=("num_keys", "tile", "interpret"))
def _merge_sorted_pair_jit(a, b, num_keys: int, tile: int, interpret: bool):
    """Shape-specialized core: jit so repeat calls at the same (na, nb)
    hit the executable cache instead of re-tracing the pallas_call
    (the overlapped merger calls this many times per job)."""
    na, nb, cols = a.shape[0], b.shape[0], a.shape[1]
    total = na + nb
    num_tiles = (total + tile - 1) // tile
    padded = num_tiles * tile
    splits = merge_splits(a, b, tile, num_keys)
    # tail-pad each input by one tile: every window ds(start, tile) with
    # start <= n is then in bounds, and invalid rows only ever appear at
    # a window's tail (see kernel comment on bitonicity). B is flipped
    # here (XLA) because Mosaic cannot reverse in-kernel.
    a = jnp.pad(a, ((0, tile), (0, 0)))
    brev = jnp.flip(jnp.pad(b, ((0, tile), (0, 0))), axis=0)

    out = pl.pallas_call(
        partial(_merge_tile_kernel, tile=tile, num_keys=num_keys,
                na=na, nb=nb, cols=cols),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(num_tiles,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((tile, cols), lambda t, s: (t, 0)),
            scratch_shapes=[
                pltpu.VMEM((tile, cols), jnp.uint32),
                pltpu.VMEM((tile, cols), jnp.uint32),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((padded, cols), jnp.uint32),
        interpret=interpret,
    )(splits, a, brev)
    return out[:total]


def merge_sorted_pair(a, b, num_keys: int, tile: int = 512,
                      interpret: bool = False):
    """Merge two key-sorted row matrices into one (stable: A's rows
    precede B's on equal keys). ``a``/``b``: uint32[n, W] with key words
    in the leading ``num_keys`` columns. Row counts are padded up to the
    tile internally; the output has a.shape[0]+b.shape[0] rows."""
    if tile <= 0 or (tile & (tile - 1)) != 0:
        raise ValueError(f"tile must be a power of two, got {tile} "
                         "(the bitonic merge network requires it)")
    a = jnp.asarray(a, jnp.uint32)
    b = jnp.asarray(b, jnp.uint32)
    if a.shape[0] == 0:
        return b
    if b.shape[0] == 0:
        return a
    return _merge_sorted_pair_jit(a, b, num_keys, tile, interpret)
