"""Folded keys cascade: the keys8 pipeline at half lane width.

The keys8 engine (pallas_sort.keys8_sort_perm) spends its VPU time on
compare-exchange stages over an [8, n] array in which only 4 rows carry
data (<= 3 key rows + the tie-break). This module packs TWO
element-halves into the 8 rows — element ``e`` of a folded block of
``2h`` elements lives at lane ``e % h`` in row group ``(e // h) * 4``,
rows ``[k0, k1, k2, tb]`` — so:

- every lane-stride stage (j < h) rolls/selects an [8, h] array that
  holds 2h elements: HALF the per-element data movement of the
  standard layout's [8, 2h];
- the stride-h stage pairs the two row groups at equal lanes — a
  static row-group swap plus selects, NO rolls at all;
- strides above h never occur (bitonic strides are powers of two
  below the span, and e XOR j for j < h never crosses the half bit).

The HBM layout BETWEEN passes is slim: ``[4, n]`` rows
``[k0, k1, k2, tb]`` — the 8-row keys layout's rows 3..6 are always
zero for the <= 3-key shapes this engine serves, so carrying them
through every pass would double the inter-pass HBM traffic and the
merge-pass DMA windows for nothing. Folding becomes free with this
layout: a merge kernel DMAs the A window into the lower 4-row slot
and the B window into the upper one (no in-kernel fold shuffle at
all). The pass bookkeeping (pallas_sort._pass_splits) is row-count
generic and reused as-is with ``tb_row=3``. Requires num_keys <= 3
(keys + tie-break fit the 4-row slot); the TeraSort keyset is exactly
that shape. ``sort_lanes_folded`` keeps the 8-row in/out contract
(slims on entry, rebuilds on exit); ``sort_lanes_folded4`` is the
slim-layout core.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from uda_tpu.ops.pallas_sort import (_LANE, _lex_lt, _pass_splits,
                                      _uint32_struct)

__all__ = ["sort_lanes_folded", "sort_lanes_folded4"]

_INF = np.uint32(0xFFFFFFFF)
_SLOT = 4                # rows per element-half: 3 key rows + tie-break
_TB = 7                  # tie-break row of the standard keys8 layout
_TB4 = 3                 # tie-break row of the slim [4, n] layout


def _emat(h):
    """Element index of every folded cell: [8, h] int32, constant within
    each 4-row slot (lane + h for the upper slot)."""
    lane = lax.broadcasted_iota(jnp.int32, (8, h), 1)
    upper = lax.broadcasted_iota(jnp.int32, (8, 1), 0) >= _SLOT
    return lane + jnp.where(upper, h, 0)


def _cmp_exchange_folded(F, j: int, asc_mat, num_keys: int, h: int):
    """One compare-exchange stage at element stride j on the folded
    layout. ``asc_mat``: [8, h] bool, constant within each slot."""
    rowi = lax.broadcasted_iota(jnp.int32, (8, 1), 0)
    e = _emat(h)
    low = (e & j) == 0
    if j >= h:           # vertical: partner is the other slot, same lane
        other = jnp.concatenate([F[_SLOT:], F[:_SLOT]], axis=0)
    else:                # lane stage: both slots roll identically
        left = jnp.roll(F, -j, axis=1)
        right = jnp.roll(F, j, axis=1)
        other = jnp.where(low, left, right)
    krl = list(range(num_keys)) + [_TB4]
    lt_lo = _lex_lt([F[r] for r in krl],
                    [other[r] for r in krl])[None, :]
    lt_hi = _lex_lt([F[r + _SLOT] for r in krl],
                    [other[r + _SLOT] for r in krl])[None, :]
    # mask logic, not select: Mosaic lowers select-on-i1 operands via an
    # i8->i1 trunci it rejects ("Unsupported target bitwidth for
    # truncation" at [8, tile] on v5e); &/| on masks lower natively
    is_lo = rowi < _SLOT
    lt = (is_lo & lt_lo) | (~is_lo & lt_hi)
    keep_self = (asc_mat == low) == lt
    return jnp.where(keep_self, F, other)


def _tile_sort_kernel_folded(x_ref, o_ref, *, tile, num_keys, alternate):
    t = pl.program_id(0)
    h = tile // 2
    x = x_ref[...]                       # [4, tile] slim layout
    # fold: elements [0, h) stay in the lower slot, [h, tile) move up
    F = jnp.concatenate([x[:, :h], x[:, h:]], axis=0)
    rowi = lax.broadcasted_iota(jnp.int32, (8, 1), 0)
    e = _emat(h)
    # stability: global arrival index into both tie-break rows
    g = (e + t * tile).astype(jnp.uint32)
    F = jnp.where((rowi == _TB4) | (rowi == _TB), g, F)
    if alternate:
        tile_asc = (t % 2) == 0
    else:
        tile_asc = jnp.bool_(True)
    k = 2
    while k <= tile:
        if k == tile:
            asc = jnp.broadcast_to(tile_asc, (8, h))
        else:
            asc = ((e & k) == 0) == tile_asc
        j = k // 2
        while j >= 1:
            F = _cmp_exchange_folded(F, j, asc, num_keys, h)
            j //= 2
        k *= 2
    o_ref[...] = jnp.concatenate([F[:_SLOT], F[_SLOT:]], axis=1)


@partial(jax.jit, static_argnames=("tile", "num_keys", "alternate",
                                   "interpret"))
def _tile_sort_folded(x, tile: int, num_keys: int, alternate: bool,
                      interpret: bool = False):
    rows, n = x.shape
    return pl.pallas_call(
        partial(_tile_sort_kernel_folded, tile=tile, num_keys=num_keys,
                alternate=alternate),
        grid=(n // tile,),
        in_specs=[pl.BlockSpec((rows, tile), lambda t: (0, t))],
        out_specs=pl.BlockSpec((rows, tile), lambda t: (0, t)),
        out_shape=_uint32_struct((rows, n), x),
        interpret=interpret,
    )(x)


def _merge_pass_kernel_folded(splits_ref, splits_nxt_ref, x_hbm, o_ref,
                              a_bufs, b_bufs, sem_a, sem_b, *, tile,
                              num_keys, split_blk):
    """One output tile of one merge pass, folded: same DMA double
    buffering and window construction as pallas_sort._merge_pass_kernel
    (see there for the rank bookkeeping), but over the slim [4, n] HBM
    layout — each window DMA moves 4 rows, and stacking the A window
    (lower slot) on the B window (upper slot) IS the folded [8, tile]
    array, so the 2*tile-element network starts with no fold shuffle;
    every lane stage moves half the standard layout's data and the
    first stage (stride=tile) is a row-group swap.

    MAINTENANCE: the DMA issue/wait protocol, the splits plumbing, and
    the non-negative-shift pltpu.roll contract are a deliberate mirror
    of pallas_sort._merge_pass_kernel (kept separate so the
    hardware-validated kernel stays untouched); any hardware-erratum
    fix applied there MUST be applied here too."""
    t = pl.program_id(0)
    nt = pl.num_programs(0)
    s = t % split_blk
    slot = t % 2
    win = tile + _LANE

    def issue(spl, slot):
        a_cp = pltpu.make_async_copy(
            x_hbm.at[:, pl.ds(spl[s, 0] * _LANE, win)], a_bufs.at[slot],
            sem_a.at[slot])
        b_cp = pltpu.make_async_copy(
            x_hbm.at[:, pl.ds(spl[s, 3] * _LANE, win)], b_bufs.at[slot],
            sem_b.at[slot])
        a_cp.start()
        b_cp.start()

    @pl.when(t == 0)
    def _():
        issue(splits_ref, 0)

    @pl.when(t + 1 < nt)
    def _():
        issue(splits_nxt_ref, (t + 1) % 2)

    pltpu.make_async_copy(x_hbm.at[:, pl.ds(0, win)], a_bufs.at[slot],
                          sem_a.at[slot]).wait()
    pltpu.make_async_copy(x_hbm.at[:, pl.ds(0, win)], b_bufs.at[slot],
                          sem_b.at[slot]).wait()

    shift_a = splits_ref[s, 1]
    thr_a = splits_ref[s, 2]
    shift_b = splits_ref[s, 4]
    thr_b = splits_ref[s, 5]
    out_asc = splits_ref[s, 6] != 0

    r_idx = lax.broadcasted_iota(jnp.int32, (1, tile), 1)
    rowi4 = lax.broadcasted_iota(jnp.int32, (_SLOT, 1), 0)
    is_key_row = (rowi4 < num_keys) | (rowi4 == _TB4)

    a_rows = pltpu.roll(a_bufs[slot], shift_a, 1)[:, :tile]
    a_rows = jnp.where(is_key_row & (r_idx >= thr_a),
                       jnp.broadcast_to(_INF, a_rows.shape), a_rows)
    b_rows = pltpu.roll(b_bufs[slot], shift_b, 1)[:, :tile]
    b_rows = jnp.where(is_key_row & (r_idx < thr_b),
                       jnp.broadcast_to(_INF, b_rows.shape), b_rows)

    # A = elements [0, tile) -> lower slot; B = [tile, 2*tile) -> upper
    F = jnp.concatenate([a_rows, b_rows], axis=0)
    asc = jnp.broadcast_to(out_asc, (8, tile))
    j = tile
    while j >= 1:
        F = _cmp_exchange_folded(F, j, asc, num_keys, tile)
        j //= 2
    # ascending output keeps the smallest tile elements = the lower
    # slot; descending keeps positions [tile, 2*tile) = the upper
    o_ref[...] = jnp.where(jnp.broadcast_to(out_asc, (_SLOT, tile)),
                           F[:_SLOT], F[_SLOT:])


@partial(jax.jit, static_argnames=("tile", "num_keys", "interpret"))
def _merge_pass_folded(x, splits, tile: int, num_keys: int,
                       interpret: bool = False):
    rows, n = x.shape
    split_blk = min(8, n // tile)
    splits_nxt = jnp.concatenate([splits[1:], splits[-1:]], axis=0)
    blk = pl.BlockSpec((split_blk, 8), lambda t: (t // split_blk, 0),
                       memory_space=pltpu.SMEM)
    return pl.pallas_call(
        partial(_merge_pass_kernel_folded, tile=tile, num_keys=num_keys,
                split_blk=split_blk),
        grid=(n // tile,),
        in_specs=[blk, blk, pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((rows, tile), lambda t: (0, t)),
        scratch_shapes=[
            pltpu.VMEM((2, rows, tile + _LANE), jnp.uint32),
            pltpu.VMEM((2, rows, tile + _LANE), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        out_shape=_uint32_struct((rows, n), x),
        interpret=interpret,
    )(splits, splits_nxt, x)


def sort_lanes_folded4(x4, num_keys: int, tile: int = 1024,
                       interpret: bool = False):
    """The slim-layout core: ``x4`` is uint32[4, n] rows
    ``[k0, k1, k2, tb]`` (row 3 is overwritten with the arrival index);
    returns the sorted [4, n] array. Half the standard keys8 pipeline's
    network work AND half its inter-pass HBM traffic / DMA window
    bytes. ``tile`` must be a power-of-two multiple of 256 (the folded
    lane width tile/2 must stay lane-aligned)."""
    x4 = jnp.asarray(x4, jnp.uint32)
    rows, n = x4.shape
    if rows != _SLOT:
        raise ValueError(f"slim folded cascade needs a 4-row array, "
                         f"got {rows} rows")
    if not 0 < num_keys <= 3:
        raise ValueError(f"folded cascade needs num_keys <= 3, got "
                         f"{num_keys}")
    if tile & (tile - 1) or tile % (2 * _LANE):
        raise ValueError(f"tile={tile} must be a power of two multiple "
                         f"of {2 * _LANE}")
    if n % tile or (n // tile) & (n // tile - 1):
        raise ValueError(f"n={n} must be a power-of-two multiple of "
                         f"tile={tile}")
    levels = int(np.log2(n // tile))
    x4 = _tile_sort_folded(x4, tile, num_keys, alternate=levels > 0,
                           interpret=interpret)
    if levels == 0:
        return x4

    def body(lvl, x4):
        run_len = jnp.int32(tile) << lvl
        final = lvl == levels - 1
        splits = _pass_splits(x4, run_len, final, tile, num_keys, _TB4)
        return _merge_pass_folded(x4, splits, tile, num_keys,
                                  interpret=interpret)

    return lax.fori_loop(0, levels, body, x4)


def sort_lanes_folded(x, num_keys: int, tile: int = 1024,
                      interpret: bool = False):
    """Drop-in for ``pallas_sort.sort_lanes(x, num_keys, tb_row=7)`` on
    8-row keys arrays with ``num_keys <= 3``: same output contract
    (rows 3..6 zeroed, row 7 = arrival index), half the network work
    and half the inter-pass HBM traffic (the pipeline itself runs on
    the slim [4, n] layout — see sort_lanes_folded4)."""
    x = jnp.asarray(x, jnp.uint32)
    rows, n = x.shape
    if rows != 8:
        raise ValueError(f"folded cascade needs an 8-row keys array, "
                         f"got {rows} rows")
    x4 = jnp.concatenate([x[:_TB4], x[_TB:_TB + 1]], axis=0)
    out4 = sort_lanes_folded4(x4, num_keys, tile=tile,
                              interpret=interpret)
    return jnp.concatenate(
        [out4[:_TB4], jnp.zeros((_TB - _TB4, n), jnp.uint32),
         out4[_TB4:]], axis=0)
