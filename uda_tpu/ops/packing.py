"""Host-side packing: variable-length records -> fixed-stride device columns.

The reference's merge engine walks variable-length VInt-framed records
with a comparator called per heap adjustment (reference
src/Merger/MergeQueue.h:151-270, StreamRW.cc:334-449). That shape cannot
map onto the MXU/VPU. The TPU-first representation is:

- ``key_words``: uint32[n, W/4] — the normalized key prefix, packed
  big-endian so uint32 numeric order == memcmp byte order;
- ``key_lens``: int32[n] — content length (shorter-is-smaller tiebreak);
- ``ranks``: int32[n] — overflow tiebreak for keys longer than the
  carried width whose prefixes collide (computed on host; rare);
- optional fixed-stride payload words for fully device-resident sorts
  (e.g. TeraSort's 10-byte keys / 90-byte values).

Everything here is vectorized numpy (one pass over the batch, no
per-record Python in the common key types). Comparator *semantics* come
from uda_tpu.utils.comparators; this module only vectorizes them.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from uda_tpu.utils.comparators import KeyType
from uda_tpu.utils.errors import MergeError
from uda_tpu.utils.ifile import RecordBatch

__all__ = ["PackedKeys", "content_spans", "pack_keys", "overflow_ranks",
           "pack_fixed_payload", "unpack_fixed_payload"]


@dataclasses.dataclass
class PackedKeys:
    """Device-ready sort columns for one batch of records."""

    key_words: np.ndarray   # uint32 [n, W/4]
    key_lens: np.ndarray    # int32 [n]
    ranks: np.ndarray       # int32 [n]

    @property
    def num_records(self) -> int:
        return int(self.key_words.shape[0])

    @property
    def width(self) -> int:
        return int(self.key_words.shape[1]) * 4


def content_spans(batch: RecordBatch, kt: KeyType) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized ``KeyType.content``: (offset, length) of the comparable
    bytes of every key, without touching per-record Python.

    Strategies mirror reference CompareFunc.cc:70-91: Text skips its VInt
    length prefix, BytesWritable skips a fixed 4-byte length, everything
    else compares the serialized bytes directly.
    """
    off = batch.key_off
    ln = batch.key_len
    if kt.name == "text":
        if np.any(ln < 1):
            raise MergeError("empty serialized Text key")
        first = batch.data[off].astype(np.int16)
        first = np.where(first > 127, first - 256, first)
        vsize = np.where(first >= -112, 1,
                         np.where(first >= -120, -111 - first, -119 - first))
        vsize = vsize.astype(np.int64)
        return off + vsize, ln - vsize
    if kt.name in ("bytes", "ibytes"):
        if np.any(ln < 4):
            raise MergeError("BytesWritable key shorter than its length field")
        return off + 4, ln - 4
    # identity / sign-flip types: content == serialized bytes
    return off, ln


def _gather_padded(data: np.ndarray, off: np.ndarray, take: np.ndarray,
                   width: int) -> np.ndarray:
    """Vectorized gather of [n, width] bytes: data[off+j] for j < take,
    zero-padded past each row's take."""
    j = np.arange(width, dtype=np.int64)
    w = int(take[0]) if take.shape[0] else 0
    if 0 < w <= width and np.all(take == w):
        # constant content width (TeraSort shape, fixed-width numerics):
        # one unmasked gather + zero columns — skips the index/value
        # where-mask passes, the staging hot path's biggest constant
        out = np.zeros((take.shape[0], width), np.uint8)
        out[:, :w] = data[off[:, None] + j[None, :w]]
        return out
    idx = off[:, None] + j[None, :]
    mask = j[None, :] < take[:, None]
    idx = np.where(mask, idx, 0)
    return np.where(mask, data[idx], 0).astype(np.uint8)


def _bytes_to_words(raw: np.ndarray) -> np.ndarray:
    """[n, 4k] uint8 -> big-endian uint32 [n, k]: the ONE place the lane
    layout is defined (memcmp byte order == ascending word order)."""
    n, nbytes = raw.shape
    w = raw.reshape(n, nbytes // 4, 4)
    return ((w[:, :, 0].astype(np.uint32) << 24)
            | (w[:, :, 1].astype(np.uint32) << 16)
            | (w[:, :, 2].astype(np.uint32) << 8)
            | w[:, :, 3].astype(np.uint32))


def _words_to_bytes(words: np.ndarray) -> np.ndarray:
    """Inverse of _bytes_to_words: uint32 [n, k] -> uint8 [n, 4k]."""
    n, k = words.shape
    raw = np.empty((n, k * 4), np.uint8)
    raw[:, 0::4] = (words >> 24) & 0xFF
    raw[:, 1::4] = (words >> 16) & 0xFF
    raw[:, 2::4] = (words >> 8) & 0xFF
    raw[:, 3::4] = words & 0xFF
    return raw


def pack_keys(batch: RecordBatch, kt: KeyType, width: int) -> PackedKeys:
    """Pack normalized key prefixes into big-endian uint32 lane columns."""
    if width % 4 != 0 or width <= 0:
        raise MergeError(f"key width must be a positive multiple of 4, got {width}")
    n = batch.num_records
    if n == 0:
        return PackedKeys(np.zeros((0, width // 4), np.uint32),
                          np.zeros(0, np.int32), np.zeros(0, np.int32))
    off, ln = content_spans(batch, kt)
    raw = _gather_padded(batch.data, off, np.minimum(ln, width), width)
    if kt.name in ("int_numeric", "long_numeric"):
        raw[:, 0] ^= 0x80  # sign-bit flip: memcmp order == numeric order
    words = _bytes_to_words(raw)
    ranks = overflow_ranks(batch, raw, off, ln, width)
    return PackedKeys(words, ln.astype(np.int32), ranks)


def overflow_ranks(batch: RecordBatch, prefixes: np.ndarray,
                   content_off: np.ndarray, content_len: np.ndarray,
                   width: int) -> np.ndarray:
    """Third sort column: orders keys whose content exceeds ``width`` and
    whose carried prefixes collide.

    Host-side: group the (rare) overflowing keys by prefix, order each
    group by its full *content* bytes — NOT the serialized key, whose
    length prefix (Text VInt / BytesWritable length field) would
    dominate the comparison — and assign dense ranks. Keys that fit the
    width keep rank 0 — the (prefix, length) pair already orders them
    exactly (see comparators.KeyType.normalize).
    """
    n = batch.num_records
    ranks = np.zeros(n, np.int32)
    over = np.nonzero(content_len > width)[0]
    if over.size == 0:
        return ranks

    def content(i: int) -> bytes:
        o, l = int(content_off[i]), int(content_len[i])
        return batch.data[o:o + l].tobytes()

    groups: dict[bytes, list[int]] = {}
    for i in over.tolist():
        groups.setdefault(prefixes[i].tobytes(), []).append(i)
    for members in groups.values():
        if len(members) < 2:
            continue
        full = sorted(members, key=lambda i: (content(i), i))
        # dense rank by full content bytes (equal contents share a rank
        # so the stable sort preserves arrival order among them)
        r = 0
        prev = None
        for i in full:
            kb = content(i)
            if prev is not None and kb != prev:
                r += 1
            ranks[i] = r
            prev = kb
    return ranks


def pack_fixed_payload(batch: RecordBatch, stride: int) -> np.ndarray:
    """Pack fixed-width values into uint32[n, ceil(stride/4)] for fully
    device-resident sorts (TeraSort: 90-byte values -> 23 words).

    Raises if any value exceeds ``stride``; shorter values are zero-padded
    (their true length travels in the batch's ``val_len`` column).
    """
    if np.any(batch.val_len > stride):
        raise MergeError(f"value exceeds fixed stride {stride}")
    wstride = (stride + 3) // 4 * 4
    raw = _gather_padded(batch.data, batch.val_off, batch.val_len, wstride)
    return _bytes_to_words(raw)


def unpack_fixed_payload(words: np.ndarray, lengths: Optional[np.ndarray],
                         stride: int) -> list[bytes]:
    """Inverse of pack_fixed_payload (host side, for emission)."""
    words = np.asarray(words, dtype=np.uint32)
    raw = _words_to_bytes(words)
    n = raw.shape[0]
    if lengths is None:
        return [raw[i, :stride].tobytes() for i in range(n)]
    return [raw[i, : int(lengths[i])].tobytes() for i in range(n)]
