"""Device sort/merge over packed key columns.

Replaces the reference's reduce-side k-way priority-queue merge (reference
src/Merger/MergeQueue.h:126-427 ``PriorityQueue``/``MergeQueue``,
consumed record-at-a-time by ``write_kv_to_stream``,
src/Merger/StreamRW.cc:151-225) with whole-run device sorts:

- ``sort_permutation``: one multi-operand lexicographic ``lax.sort`` over
  (key words..., content length, overflow rank) yielding the record
  permutation. XLA lowers this to its tuned on-chip sort; there is no
  per-record host loop anywhere.
- ``merge_runs``: k pre-sorted runs are concatenated and re-sorted. A
  k-way merge is O(n log k) vs O(n log n), but on TPU the constant factor
  of XLA's vectorized bitonic sort beats scalar heap walks by orders of
  magnitude; a Pallas merge-path kernel is the planned upgrade and slots
  in behind the same API (see uda_tpu/ops/pallas_merge.py).
- ``sort_records_fixed``: fully device-resident variant that carries a
  fixed-stride payload through the same sort (TeraSort layout).

All functions are jit-compiled with static column counts; shapes are
static per (run length, key width) pair so XLA caches one executable per
configuration, analogous to the reference sizing its buffer pools once
per job (reference src/Merger/reducer.cc:56-133).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from uda_tpu.ops.packing import PackedKeys

__all__ = ["sort_permutation", "merge_runs", "sort_records_fixed",
           "concat_packed", "resolve_sort_path", "apply_perm_chunked",
           "route_engine", "LANES_ENGINES", "FLYOFF_ENGINES",
           "BENCH_FLYOFF", "ALL_SORT_PATHS", "GATHER_BOUND_ENGINES",
           "CC_LADDER", "SMALL_BATCH_ROWS"]

# The single source of truth for engine path names. LANES_ENGINES are
# the Pallas-pipeline variants (bounded compile; interpret mode on CPU
# meshes): "lanes" carries payload through the network, "lanes2" uses
# the in-kernel two-phase gather, "keys8" runs the cascade on an 8-row
# keys view + one global XLA payload gather. "gather2" is keys8's
# XLA-native twin: the permutation comes from a narrow lax.sort
# instead of the Pallas cascade, the payload moves with the same
# single minor-dim gather (differs from "gather", which does one
# gather PER COLUMN on [n] arrays). The remaining lax.sort paths are
# "carry" (operand-carry) and "gather". bench.py, parallel.distributed
# and models.terasort all import these — adding an engine means
# extending ONE tuple.
# "carrychunk" applies the narrow-sort permutation with a few SMALL
# operand-carry sorts (invert the permutation with a 2-operand sort,
# then re-sort payload chunks of ~6 columns by it): no gathers, no
# Pallas, and every sort stays far below the operand count where XLA's
# variadic-sort compile time blows up. "keys8f" is keys8 with the
# FOLDED cascade (ops.pallas_fold: two element-halves share the 8-row
# tile, halving per-stage work) — it needs the compare set to fit a
# 4-row slot, so it is a narrow-key specialization (<= 3 compare rows
# + tie-break; the TeraSort flagship shape) and joins the bench
# fly-off but not the general-purpose engine set.
# carrychunk's payload-chunk width; overridable for deployment tuning
# (resolved once at import — see apply_perm_chunked)
DEFAULT_CHUNK_COLS = int(os.environ.get("UDA_TPU_CHUNK_COLS", "6"))

# The engine the "auto" policy deploys — how a fly-off/sweep winner
# reaches every production call site at once (the engine analogue of
# UDA_TPU_CHUNK_COLS; scripts/sweep_carrychunk.py + bench.py produce
# the datum). Empty = the built-in per-backend defaults below. Read
# ONCE at import, never inside a jitted trace. A deployed LANES engine
# applies only to lanes-capable callers (lanes_ok=True); others keep
# the built-in default rather than failing — the deploy var must never
# break a pure-XLA code path.
DEPLOYED_SORT_PATH = os.environ.get("UDA_TPU_SORT_PATH", "")

LANES_ENGINES = ("lanes", "lanes2", "keys8", "keys8f")
FLYOFF_ENGINES = ("lanes", "lanes2", "keys8", "gather2", "carrychunk")
BENCH_FLYOFF = FLYOFF_ENGINES + ("keys8f",)
ALL_SORT_PATHS = ("carry", "gather") + BENCH_FLYOFF

# Engines whose payload movement is one (or more) global HBM gathers.
# The take-ramp probe (BENCH_NOTES_r05: 0.15 GB/s at 2^16 rows vs
# 2.15 GB/s at 2^22) shows the gather is LATENCY-bound below
# SMALL_BATCH_ROWS — fixed per-row random-access cost dominates before
# the streaming rate amortizes it — so small batches route to a
# gather-free engine (route_engine below).
GATHER_BOUND_ENGINES = ("gather", "gather2", "keys8", "keys8f")
SMALL_BATCH_ROWS = 1 << 20

# carrychunk chunk-width ladder (words per payload-chunk sort). For the
# TeraSort shape's 23 payload words: cc=6 -> 4 chunk sorts moving 27
# operand-words/record, cc=8 -> 3 (26), cc=12 -> 2 (25), cc=23 -> the
# single-sort extreme (24 words/record — the ROADMAP "27->24" lever).
# Larger cc strictly reduces sort-network traffic, bounded by XLA's
# superlinear variadic-sort compile time; the ladder is what
# scripts/sweep_carrychunk.py and the tpu_return re-probe measure, and
# the sweep's winner deploys via UDA_TPU_CHUNK_COLS.
CC_LADDER = (8, 12, 23)


def resolve_sort_path(path: str, lanes_ok: bool = False) -> str:
    """Resolve a payload-movement strategy name. "auto" picks
    operand-carry on CPU (compile is cheap there) and "carrychunk" on
    TPU — the measured fly-off champion (BENCH_HW_r05.json: 3.04 GB/s
    vs lanes 1.22 / keys8 1.30) with bounded compile (no sort exceeds
    chunk_cols+1 operands; XLA's variadic-sort compile time grows
    superlinearly in operand count, and on remote-compile backends a
    wide carry sort can take hours) and no record-width limit.
    ``lanes_ok`` additionally admits the Pallas-pipeline engines
    (LANES_ENGINES) for callers that implement them; the pure-XLA
    strategies (carry/gather/gather2/carrychunk) are valid everywhere.
    Resolution happens EAGERLY, never inside a jitted trace: a
    trace-time choice would be baked into the jit cache and survive a
    later platform switch."""
    valid = (ALL_SORT_PATHS if lanes_ok
             else tuple(p for p in ALL_SORT_PATHS
                        if p not in LANES_ENGINES))
    if path == "auto":
        if DEPLOYED_SORT_PATH:
            if DEPLOYED_SORT_PATH not in ALL_SORT_PATHS:
                raise ValueError(
                    f"UDA_TPU_SORT_PATH={DEPLOYED_SORT_PATH!r} is not a "
                    f"known sort path {ALL_SORT_PATHS}")
            if DEPLOYED_SORT_PATH in valid:
                return DEPLOYED_SORT_PATH
            # deployed lanes engine, lanes-incapable caller: keep the
            # built-in default
        backend = jax.default_backend()
        if backend == "cpu":
            path = "carry"
        elif backend == "tpu":
            path = "carrychunk"
        else:
            path = "gather"
    if path not in valid:
        raise ValueError(f"unknown sort path {path!r}")
    return path


def _cached_engine(n_rows: int, lanes_ok: bool) -> "str | None":
    """The tuning-cache consult for "auto" routing (utils/tuncache.py):
    a fly-off winner persisted per (backend, row-bucket, lanes
    capability) by scripts/tune_probe.py. Returns None — today's
    built-in default — on a cold cache, an unreadable file, or a
    winner this caller cannot run (validation here, so a stale or
    hand-edited cache can never force an invalid engine name onto a
    production sort surface). Precedence is env > cache > built-in:
    callers consult this only when UDA_TPU_SORT_PATH is unset."""
    from uda_tpu.utils.tuncache import rows_bucket, tune_cache

    backend = jax.default_backend()
    key = f"{backend}|rows{rows_bucket(n_rows)}|lanes{int(lanes_ok)}"
    rec = tune_cache.lookup("sort.engine", key)
    if rec is None:
        return None
    engine = (rec.get("winner") or {}).get("engine")
    valid = (ALL_SORT_PATHS if lanes_ok
             else tuple(p for p in ALL_SORT_PATHS
                        if p not in LANES_ENGINES))
    if engine not in valid:
        return None
    return engine


def route_engine(n_rows: int, path: str = "auto",
                 lanes_ok: bool = False) -> str:
    """Batch-size-aware engine routing: resolve ``path`` like
    :func:`resolve_sort_path` — consulting the persisted tuning cache
    for "auto" when no env winner is deployed (env > cache > built-in;
    a cold cache is byte-for-byte today's defaults) — then, for "auto"
    only, steer batches below :data:`SMALL_BATCH_ROWS` away from
    :data:`GATHER_BOUND_ENGINES` onto "carrychunk" on TPU (its
    permutation apply rides small sort networks, no global gather —
    the only engine shape that holds up in the latency-bound take-ramp
    regime). The steering applies to deployed AND cached winners
    alike: a gather-bound fly-off champion (keys8f/gather2/...) must
    not be routed into the regime the take-ramp datum says it loses.
    An EXPLICIT path is always honored: routing refines the default,
    it never overrides the operator. This is the resolution entry for
    the production sort surfaces (models.terasort.single_chip_sort,
    parallel.distributed). Resolution is eager, never inside a jitted
    trace."""
    if path != "auto":
        return resolve_sort_path(path, lanes_ok)
    resolved = resolve_sort_path("auto", lanes_ok)
    if not DEPLOYED_SORT_PATH:
        cached = _cached_engine(n_rows, lanes_ok)
        if cached is not None:
            resolved = cached
    if (n_rows < SMALL_BATCH_ROWS and jax.default_backend() == "tpu"
            and resolved in GATHER_BOUND_ENGINES):
        return "carrychunk"
    return resolved


def apply_perm_chunked(perm, cols, chunk_cols: int | None = None) -> list:
    """Apply ``perm`` to columns WITHOUT gathers: ``out[c][j] ==
    cols[c][perm[j]]``. Inverts the permutation with a 2-operand sort
    (iota carried through a sort BY perm lands at the inverse), then
    re-sorts payload chunks of ``chunk_cols`` columns by it — every
    sort stays far below the operand count where XLA's variadic-sort
    compile time blows up. The single implementation behind the
    "carrychunk" engine (terasort bench and the distributed step).

    ``chunk_cols=None`` resolves ``UDA_TPU_CHUNK_COLS`` so a
    sweep-tuned value reaches every production call site at once
    (scripts/sweep_carrychunk.py produces the datum). The env var is
    read ONCE at import (module constant), never inside a jitted
    trace — a trace-time read would bake into the jit cache without
    being part of its key."""
    if chunk_cols is None:
        chunk_cols = DEFAULT_CHUNK_COLS
    n = perm.shape[0]
    iota = lax.iota(jnp.int32, n)
    # perm keys are distinct, so unstable sorts are exact
    _, inv = lax.sort((perm.astype(jnp.int32), iota), num_keys=1,
                      is_stable=False)
    out_cols: list = []
    for base in range(0, len(cols), chunk_cols):
        chunk = tuple(cols[base:base + chunk_cols])
        out = lax.sort((inv, *chunk), num_keys=1, is_stable=False)
        out_cols.extend(out[1:])
    return out_cols


@partial(jax.jit, static_argnames=("num_key_words",))
def _sort_perm(columns: tuple, num_key_words: int):
    n = columns[0].shape[0]
    iota = lax.iota(jnp.int32, n)
    operands = (*columns, iota)
    out = lax.sort(operands, num_keys=num_key_words + 2, is_stable=True)
    return out[-1]


def _as_columns(keys: PackedKeys) -> tuple:
    # Operand order matters: (prefix words..., overflow rank, content
    # length). Rank must precede length — for two keys that BOTH overflow
    # the carried width with equal prefixes, their order is decided by the
    # bytes past the width (the rank), not by their lengths (e.g.
    # b"P...P_Z" (17B) vs b"P...P_AB" (18B) with width 16: AB-key first
    # despite being longer). Length then orders the remaining ties:
    # fitting keys among themselves (shorter-is-smaller memcmp rule) and
    # fitting-vs-overflowing (the fitting key is a strict prefix, and its
    # rank is 0 <= any overflow rank, falling through to length which is
    # necessarily smaller).
    cols = tuple(jnp.asarray(keys.key_words[:, i])
                 for i in range(keys.key_words.shape[1]))
    return (*cols, jnp.asarray(keys.ranks), jnp.asarray(keys.key_lens))


def sort_permutation(keys: PackedKeys) -> np.ndarray:
    """Stable sort permutation of one run, computed on device.

    Sort key = (key words lexicographic, overflow rank, content length);
    stability preserves arrival order among equal keys, which is the
    merge-queue contract equal keys get in the reference (segments are
    advanced in heap order; Hadoop guarantees grouping, not order, so
    stable-by-arrival is a strict strengthening).
    """
    if keys.num_records == 0:
        return np.zeros(0, np.int64)
    perm = _sort_perm(_as_columns(keys), keys.key_words.shape[1])
    return np.asarray(perm, dtype=np.int64)


def concat_packed(runs: Sequence[PackedKeys]) -> PackedKeys:
    """Concatenate packed runs (the host-side prelude to merge_runs)."""
    return PackedKeys(
        np.concatenate([r.key_words for r in runs], axis=0),
        np.concatenate([r.key_lens for r in runs]),
        np.concatenate([r.ranks for r in runs]),
    )


def merge_runs(runs: Sequence[PackedKeys]) -> tuple[np.ndarray, np.ndarray]:
    """Merge k sorted runs into one global order.

    Returns ``(perm, run_id)`` where ``perm`` indexes into the
    concatenation of the runs and ``run_id[i]`` is the source run of
    output position i (the analogue of the reference's per-segment
    provenance, used to pull the right value bytes at emission).

    Overflow-rank caveat: each run's ranks were computed within that run;
    merging reuses them only when rank columns are compatible. The merge
    engine recomputes ranks across runs at staging time (see
    uda_tpu.merger), so here ranks are taken as-is.
    """
    if not runs:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    cat = concat_packed(runs)
    perm = sort_permutation(cat)
    sizes = np.asarray([r.num_records for r in runs], dtype=np.int64)
    bounds = np.cumsum(sizes)
    run_id = np.searchsorted(bounds, perm, side="right")
    return perm, run_id


@partial(jax.jit, static_argnames=("num_key_words",))
def _sort_fixed(columns: tuple, payload, num_key_words: int):
    n = columns[0].shape[0]
    iota = lax.iota(jnp.int32, n)
    pay_cols = tuple(payload[:, i] for i in range(payload.shape[1]))
    out = lax.sort((*columns, iota, *pay_cols), num_keys=num_key_words + 2,
                   is_stable=True)
    perm = out[len(columns)]
    sorted_payload = jnp.stack(out[len(columns) + 1:], axis=1)
    return sorted_payload, perm


def sort_records_fixed(keys: PackedKeys, payload: jnp.ndarray | np.ndarray):
    """Device-resident sort of (keys, fixed-stride payload words).

    The payload words are carried through the sort network as extra
    operands rather than gathered by the output permutation afterwards:
    on TPU a row gather of wide payloads runs ~5x slower than the
    operand-carried sort (random HBM access vs streaming
    compare-exchange). Returns ``(sorted_payload, perm)`` as device
    arrays.
    """
    return _sort_fixed(_as_columns(keys), jnp.asarray(payload),
                       keys.key_words.shape[1])
