"""Device ops: packing, sort, merge (the MergeQueue/StreamRW layer of
SURVEY §1, rebuilt as whole-run device sorts over packed key columns)."""

from uda_tpu.ops import packing, sort, merge

__all__ = ["packing", "sort", "merge"]
