"""Pallas full-record sort in the records-as-lanes layout.

The device-native replacement for the reference's whole merge pipeline
(reference src/Merger/MergeQueue.h:276-427 k-way PQ + StreamRW record
walk) built for how a TPU actually wants to touch memory:

- **Layout**: records are COLUMNS of a ``uint32[32, n]`` matrix ("lanes
  layout"): row r holds word r of every record, record i lives in lane
  i. Rows 0..num_keys-1 are the big-endian key words; one row is the
  stability tie-break (global arrival index, written by the tile-sort
  kernel); remaining rows are payload. Why: XLA lane-pads the minor
  dimension of an ``[n, 26]`` row matrix to 128 words (5x HBM waste),
  while ``[32, n]`` is perfectly tiled, every compare-exchange is a
  lane-axis shift applied to all 32 rows at once, and every DMA window
  is lane-aligned (the Mosaic rule that rejects ``[n, 26]`` slicing).
- **Tile sort** (`_tile_sort_kernel`): a full bitonic sorting network
  over T lanes in VMEM; static strides lower to lane rotates. Tiles are
  emitted ASCENDING or DESCENDING by tile-index parity — the classic
  bitonic trick that makes every later merge input (asc ++ desc)
  bitonic *as stored*, so no kernel ever reverses data.
- **Merge passes** (`_merge_pass_kernel`): log2(n/T) passes; pass ℓ
  merges adjacent run pairs of length L into runs of 2L whose direction
  again alternates (the final pass emits ascending). Per output tile, a
  vectorized XLA binary search (merge-path) finds the pair diagonal;
  the kernel DMAs one lane-ALIGNED superwindow per side, aligns with a
  dynamic lane roll, masks out-of-window lanes to +inf positioned so
  the concatenation stays bitonic (ascending A with +inf tail, then
  +inf front on the stored-descending B window), and runs one
  log2(2T)-stage bitonic merge network in the tile's output direction.

Stability: the tie-break row makes all sort keys distinct, so the
(unstable) bitonic networks reproduce stable arrival order exactly.

``sort_lanes`` builds the whole pipeline (1 tile-sort + log2(n/T)
merge passes) in one traced, jit-compatible function. Unlike the
operand-carry ``lax.sort`` (whose TPU compile time grows superlinearly
in operand count, uda_tpu.ops.sort.resolve_sort_path), every kernel
here has a fixed small operand surface, so compile cost is bounded
regardless of record width.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["ROWS", "sort_lanes", "rows_to_lanes", "lanes_to_rows",
           "keys8_sort_perm", "pad_pow2", "TB_ROW_DEFAULT"]

ROWS = 32               # sublane-padded row count of the lanes layout
TB_ROW_DEFAULT = 31     # default tie-break row (last)
_INF = np.uint32(0xFFFFFFFF)  # numpy scalar: kernels bake it in as a
                              # literal (a traced jnp constant would be
                              # rejected by pallas_call as a capture)
_LANE = 128             # TPU lane width: DMA lane offsets must be multiples


def rows_to_lanes(words, rows: int = ROWS):
    """[n, W] row-matrix records -> [rows, n] lanes layout (zero-padded
    rows). One transpose pass; prefer generating directly in lanes
    layout where possible."""
    w = jnp.asarray(words, jnp.uint32)
    n, cols = w.shape
    if cols > rows:
        raise ValueError(f"{cols} record words > {rows} layout rows")
    out = jnp.zeros((rows, n), jnp.uint32)
    return lax.dynamic_update_slice(out, w.T, (0, 0))


def lanes_to_rows(lanes, num_words: int):
    """[rows, n] lanes layout -> [n, num_words] row matrix."""
    return jnp.asarray(lanes)[:num_words, :].T


def _lex_lt(a_rows, b_rows):
    """Lexicographic a < b over equal-length lists of uint32 arrays."""
    lt = jnp.zeros(jnp.broadcast_shapes(a_rows[0].shape, b_rows[0].shape),
                   jnp.bool_)
    eq = jnp.ones(lt.shape, jnp.bool_)
    for a, b in zip(a_rows, b_rows):
        lt = lt | (eq & (a < b))
        eq = eq & (a == b)
    return lt


def _cmp_exchange(x, j: int, asc_mask, key_rows_idx):
    """One compare-exchange stage at static lane stride j.

    ``asc_mask``: [1, T] bool — True where the surrounding block sorts
    ascending. Lane i pairs with i^j; the "low" lane of a pair has bit
    j clear, so i+j never crosses a block boundary and the cyclic rolls
    never pair across a wrap (the wrapped values land on lanes whose
    mask points the other way)."""
    T = x.shape[1]
    idx = lax.broadcasted_iota(jnp.int32, (1, T), 1)
    low = (idx & j) == 0
    left = jnp.roll(x, -j, axis=1)   # lane i <- value of lane i+j
    right = jnp.roll(x, j, axis=1)   # lane i <- value of lane i-j
    other = jnp.where(low, left, right)
    lt = _lex_lt([x[r] for r in key_rows_idx],
                 [other[r] for r in key_rows_idx])[None, :]
    # this position should hold the pair minimum iff (ascending block)
    # == (low position); keep self iff that wish matches self<other
    # (keys are strictly ordered thanks to the tie-break row)
    take_min_here = asc_mask == low
    keep_self = take_min_here == lt
    return jnp.where(keep_self, x, other)


def _keys_view(x, num_keys, tb_row):
    """8-row (one sublane tile) working set for the two-phase engine:
    rows [keys..., tie-break, lane-position, zero pad]. The network runs
    on THIS view (4x less data movement per compare-exchange than the
    full 32 rows); the position row rides through as payload and ends
    up holding, for each sorted position, its SOURCE lane — the gather
    index that then moves the full-width payload ONCE."""
    n = x.shape[1]
    pos = lax.broadcasted_iota(jnp.uint32, (1, n), 1)
    pad = jnp.zeros((8 - num_keys - 2, n), jnp.uint32)
    seq8 = jnp.concatenate([x[:num_keys], x[tb_row:tb_row + 1], pos, pad],
                           axis=0)
    key_rows = list(range(num_keys)) + [num_keys]
    return seq8, key_rows, num_keys + 1  # (view, key row idx, pos row)


def _tile_sort_kernel(x_ref, o_ref, *, tile, num_keys, tb_row, alternate,
                      two_phase):
    t = pl.program_id(0)
    x = x_ref[...]
    lane = lax.broadcasted_iota(jnp.int32, (1, tile), 1)
    # stability: global arrival index into the tie-break row
    gidx = (lane + t * tile).astype(jnp.uint32)
    x = jnp.where(lax.broadcasted_iota(jnp.int32, x.shape, 0) == tb_row,
                  jnp.broadcast_to(gidx, x.shape), x)
    # whole-tile direction alternates by parity so merge inputs are
    # bitonic as stored (single-tile arrays sort ascending)
    if alternate:
        tile_asc = jnp.broadcast_to((t % 2) == 0, (1, tile))
    else:
        tile_asc = jnp.broadcast_to(jnp.bool_(True), (1, tile))

    if two_phase:
        net, key_rows_idx, pos_row = _keys_view(x, num_keys, tb_row)
    else:
        net, key_rows_idx = x, list(range(num_keys)) + [tb_row]
    k = 2
    while k <= tile:
        if k == tile:
            asc = tile_asc
        else:
            # standard bitonic direction per k-block, flipped wholesale
            # for descending tiles
            asc = ((lane & k) == 0) == tile_asc
        j = k // 2
        while j >= 1:
            net = _cmp_exchange(net, j, asc, key_rows_idx)
            j //= 2
        k *= 2
    if two_phase:
        o_ref[...] = jnp.take(x, net[pos_row].astype(jnp.int32), axis=1)
    else:
        o_ref[...] = net


def _vma_of(x):
    """The shard_map varying-manual-axes set of ``x`` on JAX versions
    that type it (jax.typeof(...).vma); empty elsewhere — old releases
    have no vma typing, so there is nothing to propagate."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return ()
    return tuple(getattr(typeof(x), "vma", ()) or ())


def _uint32_struct(shape, x):
    """uint32 out_shape struct carrying ``x``'s vma so Pallas pipelines
    work as-is inside distributed shard_map bodies (a plain struct on
    JAX versions without vma typing)."""
    vma = _vma_of(x)
    if vma:
        return jax.ShapeDtypeStruct(shape, jnp.uint32, vma=vma)
    return jax.ShapeDtypeStruct(shape, jnp.uint32)


@partial(jax.jit, static_argnames=("tile", "num_keys", "tb_row",
                                   "alternate", "interpret", "two_phase"))
def _tile_sort(x, tile: int, num_keys: int, tb_row: int, alternate: bool,
               interpret: bool = False, two_phase: bool = False):
    rows, n = x.shape
    return pl.pallas_call(
        partial(_tile_sort_kernel, tile=tile, num_keys=num_keys,
                tb_row=tb_row, alternate=alternate, two_phase=two_phase),
        grid=(n // tile,),
        in_specs=[pl.BlockSpec((rows, tile), lambda t: (0, t))],
        out_specs=pl.BlockSpec((rows, tile), lambda t: (0, t)),
        # vma propagates the caller's shard_map varying-axes set, so the
        # pipeline works as-is inside distributed shard_map bodies
        out_shape=_uint32_struct((rows, n), x),
        interpret=interpret,
    )(x)


def _pass_splits(x, run_len, final, tile: int, num_keys: int, tb_row: int):
    """Merge-path windows for one pass, in XLA.

    ``run_len`` (= L) and ``final`` may be TRACED scalars: every
    pass-dependent quantity is computed here and handed to the kernel as
    data, so ONE compiled kernel serves every pass (and the pass loop
    can be a ``lax.fori_loop``) — the whole pipeline costs two Mosaic
    kernel compiles regardless of n.

    Rank bookkeeping: per output tile, d_eff is the pair-local diagonal
    in ASCENDING rank space — for descending-output tiles the tile's
    ranks are [2L - d_local - T, 2L - d_local), counted from the top —
    and i0 is the number of A-run records among the first d_eff merged
    records (vectorized merge-path binary search). B is the
    stored-DESCENDING run read through its logical ascending view
    B'[m] = B[L-1-m]; ties go to A (arrival order) which the strict
    tie-break ordering decides naturally.

    Returns int32[num_tiles, 8] rows
    (a_blk, shift_a, thr_a, b_blk, shift_b, thr_b, out_asc, 0):
    per side an aligned superwindow start (in lane-block units), the
    non-negative cyclic lane shift in [0, win) that places the wanted
    first record at lane 0, and the invalid-lane threshold
    (A: lanes >= thr_a are past the run end; B: lanes < thr_b are below
    B'[j0]); see _merge_pass_kernel for how they are applied.
    """
    rows, n = x.shape
    L = jnp.asarray(run_len, jnp.int32)
    final = jnp.asarray(final, jnp.bool_)
    num_tiles = n // tile
    win = tile + _LANE
    t = jnp.arange(num_tiles, dtype=jnp.int32)
    pair = (t * tile) // (2 * L)
    d_local = t * tile - pair * 2 * L
    out_asc = final | ((pair % 2) == 0)
    d_eff = jnp.where(out_asc, d_local, 2 * L - (d_local + tile))
    a_base = pair * 2 * L
    b_base = a_base + L
    key_rows_idx = list(range(num_keys)) + [tb_row]

    def key_at(global_idx):
        return [x[r, global_idx] for r in key_rows_idx]

    lo = jnp.maximum(0, d_eff - L)
    hi = jnp.minimum(d_eff, L)
    # under shard_map's strict vma typing the carry must ENTER the loop
    # varying over the same manual axes it EXITS with: the body compares
    # against x (device-varying), so (lo, hi) become varying after one
    # iteration while their iota/run_len-derived inits are replicated.
    # pcast the inits to x's vma (a no-op outside shard_map, where vma
    # is empty) — this is what lets the distributed sort run the lanes
    # engines with check_vma=True (see parallel/distributed._sort_step)
    vma = _vma_of(x)
    if vma:
        lo = lax.pcast(lo, vma, to="varying")
        hi = lax.pcast(hi, vma, to="varying")

    def body(_, carry):
        lo, hi = carry
        mid = (lo + hi + 1) // 2          # candidate: A-records taken
        j = d_eff - mid                   # B'-records taken
        a_idx = a_base + jnp.clip(mid - 1, 0, L - 1)
        b_idx = b_base + jnp.clip(L - 1 - j, 0, L - 1)  # B'[j] stored lane
        a_le_b = ~_lex_lt(key_at(b_idx), key_at(a_idx))
        ok = (mid <= 0) | (j >= L) | a_le_b
        lo = jnp.where(ok, mid, lo)
        hi = jnp.where(ok, hi, mid - 1)
        return lo, hi

    bits = max(2, int(np.log2(n)) + 2)    # covers any L <= n/2
    i0, _ = lax.fori_loop(0, bits, body, (lo, hi))
    j0 = d_eff - i0

    # ---- A: records [i0, i0+tile) of the ascending run ----
    a_start = a_base + i0
    a_align = jnp.minimum((a_start // _LANE) * _LANE, n - win)
    roll_a = a_start - a_align
    thr_a = L - i0                        # lanes >= thr_a: past run end
    # ---- B: stored lanes holding B'[j0+tile-1] ... B'[j0] ----
    # unclamped start b_base + L - j0 - tile undershoots b_base by
    # inv = max(0, j0 + tile - L); read from the clamped start and roll
    # RIGHT by inv so position r holds B'[j0 + tile - 1 - r] for r>=inv
    # and the first inv lanes are masked (+inf front)
    inv = jnp.maximum(0, j0 + tile - L)
    b_clamp = b_base + jnp.maximum(0, L - j0 - tile)
    b_align = jnp.minimum((b_clamp // _LANE) * _LANE, n - win)
    roll_b = inv - (b_clamp - b_align)
    # aligned starts ship as LANE-BLOCK indices; the kernel multiplies
    # by _LANE so Mosaic can statically prove the HBM slice offset is
    # lane-divisible (a raw traced offset fails its divisibility check).
    # Roll amounts are normalized to [0, win): hardware pltpu.roll
    # miscomputes NEGATIVE dynamic shifts (interpret mode is fine), so
    # only non-negative cyclic shifts may reach the kernel.
    shift_a = jnp.mod(-roll_a, win)
    shift_b = jnp.mod(roll_b, win)
    cols = [a_align // _LANE, shift_a, thr_a, b_align // _LANE, shift_b, inv,
            out_asc.astype(jnp.int32), jnp.zeros_like(a_align)]
    return jnp.stack([c.astype(jnp.int32) for c in cols], axis=1)


def _merge_pass_kernel(splits_ref, splits_nxt_ref, x_hbm, o_ref, a_bufs,
                       b_bufs, sem_a, sem_b, *, tile, num_keys, tb_row,
                       split_blk, two_phase):
    """One output tile of one merge pass (see _pass_splits for the rank
    bookkeeping; every pass-dependent scalar arrives via splits_ref, so
    this kernel compiles once and serves all log2(n/tile) passes).

    MAINTENANCE: ops.pallas_fold._merge_pass_kernel_folded mirrors this
    kernel's DMA protocol and roll contract — apply hardware-erratum
    fixes to both.

    DMA double buffering: the windows for tile t+1 (whose aligned starts
    arrive via splits_nxt_ref, the splits table shifted by one row) are
    DMA'd into the other scratch slot WHILE tile t's merge network runs,
    so HBM latency overlaps compute across sequential grid steps.

    Window construction: each side DMAs a lane-aligned superwindow of
    tile+128 lanes (align floor-clamped so it never leaves the array),
    then one dynamic cyclic roll places the wanted first record at lane
    0. Out-of-window lanes are masked to +inf *positionally* so the
    concatenation stays bitonic:

      [ A: ascending, +inf tail ] ++ [ B: +inf front, descending ]

    (ascending -> +inf plateau -> descending = bitonic). The +inf lanes
    always land in the discarded half of the merge: smallest-T taken
    for ascending output, largest-T (positions [T, 2T) of the
    descending-direction network) for descending output."""
    rows = a_bufs.shape[1]
    t = pl.program_id(0)
    nt = pl.num_programs(0)
    s = t % split_blk                    # this tile's row in the block
    slot = t % 2
    win = tile + _LANE

    def issue(spl, slot):
        a_cp = pltpu.make_async_copy(
            x_hbm.at[:, pl.ds(spl[s, 0] * _LANE, win)], a_bufs.at[slot],
            sem_a.at[slot])
        b_cp = pltpu.make_async_copy(
            x_hbm.at[:, pl.ds(spl[s, 3] * _LANE, win)], b_bufs.at[slot],
            sem_b.at[slot])
        a_cp.start()
        b_cp.start()

    @pl.when(t == 0)
    def _():
        issue(splits_ref, 0)

    @pl.when(t + 1 < nt)
    def _():
        issue(splits_nxt_ref, (t + 1) % 2)

    # wait for this tile's windows (issued at t-1, or just above for t=0)
    pltpu.make_async_copy(x_hbm.at[:, pl.ds(0, win)], a_bufs.at[slot],
                          sem_a.at[slot]).wait()
    pltpu.make_async_copy(x_hbm.at[:, pl.ds(0, win)], b_bufs.at[slot],
                          sem_b.at[slot]).wait()

    shift_a = splits_ref[s, 1]           # non-negative cyclic shifts only
    thr_a = splits_ref[s, 2]
    shift_b = splits_ref[s, 4]
    thr_b = splits_ref[s, 5]
    out_asc = splits_ref[s, 6] != 0

    r_idx = lax.broadcasted_iota(jnp.int32, (1, tile), 1)
    rowi = lax.broadcasted_iota(jnp.int32, (rows, 1), 0)
    is_key_row = (rowi < num_keys) | (rowi == tb_row)

    a_rows = pltpu.roll(a_bufs[slot], shift_a, 1)[:, :tile]
    a_invalid = r_idx >= thr_a             # tail lanes past the run end
    a_rows = jnp.where(is_key_row & a_invalid,
                       jnp.broadcast_to(_INF, a_rows.shape), a_rows)

    b_rows = pltpu.roll(b_bufs[slot], shift_b, 1)[:, :tile]
    b_invalid = r_idx < thr_b              # front lanes below B'[j0]
    b_rows = jnp.where(is_key_row & b_invalid,
                       jnp.broadcast_to(_INF, b_rows.shape), b_rows)

    seq = jnp.concatenate([a_rows, b_rows], axis=1)
    asc_mask = jnp.broadcast_to(out_asc, (1, 2 * tile))
    if two_phase:
        net, key_rows_idx, pos_row = _keys_view(seq, num_keys, tb_row)
    else:
        net, key_rows_idx = seq, list(range(num_keys)) + [tb_row]
    j = tile
    while j >= 1:
        net = _cmp_exchange(net, j, asc_mask, key_rows_idx)
        j //= 2
    if two_phase:
        # Mosaic's gather rule requires input == indices == output
        # shape, so a narrowing take([32, 2T] by [T]) does not lower:
        # gather the full 2T window with the broadcast permutation row,
        # then slice the kept half (2x the gather traffic, but it's the
        # only formulation the lowering accepts — scripts/probe_gather)
        perm = jnp.broadcast_to(net[pos_row].astype(jnp.int32)[None, :],
                                seq.shape)
        gathered = jnp.take_along_axis(seq, perm, axis=1)
        o_ref[...] = jnp.where(out_asc, gathered[:, :tile],
                               gathered[:, tile:])
    else:
        o_ref[...] = jnp.where(out_asc, net[:, :tile], net[:, tile:])


@partial(jax.jit, static_argnames=("tile", "num_keys", "tb_row", "interpret",
                                   "two_phase"))
def _merge_pass(x, splits, tile: int, num_keys: int, tb_row: int,
                interpret: bool = False, two_phase: bool = False):
    rows, n = x.shape
    # The splits table is BLOCKED into SMEM a few rows per grid step: a
    # whole-table scalar prefetch would put [num_tiles, 8] int32 in SMEM
    # with the minor dim padded to 128 lanes — 4 MB at n=8M vs the 1 MB
    # SMEM budget. An (8, 8) block is 256 bytes regardless of n (the
    # lowering wants the sublane block dim divisible by 8 or equal to
    # the array dim, hence 8 rows — the kernel picks its row by
    # program_id % 8).
    split_blk = min(8, n // tile)
    # splits shifted by one row: step t reads tile t+1's aligned starts
    # for the double-buffered prefetch (last row duplicated, never used)
    splits_nxt = jnp.concatenate([splits[1:], splits[-1:]], axis=0)
    blk = pl.BlockSpec((split_blk, 8), lambda t: (t // split_blk, 0),
                       memory_space=pltpu.SMEM)
    return pl.pallas_call(
        partial(_merge_pass_kernel, tile=tile, num_keys=num_keys,
                tb_row=tb_row, split_blk=split_blk, two_phase=two_phase),
        grid=(n // tile,),
        in_specs=[blk, blk, pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((rows, tile), lambda t: (0, t)),
        scratch_shapes=[
            pltpu.VMEM((2, rows, tile + _LANE), jnp.uint32),
            pltpu.VMEM((2, rows, tile + _LANE), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        out_shape=_uint32_struct((rows, n), x),
        interpret=interpret,
    )(splits, splits_nxt, x)


def pad_pow2(n: int, tile: int) -> tuple[int, int]:
    """The lane-padding rule every lanes-engine entry point shares:
    pad ``n`` lanes up to ``m`` (a power of two, at least one lane
    block) and clamp ``tile`` so sort_lanes' preconditions hold
    (m % tile == 0 with m/tile a power of two). Returns (m, tile)."""
    m = max(_LANE, 1 << max(0, n - 1).bit_length())
    return m, min(tile, m)


def keys8_sort_perm(keyrows, tile: int = 1024, interpret: bool = False,
                    folded: bool = False):
    """The keys8 cascade core, shared by every keys8 engine (the
    single-chip sort, the bench bodies, the distributed local sort):
    run the FULL bitonic pipeline on an 8-row keys-only matrix and
    return ``(sorted_key_rows, perm)`` — ``perm[j]`` is the source lane
    of sorted position j (int32), stable by arrival order among equal
    keys (the row-7 tie-break holds the lane index).

    ``keyrows``: uint32[k, m] with k <= 7 key rows, m a power-of-two
    multiple of ``tile``. Rows k..6 pad with zeros (never compared);
    row 7 is overwritten by the tile-sort kernel. Callers own their
    lane padding: pad lanes' key rows must sort after every real
    lane's (e.g. all-0xFFFFFFFF keys tie with real all-max keys, and
    the arrival tie-break then keeps real lanes first because padding
    occupies the highest lane indices)."""
    k, m = keyrows.shape
    if not 0 < k <= 7:
        raise ValueError(f"keys8 needs 1..7 key rows, got {k}")
    if folded and k <= 3 and tile % (2 * _LANE) == 0:
        # the folded cascade (ops.pallas_fold): half the network work
        # AND half the inter-pass HBM traffic (slim [4, n] layout);
        # needs the compare set to fit a 4-row slot. Tiles below two
        # lane blocks cannot fold (the half width must stay
        # lane-aligned) and quietly use the standard cascade — the
        # output contract is identical.
        from uda_tpu.ops.pallas_fold import sort_lanes_folded4

        mat4 = jnp.concatenate(
            [jnp.asarray(keyrows, jnp.uint32),
             jnp.zeros((4 - k, m), jnp.uint32)], axis=0)
        out4 = sort_lanes_folded4(mat4, num_keys=k, tile=tile,
                                  interpret=interpret)
        return out4[:k], out4[3].astype(jnp.int32)
    mat8 = jnp.concatenate(
        [jnp.asarray(keyrows, jnp.uint32),
         jnp.zeros((8 - k, m), jnp.uint32)], axis=0)
    out8 = sort_lanes(mat8, num_keys=k, tb_row=7, tile=tile,
                      interpret=interpret)
    return out8[:k], out8[7].astype(jnp.int32)


def sort_lanes(x, num_keys: int, tb_row: int = TB_ROW_DEFAULT,
               tile: int = 1024, interpret: bool = False,
               two_phase: bool = False):
    """Full stable sort of records in lanes layout.

    ``x``: uint32[ROWS, n] with key words in rows [0, num_keys); row
    ``tb_row`` is overwritten with the arrival index (stability) and
    holds it in the output. n must be a power-of-two multiple of
    ``tile`` (pad with +inf-key records otherwise).

    ``two_phase``: run every bitonic network on an 8-row keys view and
    move the 32-row payload with ONE lane gather per kernel instead of
    through every compare-exchange stage (~4x less data movement per
    stage; requires Mosaic to lower a dynamic lane-axis gather — see
    scripts/probe_gather.py; needs num_keys <= 6).

    Returns the sorted [ROWS, n] array (ascending by keys, stable by
    arrival among equal keys).
    """
    x = jnp.asarray(x, jnp.uint32)
    rows, n = x.shape
    if tile & (tile - 1) or tile % _LANE:
        raise ValueError(f"tile={tile} must be a power of two multiple "
                         f"of {_LANE}")
    if n % tile or (n // tile) & (n // tile - 1):
        raise ValueError(f"n={n} must be a power-of-two multiple of "
                         f"tile={tile}")
    if not 0 < num_keys <= tb_row < rows:
        raise ValueError(f"bad num_keys={num_keys} / tb_row={tb_row}")
    if two_phase and num_keys + 2 > 8:
        raise ValueError(f"two_phase needs num_keys <= 6, got {num_keys}")
    levels = int(np.log2(n // tile))
    x = _tile_sort(x, tile, num_keys, tb_row, alternate=levels > 0,
                   interpret=interpret, two_phase=two_phase)
    if levels == 0:
        return x

    # One fori_loop body serving every pass: run_len/final are traced,
    # so the program holds exactly ONE merge pallas_call (and one tile
    # sort) no matter how many passes run — compile cost is bounded in
    # n, the property the operand-carry lax.sort path lacks.
    def body(lvl, x):
        run_len = jnp.int32(tile) << lvl
        final = lvl == levels - 1
        splits = _pass_splits(x, run_len, final, tile, num_keys, tb_row)
        return _merge_pass(x, splits, tile, num_keys, tb_row,
                           interpret=interpret, two_phase=two_phase)

    return lax.fori_loop(0, levels, body, x)
