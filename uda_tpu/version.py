"""Version of the uda_tpu framework.

The reference UDA is version 3.4.1-0 (release:1), autoconf package
``libuda`` 3.1 (reference src/configure.ac:20). We restart at 0.x for the
TPU-native rebuild.
"""

__version__ = "0.1.0"
