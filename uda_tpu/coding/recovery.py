"""k-of-n stripe reconstruction: the post-retry rung of the fetch
ladder.

When a Segment has exhausted transport retries against its primary
supplier (dead host, poisoned penalty box), this driver rebuilds the
partition's on-disk bytes from ANY k of the stripe's n chunks: it
fans shard fetches (``<map_id>~s<i>`` pseudo-maps) out over the
ordinary InputClient — so shards ride the same routing, wire, retry
and zero-copy machinery as data — collects the first k complete
chunks, and Reed-Solomon-decodes them (uda_tpu.coding.rs) into one
full-partition FetchResult (offset 0, last=True).

Source choice shares the task's recovery ledger: candidates are
ordered non-primary first (the primary just proved itself dead), then
by PenaltyBox rank, then data chunks before parity (systematic chunks
decode by concatenation). A failed shard stream promotes the next
candidate; the reconstruction fails only when fewer than k of the n
chunks are reachable at all.

Everything here is completion-driven (no blocking waits): shard
fetches chain from transport callbacks exactly like Segment's drive
loop, so the driver is safe to start from a completion thread.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Optional, Sequence

from uda_tpu.coding import rs, stripe_host
from uda_tpu.mofserver.index import shard_map_id
from uda_tpu.utils.errors import StorageError, attribute_supplier
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["StripeContext", "start_recovery"]

log = get_logger()


class StripeContext:
    """Everything the reconstruction needs that the failing request
    does not carry: the coding scheme, the job's canonically-ordered
    supplier list (the placement universe — sorted unique hosts), the
    declared failure-domain map (``uda.tpu.coding.domains``; empty =
    positional rotation), and the task's recovery ledger for source
    ranking/accounting."""

    def __init__(self, scheme, suppliers: Sequence[str], ledger=None,
                 domains=None):
        self.scheme = scheme
        self.suppliers = list(suppliers)
        self.ledger = ledger
        self.domains = dict(domains or {})
        # per-primary placement cache: the permutation depends only on
        # (suppliers, domains, primary), and host_of runs once per
        # CHUNK on the reconstruction hot path — rebuilding the
        # domain-interleave per chunk would be O(h) each
        self._orders: dict = {}

    def host_of(self, primary: str, chunk: int) -> str:
        order = self._orders.get(primary)
        if order is None:
            order = self._orders[primary] = [
                stripe_host(self.suppliers, primary, c,
                            domains=self.domains)
                for c in range(max(1, len(self.suppliers)))]
        return order[chunk % len(order)]


def start_recovery(client, req, ctx: StripeContext,
                   on_complete: Callable) -> None:
    """Reconstruct ``req``'s partition; ``on_complete`` receives a
    full-partition FetchResult or an Exception. ``client`` serves the
    shard fetches (its ``start_fetch``); ``req.host`` names the failed
    primary."""
    _Reconstruction(client, req, ctx, on_complete).start()


class _Reconstruction:
    def __init__(self, client, req, ctx: StripeContext, on_complete):
        from uda_tpu.mofserver.data_engine import FetchResult  # cycle-free

        self._result_cls = FetchResult
        self.client = client
        self.req = req
        self.ctx = ctx
        self.on_complete = on_complete
        self.k = ctx.scheme.k
        self.n = ctx.scheme.n
        # span attribution: the reconstruction is started under the
        # owning Segment's fetch span (use_span in Segment._try_recover)
        # — capture it HERE, because every later shard issue happens on
        # a transport completion thread whose contextvar is empty, and
        # the shard streams' net.fetch spans would otherwise start as
        # parentless roots invisible in the trace tree
        self.parent_span = metrics.current_span()
        self._lock = threading.Lock()
        # chunks grouped by their reported stripe identity (the
        # full-partition length): a STALE shard from a prior map
        # attempt lands in its own group instead of poisoning the
        # fresh one — whichever identity first collects k chunks wins
        self._groups: dict[int, dict[int, bytes]] = {}
        self._active = 0
        self._finished = False
        self._last_error: Optional[Exception] = None
        ranked = self._rank_candidates()
        self._pending: deque = deque(ranked)

    def _rank_candidates(self) -> list[tuple[int, str]]:
        cands = [(i, self.ctx.host_of(self.req.host, i))
                 for i in range(self.n)]
        hosts = []
        for _, h in cands:
            if h not in hosts:
                hosts.append(h)
        ledger = self.ctx.ledger
        order = {h: r for r, h in enumerate(
            ledger.rank(hosts) if ledger is not None else hosts)}
        cands.sort(key=lambda c: (c[1] == self.req.host,
                                  order.get(c[1], 0), c[0] >= self.k,
                                  c[0]))
        return cands

    # -- stream scheduling ---------------------------------------------------

    def start(self) -> None:
        self._launch()

    def _best_group(self) -> dict:
        return max(self._groups.values(), key=len) if self._groups \
            else {}

    def _launch(self) -> None:
        """Start shard streams until k are in flight or collected.
        Issues outside the lock (a dial may block)."""
        while True:
            with self._lock:
                if self._finished:
                    return
                need = self.k - len(self._best_group()) - self._active
                if need <= 0 or not self._pending:
                    exhausted = (need > 0 and self._active == 0
                                 and not self._pending)
                    break
                idx, host = self._pending.popleft()
                self._active += 1
            _ShardStream(self, idx, host).issue(0)
        if exhausted:
            have = sorted(self._best_group())
            err = StorageError(
                f"stripe of {self.req.map_id}/{self.req.reduce_id} "
                f"unrecoverable: {len(have)}/{self.k} chunks reachable "
                f"(have {have}; last shard error: {self._last_error})")
            attribute_supplier(err, self.req.host)
            self._finish(err)

    def _stream_done(self, idx: int, host: str, data: bytes,
                     full_part: int) -> None:
        with self._lock:
            if self._finished:
                return
            self._active -= 1
            # group by stripe identity: shards of a DIFFERENT map
            # attempt (different full-partition length) collect
            # separately — mixing them would decode garbage, and
            # letting the FIRST arrival define the baseline would let
            # one stale shard poison k fresh ones
            group = self._groups.setdefault(full_part, {})
            group[idx] = data
            decode = len(group) >= self.k
        ledger = self.ctx.ledger
        if ledger is not None:
            ledger.record("shard_fetched", supplier=host,
                          map_id=self.req.map_id)
        metrics.add("coding.shard.fetches", supplier=host)
        if decode:
            self._decode(full_part)
        else:
            self._launch()

    def _stream_failed(self, idx: int, host: str, exc: Exception) -> None:
        with self._lock:
            if self._finished:
                return
            self._active -= 1
            self._last_error = exc
        metrics.add("coding.shard.failures", supplier=host)
        ledger = self.ctx.ledger
        if ledger is not None:
            ledger.record("shard_failed", supplier=host,
                          map_id=self.req.map_id, error=exc)
        log.warn(f"stripe shard {idx} of {self.req.map_id} from "
                 f"{host or 'local'} failed ({exc}); trying the next "
                 f"candidate")
        self._launch()

    # -- decode + delivery ---------------------------------------------------

    def _decode(self, full_part: int) -> None:
        with self._lock:
            if self._finished:
                return
            chunks = dict(self._groups.get(full_part, {}))
        try:
            failpoint("coding.decode",
                      key=f"{self.req.map_id}/{self.req.reduce_id}")
            blob = rs.decode(chunks, self.k, self.n, full_part)
        except Exception as e:  # noqa: BLE001 - decode failure is the
            # reconstruction's terminal error; surfaced to the segment
            attribute_supplier(e, self.req.host)
            self._finish(e)
            return
        metrics.add("coding.reconstructed.partitions")
        metrics.add("coding.reconstructed.bytes", len(blob))
        ledger = self.ctx.ledger
        if ledger is not None:
            ledger.record("reconstructed", supplier=self.req.host,
                          map_id=self.req.map_id)
        log.warn(f"reconstructed {self.req.map_id}/{self.req.reduce_id} "
                 f"({len(blob)} B) from {sorted(chunks)} of "
                 f"{self.n} stripe chunks (k={self.k})")
        self._finish(self._result_cls(
            blob, len(blob), len(blob), 0,
            f"rs://{self.req.map_id}/{self.req.reduce_id}", last=True))

    def _finish(self, result) -> None:
        with self._lock:
            if self._finished:
                return
            self._finished = True
        self.on_complete(result)


class _ShardStream:
    """One shard's sequential chunk-fetch chain (offset loop until
    ``last``), iterative like Segment._drive: an inline completion is
    handed back to the issuing frame instead of recursing."""

    _PENDING = object()

    def __init__(self, rec: _Reconstruction, idx: int, host: str):
        self.rec = rec
        self.idx = idx
        self.host = host
        self.map_id = shard_map_id(rec.req.map_id, idx)
        self.buf = bytearray()
        self.full_part: Optional[int] = None
        self._mu = threading.Lock()
        self._issuing = False
        self._inline = self._PENDING

    def issue(self, offset: int) -> None:
        from uda_tpu.mofserver.data_engine import ShuffleRequest

        result = self._PENDING
        while True:
            req = ShuffleRequest(self.rec.req.job_id, self.map_id,
                                 self.rec.req.reduce_id, offset,
                                 self.rec.req.chunk_size, host=self.host)
            with self._mu:
                self._issuing = True
                self._inline = self._PENDING
            try:
                # adopt the owning fetch span for this issue: shard
                # streams chain from completion threads, so the
                # explicit parent is the only way their transport
                # spans join the segment's trace tree
                with metrics.use_span(self.rec.parent_span):
                    self.rec.client.start_fetch(req, self._on_complete)
            except Exception as e:  # noqa: BLE001 - sync transport
                # raise == failed stream, same as an error completion
                with self._mu:
                    self._issuing = False
                self.rec._stream_failed(self.idx, self.host, e)
                return
            with self._mu:
                self._issuing = False
                result = self._inline
                self._inline = self._PENDING
            if result is self._PENDING:
                return  # async: _on_complete drives the next step
            offset = self._step(result)
            if offset is None:
                return
            result = self._PENDING

    def _on_complete(self, result) -> None:
        with self._mu:
            if self._issuing:
                self._inline = result
                return
        offset = self._step(result)
        if offset is not None:
            self.issue(offset)

    def _step(self, result) -> Optional[int]:
        """Absorb one completion; returns the next offset to fetch or
        None when the stream ended (complete or failed)."""
        if isinstance(result, Exception):
            self.rec._stream_failed(self.idx, self.host, result)
            return None
        crc = getattr(result, "crc", None)
        if crc is not None:
            import zlib

            if zlib.crc32(result.data) & 0xFFFFFFFF != crc:
                self.rec._stream_failed(self.idx, self.host, StorageError(
                    f"shard chunk CRC mismatch at {self.map_id}:"
                    f"{result.offset}"))
                return None
        self.full_part = result.raw_length  # the decode-trim total
        self.buf += result.data
        if result.is_last:
            self.rec._stream_done(self.idx, self.host, bytes(self.buf),
                                  self.full_part)
            return None
        return result.offset + len(result.data)
