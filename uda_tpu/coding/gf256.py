"""Vectorized GF(2^8) arithmetic — the finite-field kernel under the
Reed-Solomon map-output coding (uda_tpu.coding.rs).

Pure numpy, no native deps: multiplication is one 256x256 table
(``MUL``, 64 KB, built once at import from log/exp tables over the
classic RS polynomial 0x11D with generator 2 — the QR/RS-255 field),
so a scalar-by-vector product is a single fancy-index gather and a
matrix-vector product over chunk bytes is k gathers + k XORs. On this
host that moves ~1 GB/s per core through the decode path — far above
the shuffle fetch rates it sits behind.

Addition/subtraction in GF(2^8) are XOR; ``a/b = a * inv(b)`` with
``inv(a) = EXP[255 - LOG[a]]``. Division by zero raises — a zero pivot
in the decode matrix would mean a non-MDS construction, which the
Cauchy parity rows rule out by design (see rs.py).
"""

from __future__ import annotations

import numpy as np

__all__ = ["EXP", "LOG", "MUL", "gf_mul", "gf_inv", "mul_vec",
           "matmul", "inv_matrix"]

_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1, generator alpha = 2

EXP = np.zeros(510, dtype=np.uint8)
LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    EXP[_i] = _x
    LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= _POLY
EXP[255:510] = EXP[:255]  # wraparound: EXP[i+j] needs no mod 255

# Full multiplication table: MUL[a][b] = a*b in GF(2^8). MUL[a] is a
# 256-entry row, so MUL[a][vec] is the vectorized scalar-vector product.
MUL = np.zeros((256, 256), dtype=np.uint8)
_nz = np.arange(1, 256)
MUL[1:, 1:] = EXP[(LOG[_nz][:, None] + LOG[_nz][None, :]) % 255]


def gf_mul(a: int, b: int) -> int:
    return int(MUL[a, b])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of 0")
    return int(EXP[255 - LOG[a]])


def mul_vec(c: int, v: np.ndarray) -> np.ndarray:
    """Scalar-by-vector product (one table gather)."""
    if c == 0:
        return np.zeros_like(v)
    if c == 1:
        return v
    return MUL[c][v]


def matmul(a: np.ndarray, x: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product: ``a`` is (r, c) uint8, ``x`` is (c, L)
    uint8 (c chunk rows of L bytes) -> (r, L). XOR-accumulated table
    gathers; O(r*c) gathers over L-byte rows."""
    r, c = a.shape
    out = np.zeros((r, x.shape[1]), dtype=np.uint8)
    for i in range(r):
        acc = out[i]
        for j in range(c):
            coeff = int(a[i, j])
            if coeff:
                acc ^= mul_vec(coeff, x[j])
    return out


def inv_matrix(a: np.ndarray) -> np.ndarray:
    """Invert a (k, k) GF(2^8) matrix by Gauss-Jordan elimination.
    Raises ``np.linalg.LinAlgError`` on a singular matrix (cannot
    happen for the k-subsets of the rs.py generator by the Cauchy/MDS
    property — a raise here means corrupted chunk indexing)."""
    k = a.shape[0]
    aug = np.concatenate([a.astype(np.uint8),
                          np.eye(k, dtype=np.uint8)], axis=1)
    for col in range(k):
        pivot = None
        for row in range(col, k):
            if aug[row, col]:
                pivot = row
                break
        if pivot is None:
            raise np.linalg.LinAlgError("singular GF(2^8) matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = gf_inv(int(aug[col, col]))
        aug[col] = mul_vec(inv_p, aug[col])
        for row in range(k):
            if row != col and aug[row, col]:
                aug[row] ^= mul_vec(int(aug[row, col]), aug[col])
    return aug[:, k:].copy()
