"""Background stripe scrub: proactive verification (and optional
repair) of the k-of-n coded map-output layout.

PR 8's coding machinery only ever DECODES on demand — a shard lost
months before the fetch is discovered at reconstruction time, when it
may be the k-th loss. The scrub closes that window: a low-priority
pass re-derives each coded map output's parity from its data region
and checks every peer shard MOF against the bytes the placement rule
says it must hold, counting ``coding.scrub.stripes`` (partitions whose
stripe was verified) and ``coding.scrub.repairs`` (shards found lost
or corrupt). Dump-only by default — mismatches are counted and logged,
never written; ``uda.tpu.coding.scrub.repair`` lets the scrub REBUILD
a lost/corrupt peer shard from the primary's data+parity (the shard is
a pure function of them, so the rewrite is byte-exact).

Scheduling rides the ``tuncache.ensure_fresh`` idiom: ``maybe_scrub``
is a cheap, non-blocking kick any hot path may call — it starts at
most ONE daemon scrub per process and only when the configured
interval (``uda.tpu.coding.scrub.s``, 0 = off) has elapsed since the
last pass; a scrub failure is swallowed into ``errors.swallowed``
(the scrub is an insurance pass, never a job hazard).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Sequence

from uda_tpu.coding import (domain_labels, parse_domains, parse_scheme,
                            rs, stripe_order)
from uda_tpu.mofserver.index import read_index_file, shard_map_id
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["scrub_roots", "maybe_scrub", "scrub_state_reset"]

log = get_logger()


def _expected_shard_chunks(mof: bytes, recs, chunk: int) -> list[bytes]:
    """The bytes shard MOF ``<map>~s<chunk>`` must hold: per partition,
    data chunk ``chunk`` (a slice of the data region) or parity chunk
    ``chunk - k`` (a slice of the parity section)."""
    out = []
    for r in recs:
        st = r.stripe
        blob = mof[r.start_offset:r.start_offset + r.part_length]
        if chunk < st.k:
            out.append(rs.split_data(blob, st.k)[chunk])
        else:
            start, length = st.parity[chunk - st.k]
            out.append(mof[start:start + length])
    return out


def _rebuild_shard_atomic(sdir: str, chunk_bytes: list, full_parts: list
                          ) -> None:
    """Rewrite one shard MOF with rename-into-place semantics: a live
    supplier resolving the shard mid-repair reads either the old bytes
    or the new, never a torn file (``_write_shard`` writes in place —
    fine for the original fan-out, not for repairing a file something
    may be serving). Data lands before the index is replaced, so a
    reader that resolves through the new index finds the new bytes;
    the two renames are not one transaction — the residual window is
    index-new/data-new vs index-old/data-new, both self-consistent
    reads for the byte-range shard layout."""
    import shutil
    import tempfile

    from uda_tpu.mofserver.writer import _write_shard

    tmp = tempfile.mkdtemp(prefix=".scrub_", dir=os.path.dirname(sdir)
                           or ".")
    try:
        _write_shard(tmp, chunk_bytes, full_parts)
        os.makedirs(sdir, exist_ok=True)
        os.replace(os.path.join(tmp, "file.out"),
                   os.path.join(sdir, "file.out"))
        os.replace(os.path.join(tmp, "file.out.index"),
                   os.path.join(sdir, "file.out.index"))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def scrub_job_dir(roots: Sequence[str], primary_index: int,
                  job_id: str, map_id: str, repair: bool = False,
                  domains: Optional[dict] = None) -> dict:
    """Scrub ONE coded map output: parity section vs data region, and
    every peer shard's bytes vs the placement rule. Returns the report
    row; counts coding.scrub.stripes / coding.scrub.repairs."""
    d = os.path.join(roots[primary_index], job_id, map_id)
    recs = read_index_file(os.path.join(d, "file.out.index"),
                           os.path.join(d, "file.out"))
    row = {"map_id": map_id, "stripes": 0, "parity_mismatches": 0,
           "shard_faults": 0, "repaired": 0}
    if not recs or recs[0].stripe is None:
        return row           # uncoded map output: nothing to scrub
    with open(os.path.join(d, "file.out"), "rb") as f:
        mof = f.read()
    st = recs[0].stripe
    # 1. parity section vs data region (the primary's own health)
    for r in recs:
        blob = mof[r.start_offset:r.start_offset + r.part_length]
        want = rs.encode_parity(blob, r.stripe.k, r.stripe.n)
        got = [mof[s:s + ln] for s, ln in r.stripe.parity]
        row["stripes"] += 1
        metrics.add("coding.scrub.stripes")
        if got != want:
            row["parity_mismatches"] += 1
            log.warn(f"scrub: parity mismatch in {d} partition "
                     f"{r.start_offset} (stripe rs:{r.stripe.k}:"
                     f"{r.stripe.n})")
    if row["parity_mismatches"]:
        # an unhealthy PRIMARY must never drive the shard pass: the
        # expected-shard bytes derive from the primary's file.out, so
        # comparing (or worse, repairing) peer shards against corrupt
        # bytes would count every HEALTHY shard as a fault and — in
        # repair mode — overwrite the last good copies of the stripe
        # with the corruption. The primary's own recovery is the
        # reconstruction rung's job (any k of n shards); scrub only
        # reports it.
        log.warn(f"scrub: {d} parity mismatch — primary untrusted, "
                 f"shard checks/repair skipped for this map (rebuild "
                 f"the primary via reconstruction first)")
        return row
    # 2. peer shards vs the placement rule (domain_labels: the ONE
    # label derivation, including the namespace-miss warning)
    h = len(roots)
    order = stripe_order(h, primary_index, domain_labels(roots, domains))
    full_parts = [r.part_length for r in recs]
    for i in range(st.n):
        target = order[i % h]
        if target == primary_index:
            continue         # synthesized from file.out, no bytes
        sdir = os.path.join(roots[target], job_id,
                            shard_map_id(map_id, i))
        want_chunks = _expected_shard_chunks(mof, recs, i)
        ok = False
        try:
            srecs = read_index_file(os.path.join(sdir, "file.out.index"),
                                    os.path.join(sdir, "file.out"))
            with open(os.path.join(sdir, "file.out"), "rb") as f:
                smof = f.read()
            got_chunks = [smof[r.start_offset:r.start_offset
                               + r.part_length] for r in srecs]
            ok = got_chunks == want_chunks
        except Exception as e:  # noqa: BLE001 - a damaged
            # shard IS the finding; count below, never raise out of
            # the insurance pass
            log.debug(f"scrub: shard {sdir} unreadable: {e}")
        if not ok:
            row["shard_faults"] += 1
            metrics.add("coding.scrub.repairs")
            if repair:
                _rebuild_shard_atomic(sdir, want_chunks, full_parts)
                row["repaired"] += 1
                log.warn(f"scrub: rebuilt shard {sdir}")
            else:
                log.warn(f"scrub: shard {sdir} lost/corrupt "
                         f"(dump-only; set uda.tpu.coding.scrub."
                         f"repair to rebuild)")
    return row


def scrub_roots(roots: Sequence[str], repair: bool = False,
                domains: Optional[dict] = None,
                min_age_s: float = 0.0) -> dict:
    """Scrub every coded map output reachable under ``roots``: each
    root is scanned for ``<job>/<map>/file.out.index`` layouts; maps
    whose primary lives under root r are the ones whose full-stripe v2
    index sits there (shard pseudo-dirs are skipped — they are checked
    from their primary). ``min_age_s`` skips maps whose index was
    written within the last N seconds: the striped write lands the
    primary index BEFORE its peer shards (and neither write is
    atomic), so a background pass racing a live writer would book
    phantom shard faults — or, in repair mode, rewrite a shard the
    writer is still producing. ``roots`` are canonicalized (sorted
    unique — the placement order writer and reducer both derive,
    uda_tpu.coding) so shards are checked where the placement rule
    actually put them, whatever order the caller listed the roots in.
    Returns the aggregate report."""
    from uda_tpu.mofserver.index import parse_shard_id

    roots = sorted(set(roots))
    report = {"maps": 0, "stripes": 0, "parity_mismatches": 0,
              "shard_faults": 0, "repaired": 0, "primary_faults": 0,
              "rows": []}
    now = time.time()
    for pi, root in enumerate(roots):
        if not os.path.isdir(root):
            continue
        for job_id in sorted(os.listdir(root)):
            jdir = os.path.join(root, job_id)
            if not os.path.isdir(jdir):
                continue
            for map_id in sorted(os.listdir(jdir)):
                if parse_shard_id(map_id) is not None:
                    continue     # a peer shard, checked via its primary
                idx = os.path.join(jdir, map_id, "file.out.index")
                if not os.path.exists(idx):
                    continue
                if min_age_s > 0:
                    try:
                        if now - os.path.getmtime(idx) < min_age_s:
                            continue   # possibly mid-write: next pass
                    except OSError:
                        continue       # vanished under us: next pass
                try:
                    row = scrub_job_dir(roots, pi, job_id, map_id,
                                        repair=repair, domains=domains)
                except Exception as e:  # noqa: BLE001 - a torn/lost
                    # PRIMARY is itself a finding, and one damaged map
                    # must never abort the pass over its neighbors
                    # (the peer-shard reads below already have this
                    # contract)
                    log.warn(f"scrub: primary map output "
                             f"{job_id}/{map_id} unreadable: {e}")
                    metrics.add("coding.scrub.repairs")
                    report["primary_faults"] = (
                        report.get("primary_faults", 0) + 1)
                    report["rows"].append({"map_id": map_id,
                                           "primary_fault": str(e)})
                    continue
                if row["stripes"]:
                    report["maps"] += 1
                    report["rows"].append(row)
                    for k in ("stripes", "parity_mismatches",
                              "shard_faults", "repaired"):
                        report[k] += row[k]
    return report


# -- the low-priority daemon rung (the tuncache.ensure_fresh idiom) ----------

_SCRUB_MU = threading.Lock()
_SCRUB_ACTIVE = False
# None = never ran (NOT monotonic 0.0: the monotonic epoch is
# unspecified — on a freshly booted host `now < interval` would
# otherwise suppress the first pass until uptime exceeds the interval)
_SCRUB_LAST: Optional[float] = None


def scrub_state_reset() -> None:
    """Test hygiene: forget the last-pass timestamp."""
    global _SCRUB_LAST
    with _SCRUB_MU:
        _SCRUB_LAST = None


def maybe_scrub(cfg, roots: Sequence[str]) -> bool:
    """Kick a background scrub when the interval has elapsed
    (``uda.tpu.coding.scrub.s``; 0 = off) and coding is configured.
    Non-blocking, at most one scrub in flight per process; the caller
    never learns the outcome (counters and logs do). Returns True when
    a pass was started."""
    global _SCRUB_ACTIVE, _SCRUB_LAST
    interval = int(cfg.get("uda.tpu.coding.scrub.s"))
    if interval <= 0 or parse_scheme(
            str(cfg.get("uda.tpu.coding.scheme"))) is None:
        return False
    repair = bool(cfg.get("uda.tpu.coding.scrub.repair"))
    domains = parse_domains(str(cfg.get("uda.tpu.coding.domains")))
    now = time.monotonic()
    with _SCRUB_MU:
        if _SCRUB_ACTIVE or (_SCRUB_LAST is not None
                             and now - _SCRUB_LAST < interval):
            return False
        _SCRUB_ACTIVE = True
        _SCRUB_LAST = now

    roots = list(roots)

    def _run() -> None:
        global _SCRUB_ACTIVE
        try:
            # a daemon pass never scrubs a map written in the last
            # minute — the striped write is not atomic and a live
            # writer's half-landed fan-out is not a fault
            rep = scrub_roots(roots, repair=repair, domains=domains,
                              min_age_s=min(60.0, float(interval)))
            if rep["shard_faults"] or rep["parity_mismatches"]:
                log.warn(f"stripe scrub: {rep['maps']} coded maps, "
                         f"{rep['parity_mismatches']} parity "
                         f"mismatches, {rep['shard_faults']} shard "
                         f"faults ({rep['repaired']} repaired)")
        except Exception as e:  # noqa: BLE001 - the scrub is an
            # insurance pass; a failure must never surface into the
            # data plane that kicked it
            metrics.add("errors.swallowed")
            log.warn(f"stripe scrub failed: {e}")
        finally:
            with _SCRUB_MU:
                _SCRUB_ACTIVE = False

    threading.Thread(target=_run, daemon=True,
                     name="uda-stripe-scrub").start()
    return True
