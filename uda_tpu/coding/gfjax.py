"""On-device GF(2^8) arithmetic for the coded multicast exchange.

The coded stage-B path (uda_tpu.parallel.exchange ``coded_round_body``)
encodes a pod pair's per-destination row blocks INSIDE the jitted round
program, so the field arithmetic has to be expressible in XLA ops. The
records are uint32 row matrices; GF(2^8) acts bytewise, so a
scalar-by-tensor product is four table gathers (one per byte lane of
the word) through the same 256x256 ``MUL`` table uda_tpu.coding.gf256
built for the host codec — addition stays ``bitwise_xor`` on whole
words. Everything is exact integer arithmetic: encode -> decode is
byte-identical by construction, which is what lets the coded exchange
keep the flat oracle's byte-identity gate.

The code itself is the square Cauchy matrix ``A[t, j] = 1/((c + t) ^
j)`` over the ``c = pod_size`` destination blocks — literally the
parity rows of the in-tree Cauchy-RS construction at ``k = c, n = 2c``
(uda_tpu.coding.rs.parity_matrix), whose every square submatrix is
invertible, so the full matrix is too. ``coded_matrices`` returns the
matrix and its inverse (host-side Gauss-Jordan, gf256.inv_matrix);
both ride into the jitted body as compile-time constants.

A decoder only ever needs its OWN destination block, and the owning
chip index is a traced value inside the SPMD body — ``gf_decode_row``
therefore takes the inverse-matrix ROW by traced index and combines
the coded chunks with traced coefficients (the flattened MUL table
indexed at ``coeff * 256 + byte``), instead of materializing all c
decoded blocks and dynamically slicing one.
"""

from __future__ import annotations

import numpy as np

from uda_tpu.coding import gf256, rs
from uda_tpu.utils.errors import ConfigError

__all__ = ["coded_matrices", "gf_scale_words", "gf_matmul_words",
           "gf_decode_row", "MAX_CODED_BLOCKS"]

# the Cauchy points c+t and j must stay distinct inside GF(2^8):
# c + (c-1) <= 255 -> c <= 128
MAX_CODED_BLOCKS = 128

_BYTE_SHIFTS = (0, 8, 16, 24)


def coded_matrices(c: int) -> tuple[np.ndarray, np.ndarray]:
    """The (c, c) encode matrix for ``c`` destination blocks and its
    inverse, both uint8. ``c`` is the pod size — one coded chunk per
    member chip, full rank so any member can recover any block."""
    if not (2 <= c <= MAX_CODED_BLOCKS):
        raise ConfigError(f"coded exchange needs 2 <= pod_size <= "
                          f"{MAX_CODED_BLOCKS}, got {c}")
    enc = rs.parity_matrix(c, 2 * c)
    return enc, gf256.inv_matrix(enc)


def gf_scale_words(coeff: int, x):
    """``coeff * x`` in GF(2^8), bytewise over a uint32 tensor.
    ``coeff`` is a STATIC python int (an encode-matrix entry)."""
    import jax.numpy as jnp

    coeff = int(coeff)
    if coeff == 0:
        return jnp.zeros_like(x)
    if coeff == 1:
        return x
    tab = jnp.asarray(gf256.MUL[coeff], jnp.uint32)
    out = jnp.zeros_like(x)
    for shift in _BYTE_SHIFTS:
        b = (x >> np.uint32(shift)) & np.uint32(0xFF)
        out = out | (jnp.take(tab, b) << np.uint32(shift))
    return out


def gf_matmul_words(mat: np.ndarray, blocks):
    """GF(2^8) matrix action on stacked uint32 blocks: ``mat`` is a
    STATIC (r, k) uint8 matrix, ``blocks`` is uint32[k, ...]; returns
    uint32[r, ...] where row t = XOR_j mat[t, j] * blocks[j]. The
    static coefficients unroll at trace time (k^2 scalar products of
    4 gathers each — c <= 8 on every bench mesh)."""
    import jax.numpy as jnp

    outs = []
    for t in range(mat.shape[0]):
        acc = None
        for j in range(mat.shape[1]):
            coeff = int(mat[t, j])
            if coeff == 0:
                continue
            term = gf_scale_words(coeff, blocks[j])
            acc = term if acc is None else acc ^ term
        outs.append(acc if acc is not None
                    else jnp.zeros_like(blocks[0]))
    return jnp.stack(outs)


def gf_decode_row(inv, row_index, chunks):
    """One decoded block: ``XOR_t inv[row_index, t] * chunks[t]`` with
    ``row_index`` TRACED (the decoder's own chip index inside the SPMD
    body). ``inv`` is the static (k, k) uint8 inverse; the traced
    coefficients index the flattened MUL table at ``coeff*256 + byte``
    (coeff 0 rows of the table are all zero, so zero coefficients
    vanish without a branch)."""
    import jax.numpy as jnp

    k = int(inv.shape[0])
    inv_dev = jnp.asarray(inv, jnp.uint32)
    mul_flat = jnp.asarray(gf256.MUL.reshape(-1), jnp.uint32)
    coeffs = inv_dev[row_index]                   # [k], traced
    acc = jnp.zeros_like(chunks[0])
    for t in range(k):
        base = coeffs[t] * np.uint32(256)
        term = jnp.zeros_like(chunks[t])
        for shift in _BYTE_SHIFTS:
            b = (chunks[t] >> np.uint32(shift)) & np.uint32(0xFF)
            term = term | (jnp.take(mul_flat, base + b)
                           << np.uint32(shift))
        acc = acc ^ term
    return acc
