"""k-of-n erasure-coded map outputs (the survivable-shuffle layer).

Coded TeraSort (arXiv:1702.04850) showed that trading cheap redundant
compute for scarce shuffle bandwidth wins whenever compute is abundant
— which on TPU hosts it is. This package applies the idea to supplier
LOSS rather than bandwidth: with ``uda.tpu.coding.scheme=rs:k:n`` each
map partition's on-disk bytes are a systematic Reed-Solomon stripe —
k data chunks + (n-k) parity chunks over GF(2^8) (uda_tpu.coding.rs,
pure numpy) — spread over n suppliers, and the reduce side can rebuild
the partition from ANY k of them when the primary is dead or penalized
(uda_tpu.coding.recovery, the post-retry rung of the Segment ladder).

Layout contract (shared with uda_tpu.mofserver):

- the PRIMARY supplier holds the full plain MOF with the parity chunks
  appended as a parity section (data offsets byte-identical to the
  uncoded layout) and a v2 index recording the stripe (index.py);
- stripe chunk ``i`` is addressable as the shard pseudo-map
  ``<map_id>~s<i>`` — a tiny MOF of its own on peer suppliers, or a
  synthesized byte range of the primary's file.out (both resolve
  through the ordinary DirIndexResolver, so the whole data plane —
  DataEngine, wire, zero-copy serve — serves shards unchanged);
- placement is derived over the job's canonically-ordered supplier
  list (sorted unique host strings) by :func:`stripe_host`: the
  positional rotation ``(p + i) % num_suppliers`` by default, or —
  with ``uda.tpu.coding.domains`` declared — a FAILURE-DOMAIN-aware
  interleave (:func:`stripe_order`) that walks the domains round-robin
  so one rack/power domain never accumulates enough of a stripe's
  shards to make it unrecoverable. Writer and reducer derive it
  independently from the same rule and the same domain map — no
  placement metadata travels. Chunk 0 always stays on the primary
  (its chunks are synthesized from file.out, never duplicated).

The decoder slots in BELOW DecompressingClient and the CRC layer:
reconstruction rebuilds the partition's on-disk bytes, so compression
and integrity checking downstream stay byte-agnostic.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from uda_tpu.mofserver.index import parse_shard_id, shard_map_id
from uda_tpu.utils.errors import ConfigError
from uda_tpu.utils.logging import get_logger

__all__ = ["CodingScheme", "parse_scheme", "parse_domains",
           "domain_labels", "stripe_order", "stripe_host",
           "shard_map_id", "parse_shard_id"]

log = get_logger()


@dataclasses.dataclass(frozen=True)
class CodingScheme:
    """One parsed ``uda.tpu.coding.scheme`` value (``rs:k:n``)."""

    k: int
    n: int

    @property
    def parity(self) -> int:
        return self.n - self.k

    def __str__(self) -> str:
        return f"rs:{self.k}:{self.n}"


def parse_scheme(spec: str) -> Optional[CodingScheme]:
    """``"rs:k:n"`` -> CodingScheme; empty/None -> None (coding off)."""
    spec = (spec or "").strip()
    if not spec:
        return None
    parts = spec.split(":")
    if len(parts) != 3 or parts[0] != "rs":
        raise ConfigError(f"bad uda.tpu.coding.scheme {spec!r} "
                          f"(want rs:<k>:<n>)")
    try:
        k, n = int(parts[1]), int(parts[2])
    except ValueError as e:
        raise ConfigError(f"bad uda.tpu.coding.scheme {spec!r}: {e}") from e
    if not (1 <= k <= n <= 255):
        raise ConfigError(f"bad uda.tpu.coding.scheme {spec!r} "
                          f"(need 1 <= k <= n <= 255)")
    return CodingScheme(k, n)


def parse_domains(spec: str) -> dict:
    """``uda.tpu.coding.domains`` -> {supplier: domain}. The spec is
    ``'host=domain,host=domain,...'``; empty/None -> {} (positional
    rotation). A segment without '=' is a ConfigError — a silently
    dropped declaration would quietly degrade the placement back to
    rotation on exactly the host someone meant to protect."""
    spec = (spec or "").strip()
    if not spec:
        return {}
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, sep, dom = part.partition("=")
        if not sep or not host.strip() or not dom.strip():
            raise ConfigError(f"bad uda.tpu.coding.domains segment "
                              f"{part!r} (want host=domain)")
        out[host.strip()] = dom.strip()
    return out


def stripe_order(count: int, primary_index: int,
                 domains: Optional[Sequence[str]] = None) -> list:
    """The placement permutation of supplier INDICES for one stripe:
    position i of the result holds chunk i. Without ``domains`` it is
    the positional rotation ``(primary + i) % count`` (the PR 8 rule,
    unchanged). With ``domains`` (one label per supplier index;
    undeclared suppliers should be pre-mapped to singleton domains by
    the caller) the order interleaves ROUND-ROBIN across domains —
    primary's domain first, then the others by first appearance —
    taking each domain's suppliers in rotation order, so consecutive
    chunks land in distinct domains while any remain: a stripe's n
    shards spread ``ceil``-evenly and no domain accumulates more than
    ``ceil(n / num_domains)`` of them. Position 0 is ALWAYS the
    primary (chunk 0 is synthesized from its file.out)."""
    if count <= 0:
        return []
    primary_index %= count
    rotation = [(primary_index + i) % count for i in range(count)]
    if not domains:
        return rotation
    if len(domains) != count:
        raise ConfigError(f"stripe_order: {len(domains)} domain labels "
                          f"for {count} suppliers")
    # group the rotation by domain, preserving rotation order inside
    # each; ring the domains by first appearance along the rotation
    # (primary's domain is first by construction)
    ring: list = []
    by_dom: dict = {}
    for idx in rotation:
        dom = domains[idx]
        if dom not in by_dom:
            by_dom[dom] = []
            ring.append(dom)
        by_dom[dom].append(idx)
    order = []
    cursors = {dom: 0 for dom in ring}
    while len(order) < count:
        for dom in ring:
            cur = cursors[dom]
            if cur < len(by_dom[dom]):
                order.append(by_dom[dom][cur])
                cursors[dom] = cur + 1
    return order[:count]


_WARNED_NAMESPACES: set = set()


def domain_labels(suppliers: Sequence[str],
                  domains: Optional[dict]) -> Optional[list]:
    """Per-supplier domain labels for :func:`stripe_order`, or None
    when no domains are declared. The writer keys ``uda.tpu.coding.
    domains`` by supplier ROOTS and the reduce side by HOST names —
    ONE spec must therefore declare BOTH namespaces (extra keys are
    harmless; each side matches its own). A declared map that matches
    NONE of this side's suppliers silently degrades every supplier to
    a singleton domain — which is exactly the positional rotation, so
    writer and reducer still AGREE when both sides miss, but a
    one-sided miss would place shards where the other side never
    looks: warn LOUDLY (once per supplier set) so the misdeclared
    namespace is caught before the k-th loss needs the placement."""
    if not domains:
        return None
    if not any(s in domains for s in suppliers):
        # warn once per (supplier set, SPEC) — a re-edited spec that
        # is still mismatched must warn again; bounded so a long-lived
        # daemon's many jobs cannot grow the set without limit
        key = (tuple(sorted(suppliers)),
               tuple(sorted(domains.items())))
        if key not in _WARNED_NAMESPACES:
            if len(_WARNED_NAMESPACES) >= 256:
                _WARNED_NAMESPACES.clear()
            _WARNED_NAMESPACES.add(key)
            log.warn(
                f"uda.tpu.coding.domains declares {len(domains)} "
                f"entr(ies) but matches NONE of this side's suppliers "
                f"{list(suppliers)[:4]}... — placement degrades to "
                f"the positional rotation HERE; if the other side's "
                f"namespace matches, writer and reducer DISAGREE. "
                f"Declare both namespaces (hosts and writer roots) in "
                f"the one spec.")
    return [domains.get(s, s) for s in suppliers]


def stripe_host(suppliers: Sequence[str], primary: str, chunk: int,
                domains: Optional[dict] = None) -> str:
    """The supplier holding stripe chunk ``chunk`` of a map whose
    primary is ``primary``: the :func:`stripe_order` permutation
    (positional rotation, or failure-domain interleave when
    ``domains`` — a {supplier: domain} map — is declared; suppliers
    absent from the map count as their own singleton domain). A
    primary absent from the list (a supplier the reduce side never
    saw as a map host) anchors at index 0 — placement stays total
    either way."""
    if not suppliers:
        return primary
    suppliers = list(suppliers)
    try:
        p = suppliers.index(primary)
    except ValueError:
        p = 0
    order = stripe_order(len(suppliers), p,
                         domain_labels(suppliers, domains))
    return suppliers[order[chunk % len(suppliers)]]
