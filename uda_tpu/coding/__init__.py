"""k-of-n erasure-coded map outputs (the survivable-shuffle layer).

Coded TeraSort (arXiv:1702.04850) showed that trading cheap redundant
compute for scarce shuffle bandwidth wins whenever compute is abundant
— which on TPU hosts it is. This package applies the idea to supplier
LOSS rather than bandwidth: with ``uda.tpu.coding.scheme=rs:k:n`` each
map partition's on-disk bytes are a systematic Reed-Solomon stripe —
k data chunks + (n-k) parity chunks over GF(2^8) (uda_tpu.coding.rs,
pure numpy) — spread over n suppliers, and the reduce side can rebuild
the partition from ANY k of them when the primary is dead or penalized
(uda_tpu.coding.recovery, the post-retry rung of the Segment ladder).

Layout contract (shared with uda_tpu.mofserver):

- the PRIMARY supplier holds the full plain MOF with the parity chunks
  appended as a parity section (data offsets byte-identical to the
  uncoded layout) and a v2 index recording the stripe (index.py);
- stripe chunk ``i`` is addressable as the shard pseudo-map
  ``<map_id>~s<i>`` — a tiny MOF of its own on peer suppliers, or a
  synthesized byte range of the primary's file.out (both resolve
  through the ordinary DirIndexResolver, so the whole data plane —
  DataEngine, wire, zero-copy serve — serves shards unchanged);
- placement is positional over the job's canonically-ordered supplier
  list (sorted unique host strings): chunk i of a map whose primary
  sits at index p lives on supplier ``(p + i) % num_suppliers``
  (:func:`stripe_host`). Writer and reducer derive it independently
  from the same rule — no placement metadata travels.

The decoder slots in BELOW DecompressingClient and the CRC layer:
reconstruction rebuilds the partition's on-disk bytes, so compression
and integrity checking downstream stay byte-agnostic.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from uda_tpu.mofserver.index import parse_shard_id, shard_map_id
from uda_tpu.utils.errors import ConfigError

__all__ = ["CodingScheme", "parse_scheme", "stripe_host", "shard_map_id",
           "parse_shard_id"]


@dataclasses.dataclass(frozen=True)
class CodingScheme:
    """One parsed ``uda.tpu.coding.scheme`` value (``rs:k:n``)."""

    k: int
    n: int

    @property
    def parity(self) -> int:
        return self.n - self.k

    def __str__(self) -> str:
        return f"rs:{self.k}:{self.n}"


def parse_scheme(spec: str) -> Optional[CodingScheme]:
    """``"rs:k:n"`` -> CodingScheme; empty/None -> None (coding off)."""
    spec = (spec or "").strip()
    if not spec:
        return None
    parts = spec.split(":")
    if len(parts) != 3 or parts[0] != "rs":
        raise ConfigError(f"bad uda.tpu.coding.scheme {spec!r} "
                          f"(want rs:<k>:<n>)")
    try:
        k, n = int(parts[1]), int(parts[2])
    except ValueError as e:
        raise ConfigError(f"bad uda.tpu.coding.scheme {spec!r}: {e}") from e
    if not (1 <= k <= n <= 255):
        raise ConfigError(f"bad uda.tpu.coding.scheme {spec!r} "
                          f"(need 1 <= k <= n <= 255)")
    return CodingScheme(k, n)


def stripe_host(suppliers: Sequence[str], primary: str, chunk: int) -> str:
    """The supplier holding stripe chunk ``chunk`` of a map whose
    primary is ``primary``: positional rotation over the canonically
    ordered supplier list. A primary absent from the list (a supplier
    the reduce side never saw as a map host) anchors at index 0 —
    placement stays total either way."""
    if not suppliers:
        return primary
    try:
        p = list(suppliers).index(primary)
    except ValueError:
        p = 0
    return suppliers[(p + chunk) % len(suppliers)]
