"""Systematic k-of-n Reed-Solomon over GF(2^8) for map-output stripes.

The construction is Cauchy-RS (the jerasure/Coded-TeraSort shape,
arXiv:1702.04850): the generator is ``[I_k ; C]`` where ``C`` is an
(n-k) x k Cauchy matrix ``C[j,i] = 1/(x_j + y_i)`` with disjoint
``x_j = k+j`` and ``y_i = i``. Every k x k submatrix of such a stacked
matrix is invertible (the MDS property: deleting identity rows reduces
the minor to a smaller Cauchy minor, and every Cauchy minor is
nonsingular), so ANY k of the n stripe chunks reconstruct the data.

Systematic means chunks ``0..k-1`` ARE the data (byte slices of the
partition blob) — the healthy path never decodes, and ``n == k``
degenerates to plain chunking with zero parity and byte identity by
construction.

Stripe geometry: a blob of L bytes codes as k data chunks of
``chunk_len = ceil(L/k)`` (the last one short; coding pads with zeros
virtually) plus ``n-k`` parity chunks of exactly ``chunk_len``.
Decoding trims back to L. ``L == 0`` is the empty stripe: no chunks
carry bytes and decode returns ``b""``.
"""

from __future__ import annotations

import numpy as np

from uda_tpu.coding import gf256
from uda_tpu.utils.errors import StorageError

__all__ = ["chunk_len", "parity_matrix", "encode_parity", "split_data",
           "decode"]

_MAX_N = 255  # x_j/y_i live in GF(2^8); n beyond that has no MDS rows


def _validate(k: int, n: int) -> None:
    if not (1 <= k <= n <= _MAX_N):
        raise StorageError(f"bad RS stripe geometry k={k}, n={n} "
                           f"(need 1 <= k <= n <= {_MAX_N})")


def chunk_len(total_len: int, k: int) -> int:
    return (total_len + k - 1) // k if total_len > 0 else 0


def parity_matrix(k: int, n: int) -> np.ndarray:
    """The (n-k, k) Cauchy parity rows."""
    _validate(k, n)
    rows = n - k
    c = np.zeros((rows, k), dtype=np.uint8)
    for j in range(rows):
        for i in range(k):
            c[j, i] = gf256.gf_inv((k + j) ^ i)
    return c


def split_data(blob: bytes, k: int) -> list[bytes]:
    """The k systematic data chunks (unpadded byte slices; the last may
    be short or empty)."""
    cl = chunk_len(len(blob), k)
    if cl == 0:
        return [b""] * k
    return [bytes(blob[i * cl:(i + 1) * cl]) for i in range(k)]


def _padded_matrix(chunks: list[bytes], cl: int) -> np.ndarray:
    m = np.zeros((len(chunks), cl), dtype=np.uint8)
    for i, ch in enumerate(chunks):
        if len(ch) > cl:
            raise StorageError(f"stripe chunk {i} longer than chunk_len "
                               f"({len(ch)} > {cl})")
        if ch:
            m[i, :len(ch)] = np.frombuffer(ch, dtype=np.uint8)
    return m


def encode_parity(blob: bytes, k: int, n: int) -> list[bytes]:
    """The n-k parity chunks of ``blob``'s stripe, each exactly
    ``chunk_len(len(blob), k)`` bytes (empty list when n == k or the
    blob is empty)."""
    _validate(k, n)
    if n == k:
        return []
    if not blob:
        return [b""] * (n - k)  # the empty stripe: uniform shape
    cl = chunk_len(len(blob), k)
    data = _padded_matrix(split_data(blob, k), cl)
    parity = gf256.matmul(parity_matrix(k, n), data)
    return [parity[j].tobytes() for j in range(n - k)]


def decode(chunks: dict[int, bytes], k: int, n: int,
           total_len: int) -> bytes:
    """Reconstruct the original blob from ANY k of the n stripe chunks.

    ``chunks`` maps chunk index (0..n-1) to its bytes — data chunks may
    be short (the stored tail is unpadded); parity chunks must be full
    ``chunk_len`` long. Extra entries beyond k are ignored (data
    preferred, then lowest index). Raises StorageError when fewer than
    k distinct chunks are supplied.
    """
    _validate(k, n)
    if total_len == 0:
        return b""
    have = sorted(chunks)
    if any(i < 0 or i >= n for i in have):
        raise StorageError(f"stripe chunk index out of range in {have} "
                           f"(n={n})")
    if len(have) < k:
        raise StorageError(f"stripe unrecoverable: {len(have)} of the "
                           f"required {k} chunks present (have {have})")
    cl = chunk_len(total_len, k)
    # prefer the systematic chunks: identity rows cost nothing to invert
    use = sorted(have, key=lambda i: (i >= k, i))[:k]
    if use == list(range(k)):  # all-data fast path: pure concatenation
        out = b"".join(chunks[i][:cl] for i in range(k))
        return out[:total_len]
    cauchy = parity_matrix(k, n)
    rows = np.zeros((k, k), dtype=np.uint8)
    for r, idx in enumerate(use):
        if idx < k:
            rows[r, idx] = 1
        else:
            rows[r] = cauchy[idx - k]
    shards = _padded_matrix([chunks[i] for i in use], cl)
    data = gf256.matmul(gf256.inv_matrix(rows), shards)
    return data.reshape(-1).tobytes()[:total_len]
