"""Supplier data engine: bounded chunk pool + threaded segment reads.

TPU-native rebuild of the reference's DataEngine (reference
src/MOFServer/IndexInfo.cc:97-376): the libaio O_DIRECT read loop with a
1000-chunk pool becomes a pread thread pool (one pool per local dir,
``mapred.uda.provider.blocked.threads.per.disk`` threads each — the
capability of the orphaned AsyncIO/ reader, reference
src/AsyncIO/AsyncReaderManager.cc:16-50, now actually wired in).

Backpressure: the reference bounded supplier memory with a 1000-chunk
free list (occupy_chunk blocking when empty, IndexInfo.cc:276-292). Here
in-flight memory is bounded structurally instead: every Segment keeps at
most ONE outstanding request (uda_tpu.merger.segment), and the
MergeManager's fetch window caps concurrently-active segments
(``mapred.rdma.wqe.per.conn``), so in-flight bytes <= window x
chunk_size. A blocking budget inside ``submit`` is deliberately avoided:
chained fetches are re-issued from worker-thread completion callbacks,
and blocking there can deadlock the pool.

A fetch request asks for up to ``chunk_size`` bytes of one partition at
``offset`` within the partition; the reply carries (raw_length,
part_length, actual bytes, mof_offset) — the fields of the reference's
RDMA ACK message ("rawLen:partLen:sentSize:mofOffset:path",
src/DataNet/RDMAServer.cc:537-631). Refcounted fd reuse mirrors the
reference's fd_counter map (IndexInfo.cc:195-233).

The batched host-I/O plane (``submit_batch``; PARITY C15 consumed)
amortizes the per-op costs this host measured in PR 6 (~20 us
syscalls, ~100 us pool handoffs): one pool handoff per request burst,
per-fd grouping + gap-threshold range coalescing, and vectored reads
down the io_uring -> preadv -> pread backend ladder
(``uda.tpu.read.backend``; README "Host I/O & self-tuning").
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Dict, List, Optional, Sequence

from uda_tpu.mofserver.index import IndexResolver
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import ConfigError, StorageError
from uda_tpu.utils.failpoints import failpoint, failpoints
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics
from uda_tpu.utils.resledger import resledger

__all__ = ["ShuffleRequest", "FetchResult", "FdSlice", "DataEngine",
           "plan_coalesced", "BATCH_BACKENDS"]

log = get_logger()

# The batched-read backend ladder, best rung first (the RDMAbox lesson,
# arXiv:2104.12197: amortize per-op syscall/handoff cost by batching
# submissions). "io_uring" = the native ReadPool's kernel ring (PARITY
# C15's reserved slot, compiled in when the build host has the uapi
# header, selected only when the RUNNING kernel accepts
# io_uring_setup); "preadv" = one os.preadv per coalesced run;
# "pread" = per-request os.pread on the batch worker (one pool handoff
# per batch — the floor every host has).
BATCH_BACKENDS = ("io_uring", "preadv", "pread")

# the native-reader-unavailable fallback is warned ONCE per process
# (a fleet of engines must not spam the log; every occurrence still
# counts io.native.unavailable — the errors.swallowed posture)
_native_warn_lock = threading.Lock()
_native_warned = False


def _warn_native_unavailable(cause: Exception) -> None:
    global _native_warned
    metrics.add("io.native.unavailable")
    with _native_warn_lock:
        first = not _native_warned
        _native_warned = True
    if first:
        log.warn(f"native reader unavailable, using os.pread: {cause}")
    else:
        log.debug(f"native reader unavailable (counted): {cause}")


@dataclasses.dataclass(frozen=True)
class ShuffleRequest:
    """One chunk fetch (reference shuffle_req_t, src/MOFServer/
    IndexInfo.h:64-77: jobid, map, reduceID, map_offset, chunk_size).

    ``host`` identifies the supplier serving this map output (the
    reference addresses fetches per supplier host, RDMAClient.cc:
    498-527); single-host transports ignore it.

    ``tenant`` is the multi-tenant service plane's in-process stamp:
    the ShuffleServer copies its connection's MSG_JOB binding here
    before submitting, so the engine's per-tenant admission partitions
    and metric labels key on it. It never rides the wire (the REQ
    frame carries job identity; the TENANT identity is the
    connection's authenticated binding — a client cannot spoof a
    neighbor's tenant per request). Empty = untenanted (the
    single-job default, exact PR 1-13 behavior)."""

    job_id: str
    map_id: str
    reduce_id: int
    offset: int          # offset within the partition's record bytes
    chunk_size: int
    host: str = ""
    tenant: str = ""


@dataclasses.dataclass
class FetchResult:
    """Reply payload (reference ACK fields, RDMAServer.cc:597-607).

    ``raw_length`` is the partition's uncompressed record-byte size and
    ``part_length`` its on-disk size (they differ under compression,
    matching Hadoop's spill-index semantics); ``last`` is set by the
    producer in whatever domain it serves (DataEngine: on-disk bytes;
    DecompressingClient: uncompressed stream).

    ``data`` is bytes-LIKE, not necessarily bytes: the event-loop
    client donates its per-frame receive bytearray straight into this
    field (zero-copy receive), so consumers must stay buffer-agnostic
    (len/crc32/decompress/``bytes + data`` concatenation all are).
    """

    data: bytes  # bytes-like (bytes or bytearray); see docstring
    raw_length: int      # total uncompressed record bytes of the partition
    part_length: int     # total on-disk bytes of the partition
    offset: int          # echo of the request offset
    path: str
    last: bool           # required: a defaulted value silently truncated
                         # multi-chunk streams once; producers must decide
    crc: Optional[int] = None  # CRC32 of the chunk as read from disk
                               # (uda.tpu.fetch.crc); None = unchecked

    @property
    def is_last(self) -> bool:
        return self.last


@dataclasses.dataclass
class FdSlice:
    """A zero-copy serve plan: one chunk of a MOF described as
    ``(fd, offset, length)`` instead of bytes — the event-loop server
    streams it with ``os.sendfile`` so the chunk never transits the
    Python heap (the reference's RDMA-WRITE-from-registered-MOF-memory
    shape, RDMAServer.cc:537-631, minus the NIC).

    Holds one fd-cache reference AND the request's admission charge
    until :meth:`release` — bytes on their way to the wire stay inside
    the supplier read budget exactly like bytes sitting in a
    FetchResult would. ``release()`` is idempotent and MUST be called
    exactly-once-effective on every path (written, torn, dropped)."""

    fd: int
    file_offset: int     # absolute offset in the MOF file
    length: int          # chunk bytes to serve
    raw_length: int      # the FetchResult ACK fields, verbatim
    part_length: int
    offset: int          # echo of the request offset
    path: str
    last: bool
    _engine: "DataEngine" = dataclasses.field(repr=False, default=None)
    _admitted: int = 0
    _released: bool = False
    _tenant: str = ""    # the admission charge's tenant partition

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._engine._fds.release(self.path)
        if self._admitted:
            self._engine._unadmit(self._admitted, self._tenant)

    def view(self):
        """A memoryview of the chunk inside the MOF's cached whole-file
        mmap (the serve path's mmap mode: sent with ``sendmsg``, the
        bytes go page-cache -> socket without a Python-heap object).
        None when the file cannot be mapped — caller falls back to
        sendfile. Only valid while this slice is unreleased; callers
        must drop the view before (or with) release()."""
        if self._released:
            return None
        mm = self._engine._fds.mmap_for(self.path)
        if mm is None:
            return None
        return memoryview(mm)[self.file_offset:
                              self.file_offset + self.length]


class _FdCache:
    """Refcounted fd reuse across in-flight requests for the same MOF
    (reference fd_counter, IndexInfo.cc:195-233), with an optional
    per-entry read-only ``mmap`` of the whole file — the registered-
    memory analogue the zero-copy serve path's mmap mode slices
    memoryviews out of (one map per MOF, zero per-chunk syscalls).

    Entries whose refcount hits zero are RETAINED idle (LRU, up to
    ``_IDLE_CAP``) instead of closed: the serve path acquires/releases
    once per chunk, and paying an open+close (+ mmap/munmap) syscall
    round trip per chunk dominated the serve critical path on
    emulated-syscall kernels — this is the reference's registered-
    memory-stays-registered property. Eviction and close_all() still
    close for real."""

    _IDLE_CAP = 128

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # path -> [fd, refs, mmap|None]
        self._fds: Dict[str, list] = {}
        self._idle: list = []  # LRU order of refs==0 paths (front=oldest)

    def acquire(self, path: str) -> int:
        with self._lock:
            ent = self._fds.get(path)
            if ent:
                if ent[1] == 0:
                    self._idle.remove(path)
                ent[1] += 1
                resledger.acquire("engine.fd", key=path, owner=id(self))
                return ent[0]
        fd = os.open(path, os.O_RDONLY)
        with self._lock:
            ent = self._fds.get(path)
            if ent:  # raced: keep the existing one
                if ent[1] == 0:
                    self._idle.remove(path)
                ent[1] += 1
                os.close(fd)
                resledger.acquire("engine.fd", key=path, owner=id(self))
                return ent[0]
            self._fds[path] = [fd, 1, None]
            resledger.acquire("engine.fd", key=path, owner=id(self))
            return fd

    def mmap_for(self, path: str):
        """The whole-file read-only map for an entry the caller holds a
        reference on (lazily created, cached with the fd). None when
        the file cannot be mapped (empty file, exotic fs) — the caller
        falls back to sendfile/pread."""
        import mmap as mmap_mod

        with self._lock:
            ent = self._fds.get(path)
            if ent is None:
                return None
            if ent[2] is not None:
                return ent[2]
            fd = ent[0]
        try:
            mm = mmap_mod.mmap(fd, 0, prot=mmap_mod.PROT_READ)
        except (ValueError, OSError):
            return None
        with self._lock:
            ent = self._fds.get(path)
            if ent is None or ent[2] is not None:
                mm.close()
                return ent[2] if ent else None
            ent[2] = mm
            return mm

    @staticmethod
    def _close_entry(fd: int, mm) -> None:
        if mm is not None:
            try:
                mm.close()
            except BufferError:
                # a serve-path memoryview still points into the map
                # (abandoned mid-write item not yet collected): leaking
                # the map until process exit beats a crash
                log.warn("mmap still exported at fd-cache release; "
                         "leaking the mapping")
        os.close(fd)

    def release(self, path: str) -> None:
        evicted = None
        with self._lock:
            ent = self._fds.get(path)
            if not ent:
                return
            ent[1] -= 1
            # one ref settled (the ledger ignores an over-release's
            # unmatched settle, same as the refcount clamp below)
            resledger.settle("engine.fd", key=path, owner=id(self))
            if ent[1] > 0:
                return
            ent[1] = 0
            if path in self._idle:
                return  # defensive: an over-release must not double-add
            # keep the entry idle (fd + mmap stay warm); evict the
            # oldest idle entry beyond the cap
            self._idle.append(path)
            if len(self._idle) > self._IDLE_CAP:
                victim = self._idle.pop(0)
                evicted = self._fds.pop(victim, None)
        if evicted is not None:
            self._close_entry(evicted[0], evicted[2])

    def close_all(self) -> None:
        with self._lock:
            ents = list(self._fds.values())
            self._fds.clear()
            self._idle.clear()
        for fd, _, mm in ents:
            self._close_entry(fd, mm)


# one coalesced run never exceeds this many entries: each entry costs
# up to two iovecs (its buffer + a gap scratch view), and preadv
# rejects more than IOV_MAX (1024) buffers per call with EINVAL — a
# config/tuning-cache batch_max above 512 must split runs, not turn a
# whole burst's reads into errors
_MAX_RUN_ITEMS = 511


def plan_coalesced(ranges: Sequence[tuple], gap_bytes: int,
                   max_run_bytes: int,
                   max_items: int = _MAX_RUN_ITEMS) -> List[list]:
    """Group ``(item, file_off, length)`` triples into coalesced runs:
    within a run, ranges ascend, never overlap, successive ranges are
    at most ``gap_bytes`` apart, the whole read span stays under
    ``max_run_bytes`` and the run holds at most ``max_items`` entries
    (the IOV_MAX bound) — each run becomes ONE vectored read (the gaps
    are read into scratch and discarded). Overlapping or duplicate
    ranges start a fresh run: a scatter list cannot write the same
    disk bytes into two buffers in one preadv. Pure planning (no IO),
    unit-tested directly."""
    if not ranges:
        return []
    ordered = sorted(ranges, key=lambda r: (r[1], r[2]))
    runs: List[list] = []
    run: list = [ordered[0]]
    run_start = ordered[0][1]
    run_end = ordered[0][1] + ordered[0][2]
    for item in ordered[1:]:
        _, off, length = item
        if (off >= run_end and off - run_end <= gap_bytes
                and (off + length) - run_start <= max_run_bytes
                and len(run) < max_items):
            run.append(item)
            run_end = off + length
        else:
            runs.append(run)
            run = [item]
            run_start, run_end = off, off + length
    runs.append(run)
    return runs


def _preadv_full(fd: int, bufs: Sequence, offset: int) -> tuple:
    """os.preadv until every buffer is full or EOF: one scatter read
    for the common case, continuation reads re-sliced past the filled
    prefix when the kernel returns short (pipe-sized transfers,
    signals). Returns (bytes_read, syscalls)."""
    views = [memoryview(b) for b in bufs]
    lens = [len(v) for v in views]
    total = sum(lens)
    got = 0
    syscalls = 0
    while got < total:
        acc = 0
        i = 0
        while i < len(views) and acc + lens[i] <= got:
            acc += lens[i]
            i += 1
        iov = [views[i][got - acc:]] + views[i + 1:]
        n = os.preadv(fd, iov, offset + got)
        syscalls += 1
        if n <= 0:
            break  # EOF mid-run: callers fail the unfilled ranges
        got += n
    return got, syscalls


class _BatchEntry:
    """One request's slot in a submitted batch: the future the caller
    holds, the accounting it owes, and the per-request state the batch
    worker fills in as the stages (resolve -> read -> finish) run.
    ``err`` short-circuits later stages — one failing request never
    touches its batch-mates (per-request error isolation)."""

    __slots__ = ("req", "want_admit", "fut", "parent_span", "rec",
                 "want", "file_off", "fd", "buf", "got", "err")

    def __init__(self, req: ShuffleRequest, want_admit: int, fut: Future,
                 parent_span=None):
        self.req = req
        self.want_admit = want_admit
        self.fut = fut
        self.parent_span = parent_span
        self.rec = None
        self.want = 0          # actual chunk bytes (clamped to the MOF)
        self.file_off = 0
        self.fd = -1
        self.buf = None        # per-request read buffer (bytearray)
        self.got = 0           # bytes actually landed in buf
        self.err: Optional[Exception] = None


class _NativeReads:
    """Routes blocking reads through the native ReadPool: a router thread
    drains the pool's completion queue (the io_getevents analogue) and
    wakes the submitting thread by tag. Submit and waiter registration
    are atomic under the same lock the router needs to deliver, so a
    completion can never beat its waiter's registration."""

    def __init__(self, pool):
        self.pool = pool
        self._lock = threading.Lock()
        self._waiters: dict[int, list] = {}   # tag -> [Event, data|None]
        self._stop = False
        self._router = threading.Thread(target=self._route, daemon=True,
                                        name="uda-native-router")
        self._router.start()

    def _route(self) -> None:
        while not self._stop:
            events = self.pool.poll(min_events=1, timeout=0.2)
            with self._lock:
                for tag, result in events:
                    w = self._waiters.pop(tag, None)
                    if w is not None:
                        w[1] = result
                        w[0].set()

    def read(self, fd: int, offset: int, length: int) -> bytes:
        waiter = [threading.Event(), None]
        with self._lock:
            tag = self.pool.submit(fd, offset, length)
            self._waiters[tag] = waiter
        if not waiter[0].wait(timeout=60.0):
            with self._lock:
                self._waiters.pop(tag, None)  # don't leak the entry
            raise StorageError("native read timed out")
        result = waiter[1]
        if isinstance(result, Exception):
            raise result
        return result.tobytes()

    def read_batch(self, jobs: Sequence[tuple]) -> list:
        """Batched reads: submit every ``(fd, offset, length)`` job in
        ONE native call (uda_pool_submit_batch — one lock round/ring
        doorbell for the whole burst), then wait for all completions.
        Returns results in job order; a failed read is its job's
        StorageError, never its batch-mates' (per-tag isolation, the
        same contract as poll())."""
        waiters = []
        with self._lock:
            tags = self.pool.submit_batch(jobs)
            for tag in tags:
                w = [threading.Event(), None]
                self._waiters[tag] = w
                waiters.append((tag, w))
        deadline = time.monotonic() + 60.0
        out = []
        for tag, w in waiters:
            if not w[0].wait(timeout=max(0.0,
                                         deadline - time.monotonic())):
                with self._lock:
                    self._waiters.pop(tag, None)
                out.append(StorageError("native batch read timed out"))
                continue
            result = w[1]
            out.append(result if isinstance(result, Exception)
                       else result.tobytes())
        return out

    def close(self) -> None:
        self._stop = True
        self._router.join(timeout=2.0)
        if self._router.is_alive():
            # the router may still be inside pool.poll; destroying the
            # native pool under it would be a use-after-free of the
            # whole process — leaking the pool is the safe failure mode
            log.warn("native read router did not exit in 2s; "
                     "leaking the native pool instead of freeing it")
            return
        self.pool.close()


class DataEngine:
    """Threaded chunk server over local map-output files."""

    def __init__(self, resolver: IndexResolver, config: Optional[Config] = None,
                 num_disks: int = 1):
        cfg = config or Config()
        threads = max(1, cfg.get("mapred.uda.provider.blocked.threads.per.disk")) \
            * max(1, num_disks)
        self.chunk_size_default = cfg.get("mapred.rdma.buf.size") * 1024
        self._crc = bool(cfg.get("uda.tpu.fetch.crc"))
        # read-pool admission (the reference's 1000-chunk pool bound,
        # IndexInfo.cc:276-292, minus the blocking: submit() must stay
        # non-blocking — see the module docstring — so over-budget
        # requests are REJECTED with StorageError and the reduce side's
        # retry/backoff machinery absorbs the push-back). The budget
        # covers bytes queued or being read; 0 = a 256 MB floor scaled
        # by the reader thread count.
        budget_mb = int(cfg.get("uda.tpu.supplier.read.budget.mb"))
        if budget_mb <= 0:
            budget_mb = max(256, threads * 32)
        self.read_budget_bytes = budget_mb * (1 << 20)
        # the synchronous path's wait bound (fetch()): derived from the
        # reduce side's retry knobs so the two paths give up on a
        # wedged completion on the same schedule; both unset -> 60 s
        # (no caller means "forever" by leaving a knob at 0)
        attempt_ms = int(cfg.get("mapred.rdma.fetch.attempt.timeout.ms"))
        deadline_ms = int(cfg.get("mapred.rdma.fetch.deadline.ms"))
        self.sync_fetch_timeout_s = (
            (attempt_ms or deadline_ms) / 1e3
            if (attempt_ms or deadline_ms) else 60.0)
        self._admitted_bytes = 0
        self._admit_lock = threading.Lock()
        # multi-tenant read-budget partitions (uda_tpu/tenant/): when a
        # TenantRegistry is attached, tenant-stamped requests are
        # additionally admitted against that tenant's weighted SHARE of
        # the budget — one abusive job exhausts its slice and only its
        # own clients see the push-back (the isolation contract).
        self._tenant_registry = None
        self._tenant_admitted: Dict[str, int] = {}
        spec = cfg.get("uda.tpu.failpoints")
        if spec:
            failpoints.arm_spec(spec)
        self.resolver = resolver
        # the elastic disaggregated store (mofserver/store.py): when
        # attached, reads of store-MANAGED partitions (blob primaries,
        # twin-holding locals) route through its failover router;
        # unmanaged partitions keep the classic fd path untouched —
        # zero-copy FdSlice serve included
        self.store = None
        self._pool = ThreadPoolExecutor(max_workers=threads,
                                        thread_name_prefix="uda-data-engine")
        self._fds = _FdCache()
        self._stopped = False
        # native read path (the AIOHandler-equivalent worker pool,
        # uda_tpu/native/reader.cc), flag-gated with graceful fallback.
        # The flag also gates the process-wide native IFile codec — but
        # only when EXPLICITLY set, so a default-config engine never
        # silently reconfigures other jobs in the process.
        if cfg.is_set("uda.tpu.use.native"):
            from uda_tpu.utils.ifile import set_native_enabled
            set_native_enabled(bool(cfg.get("uda.tpu.use.native")))
        self._native = None
        if cfg.get("uda.tpu.use.native"):
            try:
                from uda_tpu import native
                if native.available() or native.build():
                    self._native = _NativeReads(native.ReadPool(threads))
            except Exception as e:  # pragma: no cover - best effort
                _warn_native_unavailable(e)
        self._resolve_batch_plane(cfg)

    def _resolve_batch_plane(self, cfg: Config) -> None:
        """Resolve the batched host-I/O plane's parameters. Precedence
        per knob: explicit config > tuning-cache winner > built-in
        default (utils/tuncache.py — env/config winners always beat
        the cache, and a cold/corrupt cache is exactly the defaults).
        The backend ladder walks io_uring -> preadv -> pread downward
        from whatever the winner/knob requests, constrained by what
        this process actually has; the selected rung is recorded as
        the ``io.backend`` metric label and the ``io_backend``
        attribute every stats provider can read."""
        winner: dict = {}
        explicit = cfg.is_set("uda.tpu.tune.cache.path")
        tc_path = (str(cfg.get("uda.tpu.tune.cache.path")) if explicit
                   else "")
        if not tc_path:
            from uda_tpu.utils.tuncache import cache_path_from_env
            tc_path = cache_path_from_env()
        if tc_path:
            from uda_tpu.utils.tuncache import (TuneCache,
                                                set_default_cache,
                                                tune_cache)
            if explicit:
                # one explicitly-configured engine makes the whole
                # process self-service: route_engine (no Config in
                # scope) consults the same table; the env var wins
                cache = set_default_cache(tc_path)
                if cache.path != tc_path:
                    cache = TuneCache(tc_path)
            else:
                cache = tune_cache
            rec = cache.lookup("io.read", sys.platform)
            if rec is not None and isinstance(rec.get("winner"), dict):
                winner = rec["winner"]
        mode = str(cfg.get("uda.tpu.read.batch")).strip().lower()
        if mode not in ("on", "off", "auto"):
            raise ConfigError(f"uda.tpu.read.batch={mode!r} is not "
                              f"on/off/auto")
        if mode == "auto" and winner.get("batch") in ("on", "off"):
            mode = winner["batch"]
        self.batch_enabled = mode != "off"
        gap_kb = int(cfg.get("uda.tpu.read.coalesce.gap.kb"))
        if not cfg.is_set("uda.tpu.read.coalesce.gap.kb") \
                and isinstance(winner.get("gap_kb"), int) \
                and winner["gap_kb"] >= 0:
            gap_kb = winner["gap_kb"]
        self.coalesce_gap_bytes = max(0, gap_kb) << 10
        bmax = int(cfg.get("uda.tpu.read.batch.max"))
        if not cfg.is_set("uda.tpu.read.batch.max") \
                and isinstance(winner.get("batch_max"), int) \
                and winner["batch_max"] > 0:
            bmax = winner["batch_max"]
        self.batch_max = max(1, bmax)
        # one coalesced run's read span stays bounded so gap scratch +
        # per-request buffers cannot balloon past the admission budget
        self.max_run_bytes = self.batch_max * (64 << 10)
        want_backend = str(cfg.get("uda.tpu.read.backend")).strip().lower()
        if want_backend not in BATCH_BACKENDS + ("auto",):
            # typo'd deploy values fail loudly (the UDA_TPU_SORT_PATH
            # posture), never silently serve the slow rung
            raise ConfigError(f"uda.tpu.read.backend={want_backend!r} "
                              f"is not one of {BATCH_BACKENDS + ('auto',)}")
        if want_backend == "auto" and winner.get("backend") \
                in BATCH_BACKENDS:
            want_backend = winner["backend"]
        self.io_backend = self._walk_backend_ladder(want_backend)
        metrics.add("io.backend", backend=self.io_backend)

    def _walk_backend_ladder(self, want: str) -> str:
        """The io_uring -> preadv -> pread fallback ladder, entered at
        ``want`` ("auto" = the top): each rung is taken only when this
        process can actually drive it — io_uring needs the native pool
        built WITH the ring backend and a kernel that accepted
        io_uring_setup (a 4.4-class host lands on preadv; the ABI is
        the drop-in for real hosts), preadv needs os.preadv."""
        start = 0 if want == "auto" else BATCH_BACKENDS.index(want)
        for rung in BATCH_BACKENDS[start:]:
            if rung == "io_uring":
                native = self._native
                if native is not None and \
                        getattr(native.pool, "backend", lambda: "pool")() \
                        == "io_uring":
                    return rung
            elif rung == "preadv":
                if hasattr(os, "preadv"):
                    return rung
            else:
                return rung
        return "pread"

    def submit(self, req: ShuffleRequest) -> Future:
        """Async fetch; the Future resolves to a FetchResult. Never
        blocks (see module docstring on backpressure); safe to call from
        completion callbacks. Blocking IN a completion callback can
        still deadlock the pool — chained fetch re-issue must stay
        non-blocking (regression-tested under a delay failpoint:
        tests/test_mofserver.py::test_chained_fetches_under_delay_
        failpoint_no_deadlock)."""
        if self._stopped:
            raise StorageError("DataEngine is stopped")
        want = req.chunk_size or self.chunk_size_default
        self._admit_bytes(want, req.tenant)
        # the +1 rides the returned Future: _serve's finally owns the
        # -1 on every outcome; the except below covers the one path
        # where the pool never ran it
        metrics.gauge_add("supplier.reads.on_air", 1)  # udalint: disable=UDA101
        try:
            # span adoption across the pool handoff: the submitting
            # thread's current span (a net.serve span on the wire path,
            # a fetch.segment span in-process) becomes the worker-side
            # engine.pread span's parent — the contextvar does not
            # cross threads, so the parent rides the work item
            return self._pool.submit(self._serve, req, want,
                                     metrics.current_span())
        except BaseException:  # pool shutdown race: undo the accounting
            self._unadmit(want, req.tenant)
            metrics.gauge_add("supplier.reads.on_air", -1)
            raise

    def attach_store(self, store) -> None:
        """Attach a :class:`~uda_tpu.mofserver.store.StoreManager`:
        the engine consults ``store.manages(path)`` per resolved record
        and routes managed reads through the store's failover router
        (``read``/``read_ranges``). Byte semantics are identical —
        short-read checks, CRC stamping and the ``data_engine.pread``
        failpoint all run on the routed bytes exactly as on the fd
        path."""
        self.store = store

    def _store_managed(self, rec) -> bool:
        store = self.store
        return store is not None and store.manages(rec.path)

    def set_tenant_registry(self, registry) -> None:
        """Attach the multi-tenant registry: tenant-stamped requests
        are admitted against per-tenant budget shares
        (``registry.share_bytes``), and a retiring job's obligation
        books are drained — any admission bytes it never released are
        reported with the tenant as the leak's attribution."""
        self._tenant_registry = registry
        if registry is not None:
            registry.on_retire(lambda tenant, job:
                               self.drain_tenant(tenant))

    def drain_tenant(self, tenant: str) -> None:
        """ResourceLedger drain of one tenant's admission books (retire
        hook). Only when the tenant is quiescent — bytes still in
        flight are live obligations, not leaks; the engine-stop drain
        owns the final sweep."""
        with self._admit_lock:
            quiescent = self._tenant_admitted.get(tenant, 0) <= 0
        if quiescent:
            resledger.drain(f"tenant.retire[{tenant}]",
                            pairs=("tenant.admit",), owner=id(self))

    def _admit_bytes(self, want: int, tenant: str = "") -> None:
        """THE read-budget admission gate (the occupy_chunk pool bound,
        IndexInfo.cc:276-292, minus the blocking): every serve path —
        submit, submit_serve, try_plan, submit_batch — charges through
        here, and every non-serving outcome must pair the charge with
        :meth:`_unadmit` (budget-critical logic lives exactly once).
        Raises StorageError on rejection. An oversized single request
        is admitted when the pool is otherwise idle: progress beats the
        bound (a request larger than the whole budget could never be
        served at all, which would turn push-back into a permanent
        dead end) — and the idle escape is PER TENANT on the tenant
        gate, so one tenant's giant request rides its own idle slice,
        never a neighbor's headroom."""
        reg = self._tenant_registry
        with self._admit_lock:
            if self._admitted_bytes > 0 and \
                    self._admitted_bytes + want > self.read_budget_bytes:
                metrics.add("supplier.admission.rejections")
                raise StorageError(
                    f"supplier read pool exhausted: {self._admitted_bytes}"
                    f" B in flight + {want} B > budget "
                    f"{self.read_budget_bytes} B (retry with backoff, or "
                    f"raise uda.tpu.supplier.read.budget.mb)")
            if tenant and reg is not None:
                mine = self._tenant_admitted.get(tenant, 0)
                share = reg.share_bytes(tenant, self.read_budget_bytes)
                if mine > 0 and mine + want > share:
                    metrics.add("supplier.admission.rejections")
                    metrics.add("tenant.admission.rejections",
                                tenant=tenant)
                    raise StorageError(
                        f"tenant {tenant!r} read share exhausted: "
                        f"{mine} B in flight + {want} B > share "
                        f"{share} B of the supplier budget (this "
                        f"tenant's clients pace; others are unaffected)")
            self._admitted_bytes += want
            if tenant:
                self._tenant_admitted[tenant] = \
                    self._tenant_admitted.get(tenant, 0) + want
        metrics.gauge_add("supplier.read.bytes.on_air", want)
        if tenant:
            metrics.gauge_add("tenant.read.bytes.on_air", want)
            metrics.gauge_add("tenant.read.bytes.on_air", want,
                              tenant=tenant)
            resledger.acquire("tenant.admit", key=tenant, amount=want,
                              owner=id(self), detail=f"tenant={tenant}")

    def _unadmit(self, want: int, tenant: str = "") -> None:
        with self._admit_lock:
            self._admitted_bytes -= want
            if tenant:
                left = self._tenant_admitted.get(tenant, 0) - want
                if left > 0:
                    self._tenant_admitted[tenant] = left
                else:
                    self._tenant_admitted.pop(tenant, None)
        metrics.gauge_add("supplier.read.bytes.on_air", -want)
        if tenant:
            metrics.gauge_add("tenant.read.bytes.on_air", -want)
            metrics.gauge_add("tenant.read.bytes.on_air", -want,
                              tenant=tenant)
            resledger.settle("tenant.admit", key=tenant, amount=want,
                             owner=id(self))

    def submit_serve(self, req: ShuffleRequest) -> Future:
        """Like :meth:`submit`, but the Future may resolve to an
        :class:`FdSlice` (the zero-copy plan: chunk described as
        fd+offset+length with the fd pinned in the cache) instead of a
        FetchResult. The byte path is taken — transparently, same
        Future type — whenever the chunk cannot be served straight off
        the fd: CRC stamping is on (the checksum needs the bytes), or
        the ``data_engine.pread`` failpoint is armed (injected
        truncation/corruption must keep mangling real bytes, or chaos
        would silently stop testing anything on the zero-copy plane).
        Identical admission, backpressure and error semantics to
        submit(); callers that receive an FdSlice own its release()."""
        if self._stopped:
            raise StorageError("DataEngine is stopped")
        want = req.chunk_size or self.chunk_size_default
        self._admit_bytes(want, req.tenant)
        # same handoff as submit(): _serve_plan's finally owns the -1
        metrics.gauge_add("supplier.reads.on_air", 1)  # udalint: disable=UDA101
        try:
            return self._pool.submit(self._serve_plan, req, want,
                                     metrics.current_span())
        except BaseException:  # pool shutdown race: undo the accounting
            self._unadmit(want, req.tenant)
            metrics.gauge_add("supplier.reads.on_air", -1)
            raise

    def _slice_eligible(self) -> bool:
        return not self._crc \
            and not failpoints.is_armed("data_engine.pread")

    def slice_eligible(self) -> bool:
        """Whether zero-copy FdSlice planning is currently possible
        (CRC off, pread failpoint disarmed). The event-loop server
        consults this to route: slice-eligible requests keep the
        zero-copy plane, everything else rides the batched byte path
        when batching is on."""
        return self._slice_eligible()

    # -- the batched host-I/O plane ------------------------------------------

    def submit_batch(self, reqs: Sequence[ShuffleRequest],
                     parent_spans: Optional[Sequence] = None
                     ) -> List[Future]:
        """Batch submission front (the RDMAbox batched-submission
        lesson; PARITY C15): the whole request burst rides ONE pool
        handoff, the worker groups per fd, coalesces adjacent/
        near-adjacent ranges (``uda.tpu.read.coalesce.gap.kb``) and
        issues vectored reads — a burst against one hot MOF is
        O(files) syscalls, not O(chunks). Returns one Future per
        request, resolving to FetchResults exactly like submit()'s.

        Semantics vs submit(): admission is PER REQUEST (an over-
        budget request fails only its own future with StorageError —
        its batch-mates proceed), and this method never raises — a
        stopped engine or pool-shutdown race fails the futures, so a
        caller iterating a burst cannot half-attach callbacks. Error
        isolation holds all the way down: one failing range in a
        coalesced batch (bad offset, short read, injected
        data_engine.preadv fault) fails only its request."""
        futs: List[Future] = []
        entries: List[_BatchEntry] = []
        parents = parent_spans or ()
        stopped = self._stopped
        for i, req in enumerate(reqs):
            fut = Future()
            futs.append(fut)
            if stopped:
                fut.set_exception(StorageError("DataEngine is stopped"))
                continue
            want = req.chunk_size or self.chunk_size_default
            try:
                # obligation hand-off, the submit()/submit_serve()
                # shape: the charge rides the _BatchEntry into
                # _serve_batch, whose finally settles every entry on
                # every outcome (the except below covers the one path
                # where the pool never ran it)
                self._admit_bytes(want, req.tenant)  # udalint: disable=UDA101
            except StorageError as e:
                fut.set_exception(e)
                continue
            # both +1s ride the batch entry: _serve_batch's finally
            # owns every -1 (or the except below when the pool never
            # ran it)
            metrics.gauge_add("supplier.reads.on_air", 1)  # udalint: disable=UDA101
            metrics.gauge_add("io.batch.inflight", 1)  # udalint: disable=UDA101
            entries.append(_BatchEntry(
                req, want, fut,
                parents[i] if i < len(parents) else None))
        if not entries:
            return futs
        metrics.add("io.batch.submits")
        # per-tenant labels advance the total AND the tenant series;
        # untenanted entries keep the plain total-only add
        plain = sum(1 for e in entries if not e.req.tenant)
        if plain:
            metrics.add("io.batch.requests", plain)
        by_tenant: Dict[str, int] = {}
        for e in entries:
            if e.req.tenant:
                by_tenant[e.req.tenant] = by_tenant.get(e.req.tenant,
                                                        0) + 1
        for tenant, n in by_tenant.items():
            metrics.add("io.batch.requests", n, tenant=tenant)
        try:
            self._pool.submit(self._serve_batch, entries)
        except BaseException as exc:  # pool shutdown race: undo + fail
            # every future (the error is FORWARDED there, chained —
            # never leave a caller holding futures nobody resolves)
            for e in entries:
                self._settle_batch_entry(e, 0.0, observe=False)
                err = StorageError("DataEngine is stopped")
                err.__cause__ = exc
                e.fut.set_exception(err)
        return futs

    def _settle_batch_entry(self, e: _BatchEntry, t0: float,
                            observe: bool = True) -> None:
        """The one settlement point for a batch entry's accounting
        (admission bytes + both paired gauges), run exactly once per
        entry on every outcome."""
        self._unadmit(e.want_admit, e.req.tenant)
        metrics.gauge_add("supplier.reads.on_air", -1)
        metrics.gauge_add("io.batch.inflight", -1)
        if observe:
            if e.req.tenant:
                metrics.observe("supplier.read.latency_ms",
                                (time.perf_counter() - t0) * 1e3,
                                tenant=e.req.tenant)
            else:
                metrics.observe("supplier.read.latency_ms",
                                (time.perf_counter() - t0) * 1e3)

    def _serve_batch(self, entries: List[_BatchEntry]) -> None:
        """Worker-side body of submit_batch, on ONE pool thread for
        the whole batch: resolve each request (the resolver may be an
        embedder upcall — pool thread, never a loop), read per the
        backend rung, then finish every entry (CRC, failpoints,
        FetchResult) — completions fire inline on this thread, one
        dispatch per batch."""
        t0 = time.perf_counter()
        try:
            with metrics.span("engine.read_batch", n=len(entries),
                              backend=self.io_backend):
                self._batch_resolve(entries)
                live = [e for e in entries if e.err is None]
                if live:
                    if self.io_backend == "io_uring" \
                            and self._native is not None:
                        self._read_batch_native(live)
                    else:
                        self._read_batch_runs(live)
                self._batch_finish(entries)
        except BaseException as exc:  # defensive: a worker bug must
            # still resolve every future (callers block on them)
            for e in entries:
                if not e.fut.done():
                    e.fut.set_exception(
                        exc if isinstance(exc, StorageError)
                        else StorageError(f"batch serve failed: {exc}"))
        finally:
            for e in entries:
                self._settle_batch_entry(e, t0)
                if not e.fut.done():  # belt and braces: no caller may
                    # wait forever on a future the stages skipped
                    e.fut.set_exception(
                        StorageError("batch entry never served"))

    def _batch_resolve(self, entries: List[_BatchEntry]) -> None:
        for e in entries:
            req = e.req
            try:
                rec = self.resolver.resolve(req.job_id, req.map_id,
                                            req.reduce_id)
                served = rec.part_length
                if req.offset < 0 or req.offset >= max(served, 1):
                    raise StorageError(
                        f"offset {req.offset} outside partition "
                        f"(on-disk {served}) for {req.map_id}/"
                        f"{req.reduce_id}")
                e.rec = rec
                e.want = min(req.chunk_size or self.chunk_size_default,
                             served - req.offset)
                e.file_off = rec.start_offset + req.offset
            except Exception as exc:  # noqa: BLE001 - per-request
                # isolation: a missing MOF fails one future, not the
                # batch (the error lands on the future below)
                e.err = exc

    def _read_batch_runs(self, live: List[_BatchEntry]) -> None:
        """The preadv/pread rungs: group per MOF (one fd pin per file
        across the whole batch), coalesce, read."""
        by_path: Dict[str, List[_BatchEntry]] = {}
        for e in live:
            by_path.setdefault(e.rec.path, []).append(e)
        for path, group in by_path.items():
            if self.store is not None and self.store.manages(path):
                self._read_batch_store(path, group)
                continue
            try:
                fd = self._fds.acquire(path)
            except OSError as exc:
                for e in group:
                    e.err = StorageError(f"cannot open {path}: {exc}")
                continue
            try:
                for e in group:
                    e.fd = fd
                if self.io_backend == "preadv":
                    runs = plan_coalesced(
                        [(e, e.file_off, e.want) for e in group],
                        self.coalesce_gap_bytes, self.max_run_bytes)
                    for run in runs:
                        self._read_run_preadv(fd, run)
                else:  # the pread floor: per-request reads, still one
                    # pool handoff for the batch
                    for e in group:
                        try:
                            data = os.pread(fd, e.want, e.file_off)
                            metrics.add("io.batch.reads",
                                        backend="pread")
                            e.buf = bytearray(data)
                            e.got = len(data)
                        except OSError as exc:
                            e.err = StorageError(
                                f"read failed at {path}:{e.file_off}: "
                                f"{exc}")
            finally:
                self._fds.release(path)

    def _read_batch_store(self, path: str,
                          group: List[_BatchEntry]) -> None:
        """One store-managed path group of a batch: the router's
        vectored read (the blob tier rides the same ``plan_coalesced``
        planner), per-request error isolation preserved — a failed
        range fails ONE future, its batch-mates complete untouched."""
        results = self.store.read_ranges(
            path, [(e.file_off, e.want) for e in group],
            keys=[f"{e.req.map_id}/{e.req.reduce_id}" for e in group])
        for e, res in zip(group, results):
            if isinstance(res, Exception):
                e.err = res
            else:
                e.buf = bytearray(res)
                e.got = len(res)

    def _read_run_preadv(self, fd: int, run: List[tuple]) -> None:
        """One coalesced run -> one vectored read: per-request
        bytearrays (these BECOME FetchResult.data — no scatter copy)
        interleaved with scratch views covering the gaps. A short read
        (truncated MOF) fails only the requests whose ranges the
        kernel didn't fill."""
        entries = [item[0] for item in run]
        run_start = run[0][1]
        run_end = run[-1][1] + run[-1][2]
        gap_total = (run_end - run_start) - sum(e.want for e in entries)
        metrics.add("io.coalesce.runs")
        if gap_total > 0:
            metrics.add("io.coalesce.gap.bytes", gap_total)
        scratch = memoryview(bytearray(gap_total)) if gap_total else None
        iov: list = []
        spans: List[tuple] = []  # (entry, start-in-run, end-in-run)
        pos = run_start
        scratch_used = 0
        for e in entries:
            if e.file_off > pos:
                gap = e.file_off - pos
                iov.append(scratch[scratch_used:scratch_used + gap])
                scratch_used += gap
                pos = e.file_off
            e.buf = bytearray(e.want)
            iov.append(e.buf)
            spans.append((e, pos - run_start, pos - run_start + e.want))
            pos += e.want
        try:
            got, syscalls = _preadv_full(fd, iov, run_start)
        except OSError as exc:
            for e in entries:
                e.err = StorageError(
                    f"vectored read failed at {e.rec.path}:"
                    f"{run_start}: {exc}")
            return
        metrics.add("io.batch.reads", syscalls, backend="preadv")
        for e, lo, hi in spans:
            e.got = max(0, min(got - lo, e.want)) if got > lo else 0

    def _read_batch_native(self, live: List[_BatchEntry]) -> None:
        """The io_uring rung: per-request ranges go straight into the
        native ring (no gap reads — the SQE array IS the batch), fds
        pinned per MOF for the duration."""
        by_path: Dict[str, List[_BatchEntry]] = {}
        for e in live:
            by_path.setdefault(e.rec.path, []).append(e)
        pinned: List[str] = []
        order: List[_BatchEntry] = []
        jobs: List[tuple] = []
        try:
            for path, group in by_path.items():
                if self.store is not None and self.store.manages(path):
                    # store-managed groups keep the router semantics
                    # (failpoints, health, failover) on every backend
                    # rung — the ring never bypasses the store
                    self._read_batch_store(path, group)
                    continue
                try:
                    # released by the pinned-list sweep in THIS
                    # function's finally (list-mediated hand-off the
                    # static rule cannot follow)
                    fd = self._fds.acquire(path)  # udalint: disable=UDA101
                except OSError as exc:
                    for e in group:
                        e.err = StorageError(
                            f"cannot open {path}: {exc}")
                    continue
                pinned.append(path)
                for e in group:
                    e.fd = fd
                    order.append(e)
                    jobs.append((fd, e.file_off, e.want))
            if not jobs:
                return
            results = self._native.read_batch(jobs)
            metrics.add("io.batch.reads", len(jobs), backend="io_uring")
            for e, res in zip(order, results):
                if isinstance(res, Exception):
                    e.err = res
                else:
                    e.buf = res
                    e.got = len(res)
        finally:
            for path in pinned:
                self._fds.release(path)

    def _batch_finish(self, entries: List[_BatchEntry]) -> None:
        """Per-entry completion: short-read check, CRC from the bytes
        as read (before any failpoint can mangle them — wire-damage
        realism, same as _serve_inner), the two injection sites, the
        FetchResult. Each entry's work runs under its own engine.pread
        span adopting ITS request's serve span, so batch-served chunks
        land in the same trace shape as single-served ones."""
        for e in entries:
            req = e.req
            if e.err is None and e.got != e.want:
                e.err = StorageError(
                    f"short read {e.got}/{e.want} at {e.rec.path}:"
                    f"{e.file_off}")
            if e.err is not None:
                e.fut.set_exception(e.err)
                continue
            try:
                with metrics.use_span(e.parent_span), \
                        metrics.span("engine.pread", map=req.map_id,
                                     reduce=req.reduce_id,
                                     offset=req.offset, batched=True):
                    data = e.buf
                    crc = (zlib.crc32(data) & 0xFFFFFFFF
                           if self._crc else None)
                    data = failpoint("data_engine.preadv", data=data,
                                     key=f"{e.fd}@{e.file_off}")
                    data = failpoint("data_engine.pread", data=data,
                                     key=f"{req.map_id}/{req.reduce_id}")
                    served = e.rec.part_length
                    if req.tenant:
                        metrics.add("supplier.bytes", len(data),
                                    tenant=req.tenant)
                    else:
                        metrics.add("supplier.bytes", len(data))
                    e.fut.set_result(FetchResult(
                        data, e.rec.raw_length, e.rec.part_length,
                        req.offset, e.rec.path,
                        last=req.offset + len(data) >= served,
                        crc=crc))
            except Exception as exc:  # noqa: BLE001 - injected faults
                # (and any finish bug) stay per-request: the error is
                # THIS future's result, batch-mates complete untouched
                e.err = exc
                e.fut.set_exception(exc)

    def try_plan(self, req: ShuffleRequest) -> Optional[FdSlice]:
        """The synchronous zero-copy fast path: an FdSlice built INLINE
        from the index cache — the (fd, offset, len) triple for a cache
        hit, no pool handoff, no IO, no upcall. Returns None whenever
        planning would need blocking work (cold index entry, CRC
        stamping on, armed pread failpoint, stopped engine) and the
        caller falls back to :meth:`submit_serve`. Admission semantics
        are submit()'s exactly: an over-budget request raises
        StorageError (typed ERR to the wire), and the slice holds its
        admission charge until release(). This is what lets the
        event-loop server serve a hot chunk entirely on the loop
        thread — read, plan, sendfile — the RDMA-WRITE-from-registered-
        memory critical path with zero thread handoffs."""
        if self._stopped or not self._slice_eligible():
            return None
        resolve_cached = getattr(self.resolver, "resolve_cached", None)
        if resolve_cached is None:
            return None
        rec = resolve_cached(req.job_id, req.map_id, req.reduce_id)
        if rec is None or self._store_managed(rec):
            # store-managed partitions (blob tier / failover twins)
            # need the router's health/failover logic — no zero-copy
            # slice can express a mid-read tier switch
            return None
        want_admit = req.chunk_size or self.chunk_size_default
        self._admit_bytes(want_admit, req.tenant)
        try:
            return self._build_slice(rec, req, want_admit)
        except BaseException:
            # bad offset / fd-open failure (MOF deleted under a cached
            # index entry): the charge MUST unwind or the budget leaks
            # permanently and eventually wedges the supplier
            self._unadmit(want_admit, req.tenant)
            raise

    def _serve_plan(self, req: ShuffleRequest, admitted: int = 0,
                    parent_span=None):
        """Worker-side body of submit_serve: resolve on the pool thread
        (the resolver may be an embedder upcall — never run it on the
        event loop), then either pin an FdSlice or fall through to the
        byte serve. An FdSlice KEEPS its admission charge until
        release(); every other outcome settles here. ``parent_span``
        is the submitting thread's span (see submit): the worker's
        engine.pread span adopts it."""
        t0 = time.perf_counter()
        sliced = False
        try:
            with metrics.use_span(parent_span), \
                    metrics.span("engine.pread", map=req.map_id,
                                 reduce=req.reduce_id, offset=req.offset):
                if self._slice_eligible():
                    plan = self._plan_inner(req, admitted)
                    if plan is not None:
                        sliced = True
                        return plan
                return self._serve_inner(req)
        finally:
            if admitted and not sliced:
                self._unadmit(admitted, req.tenant)
            metrics.gauge_add("supplier.reads.on_air", -1)
            if req.tenant:
                metrics.observe("supplier.read.latency_ms",
                                (time.perf_counter() - t0) * 1e3,
                                tenant=req.tenant)
            else:
                metrics.observe("supplier.read.latency_ms",
                                (time.perf_counter() - t0) * 1e3)

    def _plan_inner(self, req: ShuffleRequest,
                    admitted: int) -> Optional[FdSlice]:
        rec = self.resolver.resolve(req.job_id, req.map_id, req.reduce_id)
        if self._store_managed(rec):
            return None  # the caller falls through to the byte serve
        return self._build_slice(rec, req, admitted)

    def _build_slice(self, rec, req: ShuffleRequest,
                     admitted: int) -> FdSlice:
        """The one slice constructor both plan paths (pool + inline)
        share: offset validation, chunk sizing, fd pin."""
        served = rec.part_length  # the on-disk domain
        if req.offset < 0 or req.offset >= max(served, 1):
            raise StorageError(
                f"offset {req.offset} outside partition (on-disk "
                f"{served}) for {req.map_id}/{req.reduce_id}")
        want = min(req.chunk_size or self.chunk_size_default,
                   served - req.offset)
        fd = self._fds.acquire(rec.path)
        try:
            if req.tenant:
                metrics.add("supplier.bytes", want, tenant=req.tenant)
            else:
                metrics.add("supplier.bytes", want)
            return FdSlice(fd=fd, file_offset=rec.start_offset + req.offset,
                           length=want, raw_length=rec.raw_length,
                           part_length=rec.part_length, offset=req.offset,
                           path=rec.path, last=req.offset + want >= served,
                           _engine=self, _admitted=admitted,
                           _tenant=req.tenant)
        except BaseException:
            # the slice never existed, so its release() never runs: the
            # fd pin must unwind here or the cache entry's refcount rots
            # and the MOF's fd outlives every request (refcount-rot is
            # the RDMAbox-class failure the ledger exists to catch)
            self._fds.release(rec.path)
            raise

    def fetch(self, req: ShuffleRequest) -> FetchResult:
        """Synchronous fetch with a deadline. A wedged read (native pool
        stall, failpoint delay storm, dead disk) must not hang the
        caller forever: the wait is bounded by the fetch retry knobs —
        the per-attempt timeout when set, else the per-segment deadline,
        else a 60 s default — and a timeout surfaces as StorageError
        (the same class a dead disk would raise), so sync callers share
        the async path's failure semantics."""
        fut = self.submit(req)
        try:
            return fut.result(timeout=self.sync_fetch_timeout_s)
        except FutureTimeout as e:
            if fut.cancel():
                # cancelled while still QUEUED: _serve never runs, so
                # its finally-block accounting never fires — undo the
                # admission charge here or timeouts would pin the read
                # budget until submit() rejects an idle engine
                self._unadmit(req.chunk_size or self.chunk_size_default,
                              req.tenant)
                metrics.gauge_add("supplier.reads.on_air", -1)
            # else: the read is running; _serve's finally settles it
            raise StorageError(
                f"synchronous fetch of {req.map_id}/{req.reduce_id} at "
                f"offset {req.offset} did not complete within "
                f"{self.sync_fetch_timeout_s:g} s (bounded by the "
                f"mapred.rdma.fetch.* knobs)") from e

    def _serve(self, req: ShuffleRequest, admitted: int = 0,
               parent_span=None) -> FetchResult:
        t0 = time.perf_counter()
        try:
            with metrics.use_span(parent_span), \
                    metrics.span("engine.pread", map=req.map_id,
                                 reduce=req.reduce_id, offset=req.offset):
                return self._serve_inner(req)
        finally:
            if admitted:
                self._unadmit(admitted, req.tenant)
            metrics.gauge_add("supplier.reads.on_air", -1)
            if req.tenant:
                metrics.observe("supplier.read.latency_ms",
                                (time.perf_counter() - t0) * 1e3,
                                tenant=req.tenant)
            else:
                metrics.observe("supplier.read.latency_ms",
                                (time.perf_counter() - t0) * 1e3)

    def _serve_inner(self, req: ShuffleRequest) -> FetchResult:
        with metrics.timer("supplier_read"):
            rec = self.resolver.resolve(req.job_id, req.map_id, req.reduce_id)
            served = rec.part_length  # the on-disk domain
            if req.offset < 0 or req.offset >= max(served, 1):
                raise StorageError(
                    f"offset {req.offset} outside partition (on-disk "
                    f"{served}) for {req.map_id}/{req.reduce_id}")
            want = min(req.chunk_size or self.chunk_size_default,
                       served - req.offset)
            if self._store_managed(rec):
                # the disaggregated-store router: tier health, the
                # store.get failpoint site and twin failover live
                # there; the bytes come back through the same CRC/
                # failpoint/accounting tail as the fd path below
                data = self.store.read(
                    rec.path, rec.start_offset + req.offset, want,
                    key=f"{req.map_id}/{req.reduce_id}")
            else:
                fd = self._fds.acquire(rec.path)
                try:
                    if self._native is not None:
                        data = self._native.read(
                            fd, rec.start_offset + req.offset, want)
                    else:
                        data = os.pread(fd, want,
                                        rec.start_offset + req.offset)
                finally:
                    self._fds.release(rec.path)
            if len(data) != want:
                raise StorageError(
                    f"short read {len(data)}/{want} at {rec.path}:"
                    f"{rec.start_offset + req.offset}")
            # CRC stamped from the bytes as read, BEFORE the failpoint
            # can mangle them — injected truncation/corruption then looks
            # exactly like wire damage to the validating Segment
            crc = zlib.crc32(data) & 0xFFFFFFFF if self._crc else None
            data = failpoint("data_engine.pread", data=data,
                             key=f"{req.map_id}/{req.reduce_id}")
            if req.tenant:
                metrics.add("supplier.bytes", len(data),
                            tenant=req.tenant)
            else:
                metrics.add("supplier.bytes", len(data))
            return FetchResult(data, rec.raw_length, rec.part_length,
                               req.offset, rec.path,
                               last=req.offset + len(data) >= served,
                               crc=crc)

    def stop(self) -> None:
        self._stopped = True
        self._pool.shutdown(wait=True)
        if self._native is not None:
            self._native.close()
        self._fds.close_all()
        # ResourceLedger drain point (UDA_TPU_RESLEDGER=1): with the
        # pool drained and the fd cache closed, every fd pin handed out
        # by THIS engine's cache (owner scope: a concurrently-live
        # peer engine's pins are untouched — the killed-supplier chaos
        # shape) must have been released; an open one is an FdSlice
        # that never ran release() — the refcount-rot leak class
        resledger.drain("data_engine.stop", pairs=("engine.fd",),
                        owner=id(self._fds))
        # the tenant partition books: with the pool drained, every
        # tenant-stamped admission charge must have settled — an open
        # one is attributed (key=tenant) to the job that leaked it
        resledger.drain("data_engine.stop", pairs=("tenant.admit",),
                        owner=id(self))

    def __enter__(self) -> "DataEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
