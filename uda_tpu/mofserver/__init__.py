"""Map-output supplier (the MOFServer/ layer of SURVEY §1): index
resolution, chunk-served data engine."""

from uda_tpu.mofserver.data_engine import (DataEngine, FdSlice, FetchResult,
                                           ShuffleRequest)
from uda_tpu.mofserver.index import (DirIndexResolver, IndexRecord,
                                     IndexResolver, read_index_file,
                                     write_index_file)
from uda_tpu.mofserver.store import (BackendHealth, BlobStore, LocalFdStore,
                                     MOFStore, StoreManager,
                                     spill_watermark_bytes)

__all__ = ["DataEngine", "FdSlice", "FetchResult", "ShuffleRequest",
           "DirIndexResolver", "IndexRecord", "IndexResolver",
           "read_index_file", "write_index_file",
           "BackendHealth", "BlobStore", "LocalFdStore", "MOFStore",
           "StoreManager", "spill_watermark_bytes"]
