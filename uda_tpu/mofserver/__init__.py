"""Map-output supplier (the MOFServer/ layer of SURVEY §1): index
resolution, chunk-served data engine."""

from uda_tpu.mofserver.data_engine import (DataEngine, FdSlice, FetchResult,
                                           ShuffleRequest)
from uda_tpu.mofserver.index import (DirIndexResolver, IndexRecord,
                                     IndexResolver, read_index_file,
                                     write_index_file)

__all__ = ["DataEngine", "FdSlice", "FetchResult", "ShuffleRequest",
           "DirIndexResolver", "IndexRecord", "IndexResolver",
           "read_index_file", "write_index_file"]
