"""Elastic disaggregated MOF storage: backends, spill ladder, failover.

ROADMAP item 2. The reference pins every map output to the supplier
node that produced it (local disk under the per-attempt work dir,
reference plugins mlx-2.x UdaPluginSH.java:107-144), so a job's
footprint and fault domain are welded to the map fleet.
Exoshuffle-CloudSort (arXiv:2301.03734) breaks exactly this coupling to
sort beyond cluster RAM; Exoshuffle (arXiv:2203.05072) argues the
placement should be a *policy* behind a library seam. This module is
that seam:

- :class:`MOFStore` — the backend ABC. :class:`LocalFdStore` is
  today's fd/pread path extracted (byte-identical; the DataEngine's
  zero-copy FdSlice serve stays engaged for local-tier partitions —
  the engine only routes a read through the store when the partition
  is store-managed, see ``DataEngine.attach_store``).
  :class:`BlobStore` is the object-store-style tier: range reads over
  an emulated blob root, vectored through the same
  ``plan_coalesced``/``_preadv_full`` machinery the PR 13 batch plane
  uses, and CRC-verified streamed object writes.

- **Spill ladder** (:meth:`StoreManager.account_write` ->
  :meth:`StoreManager.maybe_spill`): when a supplier's locally
  retained MOF bytes cross the watermark
  (:func:`spill_watermark_bytes` — explicit MB knob, else a fraction
  of the :class:`~uda_tpu.utils.budget.MemoryBudget` host budget),
  whole partitions migrate oldest-first to the blob tier:
  streamed copy, CRC read-back verification, the v2 UDIX index
  (stripe locators preserved) rewritten at the blob root, the local
  index unlinked as the atomic cut-over (the index file IS the
  DirIndexResolver's routing key), ``store.spilled.bytes`` ledgered.
  A shuffle whose bytes exceed the host budget 10x completes with
  RSS bounded by the budget (scripts/bench_elastic.py gates this).

- **Degraded-backend failover** (:meth:`StoreManager.read`): each
  tier has PenaltyBox-style health (:class:`BackendHealth`); a read
  against a failing tier re-routes to the partition's twin copy on
  the surviving tier (blob->local when a spill kept a shadow,
  local->blob for replicated partitions), counted
  ``store.failover``. Failures are typed
  :class:`~uda_tpu.utils.errors.StoreError` with structured
  ``cause``/``backend`` (UDA005: never reason strings) and feed the
  task's RecoveryLedger as the ``store`` rung. When no twin exists
  the typed error propagates into the PR 8 ladder — retry,
  speculate, k-of-n reconstruction — unchanged.

- **Drain** (:meth:`StoreManager.drain`): a departing supplier
  migrates its retained partitions to the blob tier (moved, not
  reconstructed-from-parity) before its server stops warm — the
  storage half of the mid-job membership protocol (the net half is
  the CAP_ELASTIC/CAP_DRAINING HELLO bits, uda_tpu/net/wire.py).

Failpoint sites ``store.get``/``store.put``/``store.migrate`` are
keyed ``<backend>:<key>`` so a chaos spec's ``match:blob`` trigger
kills exactly one tier while the other keeps serving — the
degraded-backend rung in scripts/run_chaos.sh.
"""

from __future__ import annotations

import abc
import os
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from uda_tpu.mofserver.index import (DirIndexResolver, read_index_file,
                                     write_index_file)
from uda_tpu.utils.errors import StorageError, StoreError
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.flightrec import flightrec
from uda_tpu.utils.locks import TrackedLock, race_instrument
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics
from uda_tpu.utils.resledger import resledger

log = get_logger()

__all__ = ["MOFStore", "LocalFdStore", "BlobStore", "BackendHealth",
           "StoreManager", "spill_watermark_bytes"]

_COPY_CHUNK = 1 << 20  # streamed-migration chunk: RSS stays O(1 MiB)


def spill_watermark_bytes(cfg, budget=None) -> int:
    """The supplier's local-retention watermark in bytes: the explicit
    MB knob when set, else ``uda.tpu.store.spill.frac`` of the host
    memory budget (the :class:`~uda_tpu.utils.budget.MemoryBudget`
    derived-cap idiom — the same detection ``stage_inflight_cap``
    rides). 0 = the spill ladder is off."""
    mb = int(cfg.get("uda.tpu.store.spill.watermark.mb"))
    if mb > 0:
        return mb << 20
    frac = float(cfg.get("uda.tpu.store.spill.frac"))
    if frac <= 0:
        return 0
    if budget is None:
        from uda_tpu.utils.budget import MemoryBudget
        budget = MemoryBudget.from_config(cfg)
    return int(budget.host_budget_bytes * frac)


class MOFStore(abc.ABC):
    """One storage tier. ``read`` returns exactly ``length`` bytes or
    raises a typed :class:`StoreError` — short reads never escape as
    silent truncation (the Segment-side CRC would catch them late and
    blame the wire)."""

    name = "store"
    zero_copy = False  # may the DataEngine serve this tier via FdSlice?

    @abc.abstractmethod
    def read(self, path: str, file_off: int, length: int) -> bytes:
        """Range read: ``length`` bytes at ``file_off`` of ``path``."""

    def read_ranges(self, path: str,
                    ranges: Sequence[Tuple[int, int]]) -> List[bytes]:
        """Batch range read; the base implementation loops
        :meth:`read` (backends with a vectored plane override)."""
        return [self.read(path, off, ln) for off, ln in ranges]

    # -- fd obligation pair (resledger "store.fd") --------------------------

    def acquire_fd(self, path: str) -> int:
        """Open a backend object for reading; the handle is an open
        obligation (resledger pair ``store.fd``, owner = this store)
        until :meth:`release_fd`."""
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError as e:
            raise StoreError(f"{self.name}: cannot open {path}: {e}",
                             cause="missing", backend=self.name) from e
        resledger.acquire("store.fd", key=path, owner=id(self))
        return fd

    def release_fd(self, path: str, fd: int) -> None:
        try:
            os.close(fd)
        finally:
            resledger.settle("store.fd", key=path, owner=id(self))

    def _pread_full(self, path: str, file_off: int, length: int) -> bytes:
        fd = self.acquire_fd(path)
        try:
            data = os.pread(fd, length, file_off)
        except OSError as e:
            raise StoreError(
                f"{self.name}: read failed at {path}:{file_off}: {e}",
                cause="get", backend=self.name) from e
        finally:
            self.release_fd(path, fd)
        if len(data) != length:
            raise StoreError(
                f"{self.name}: short read {len(data)}/{length} at "
                f"{path}:{file_off}", cause="short_read",
                backend=self.name)
        return data

    def close(self) -> None:
        """Drain point: every handle this store handed out must have
        been released (an open one is the refcount-rot leak class)."""
        resledger.drain(f"store.close[{self.name}]", pairs=("store.fd",),
                        owner=id(self))


class LocalFdStore(MOFStore):
    """Today's supplier-local fd path, extracted behind the seam.
    Byte-identical to the in-engine pread serve; the DataEngine keeps
    its zero-copy FdSlice fast path for partitions this tier owns
    exclusively (the store only intercepts store-managed paths)."""

    name = "local"
    zero_copy = True

    def read(self, path: str, file_off: int, length: int) -> bytes:
        return self._pread_full(path, file_off, length)


class BlobStore(MOFStore):
    """Object-store-style tier over an emulated blob root: range GETs
    (vectored through the PR 13 coalescer when the host has preadv)
    and CRC-verified streamed object PUTs. The on-disk layout mirrors
    the DirIndexResolver contract (``<root>/<job>/<map>/file.out`` +
    index) so the blob root slots into the resolver's root list and
    migrated partitions resolve with zero resolver changes."""

    name = "blob"
    zero_copy = False

    def __init__(self, root: str, gap_bytes: int = 64 << 10,
                 max_run_bytes: int = 8 << 20):
        self.root = os.path.abspath(root)
        self.gap_bytes = max(0, int(gap_bytes))
        self.max_run_bytes = max(1 << 16, int(max_run_bytes))
        os.makedirs(self.root, exist_ok=True)

    def read(self, path: str, file_off: int, length: int) -> bytes:
        return self._pread_full(path, file_off, length)

    def read_ranges(self, path: str,
                    ranges: Sequence[Tuple[int, int]]) -> List[bytes]:
        """Vectored range GET: coalesce adjacent ranges into runs
        (``plan_coalesced`` — the exact PR 13 batch-plane planner) and
        read each run with one preadv; hosts without preadv take the
        per-range floor."""
        if not ranges:
            return []
        if not hasattr(os, "preadv"):
            return [self.read(path, off, ln) for off, ln in ranges]
        # lazy import: data_engine imports nothing from this module,
        # but keeping the planner import out of module scope means a
        # half-initialized engine module can still import the store
        from uda_tpu.mofserver.data_engine import (_preadv_full,
                                                   plan_coalesced)
        out: List[Optional[bytes]] = [None] * len(ranges)
        fd = self.acquire_fd(path)
        try:
            runs = plan_coalesced(
                [(i, off, ln) for i, (off, ln) in enumerate(ranges)],
                self.gap_bytes, self.max_run_bytes)
            for run in runs:
                run_start = run[0][1]
                run_end = run[-1][1] + run[-1][2]
                bufs = []
                iov: list = []
                pos = run_start
                for i, off, ln in run:
                    if off > pos:
                        iov.append(memoryview(bytearray(off - pos)))
                        pos = off
                    buf = bytearray(ln)
                    bufs.append((i, buf, pos - run_start))
                    iov.append(buf)
                    pos += ln
                try:
                    got, syscalls = _preadv_full(fd, iov, run_start)
                except OSError as e:
                    raise StoreError(
                        f"blob: vectored read failed at {path}:"
                        f"{run_start}: {e}", cause="get",
                        backend=self.name) from e
                metrics.add("store.blob.reads", syscalls)
                for i, buf, lo in bufs:
                    if got < lo + len(buf):
                        raise StoreError(
                            f"blob: short read at {path}:{run_start} "
                            f"(run length {run_end - run_start}, got "
                            f"{got})", cause="short_read",
                            backend=self.name)
                    out[i] = bytes(buf)
        finally:
            self.release_fd(path, fd)
        return [b for b in out if b is not None]

    def put_file(self, src: str, dst: str, key: str = "") -> Tuple[int, int]:
        """Streamed object PUT with CRC read-back verification:
        ``src`` is copied in :data:`_COPY_CHUNK` chunks (migration RSS
        stays O(1 MiB) regardless of partition size), then the stored
        object is re-read and its CRC32 compared — a torn or damaged
        PUT raises a typed :class:`StoreError` and the caller keeps
        the source copy authoritative. Returns (bytes, crc)."""
        failpoint("store.put", key=f"{self.name}:{key or dst}")
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        crc = 0
        nbytes = 0
        with open(src, "rb") as fin, open(dst, "wb") as fout:
            while True:
                chunk = fin.read(_COPY_CHUNK)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
                nbytes += len(chunk)
                fout.write(chunk)
        if self.object_crc(dst) != (crc & 0xFFFFFFFF):
            try:
                os.unlink(dst)  # never leave a corrupt object servable
            except OSError as e:
                metrics.add("errors.swallowed")
                log.warn(f"blob: cannot remove corrupt object {dst}: {e}")
            raise StoreError(
                f"blob: CRC mismatch after put of {src} -> {dst}",
                cause="crc", backend=self.name)
        return nbytes, crc & 0xFFFFFFFF

    def object_crc(self, path: str) -> int:
        """Streamed CRC32 of a stored object (the put verification and
        the checkpoint-resume locator revalidation both use this)."""
        crc = 0
        with open(path, "rb") as f:
            while True:
                chunk = f.read(_COPY_CHUNK)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
        return crc & 0xFFFFFFFF


class BackendHealth:
    """Per-backend fault tracker — the PenaltyBox posture applied to
    storage tiers (merger/merge_manager.py PenaltyBox is the model):
    repeated faults box a tier for ``penalty_s`` and the router serves
    the twin tier proactively; a success decays the record. Boxing is
    never exclusion — a partition whose ONLY copy lives on a boxed
    tier is still read from it (progress beats politeness)."""

    def __init__(self, threshold: int = 2, penalty_s: float = 1.0):
        self.threshold = max(1, threshold)
        self.penalty_s = penalty_s
        self._lock = TrackedLock("store.health")
        self._faults: Dict[str, int] = {}
        self._until: Dict[str, float] = {}

    def punish(self, backend: str) -> bool:
        """Record one fault; True when this fault boxed the tier."""
        with self._lock:
            n = self._faults.get(backend, 0) + 1
            self._faults[backend] = n
            if n < self.threshold:
                return False
            self._until[backend] = time.monotonic() + self.penalty_s
        metrics.add("store.penalties", backend=backend)
        return True

    def forgive(self, backend: str) -> None:
        with self._lock:
            n = self._faults.get(backend)
            if n is None:
                return
            n -= 1
            if n <= 0:
                self._faults.pop(backend, None)
                self._until.pop(backend, None)
                return
            self._faults[backend] = n
            if n < self.threshold:
                self._until.pop(backend, None)

    def boxed(self, backend: str) -> bool:
        with self._lock:
            t = self._until.get(backend)
            if t is None:
                return False
            if time.monotonic() >= t:
                # parole: one more fault re-boxes (PenaltyBox posture)
                del self._until[backend]
                self._faults[backend] = self.threshold - 1
                return False
            return True

    def faults(self, backend: str) -> int:
        with self._lock:
            return self._faults.get(backend, 0)

    def snapshot(self) -> dict:
        with self._lock:
            now = time.monotonic()
            return {"faults": dict(self._faults),
                    "boxed": [b for b, t in self._until.items()
                              if t > now]}


@race_instrument("_migrations")
class StoreManager:
    """Placement policy + spill ladder + failover router over the two
    tiers. Attach to a DataEngine with ``engine.attach_store(mgr)``:
    the engine then routes reads of *store-managed* partitions (blob
    primaries and twin-holding local partitions) through
    :meth:`read`/:meth:`read_ranges`; everything else keeps the
    classic fd path, zero-copy serve included."""

    def __init__(self, resolver, blob_root: str, *,
                 watermark_bytes: int = 0, shadow: bool = False,
                 recovery=None, health: Optional[BackendHealth] = None):
        self.resolver = resolver
        self.blob_root = os.path.abspath(blob_root)
        self.local = LocalFdStore()
        self.blob = BlobStore(self.blob_root)
        self._backends: Dict[str, MOFStore] = {"local": self.local,
                                               "blob": self.blob}
        self.health = health or BackendHealth()
        self.recovery = recovery  # RecoveryLedger: the storage rung
        self.watermark_bytes = max(0, int(watermark_bytes))
        self.shadow = bool(shadow)
        self._lock = TrackedLock("store.manager")
        # mof path -> its copy on the other tier (both directions);
        # the failover router's candidate table
        self._twin: Dict[str, str] = {}
        # (job, map) -> locally retained bytes, insertion-ordered so
        # the spill ladder evicts oldest-first
        self._retained: Dict[Tuple[str, str], int] = {}
        self._retained_total = 0
        self._migrations: List[dict] = []
        # the blob root joins the resolver's search path so migrated
        # partitions resolve with the stock map_dir walk (the local
        # index unlink below is the cut-over that makes it win)
        if isinstance(resolver, DirIndexResolver) \
                and self.blob_root not in resolver.roots:
            resolver.roots.append(self.blob_root)

    @classmethod
    def from_config(cls, resolver, cfg, recovery=None,
                    budget=None) -> Optional["StoreManager"]:
        """The flag-wired constructor: None when no blob root is
        configured (the seed behavior — supplier-local storage
        only)."""
        root = str(cfg.get("uda.tpu.store.blob.root"))
        if not root:
            return None
        return cls(
            resolver, root,
            watermark_bytes=spill_watermark_bytes(cfg, budget),
            shadow=bool(cfg.get("uda.tpu.store.shadow")),
            recovery=recovery,
            health=BackendHealth(
                threshold=int(cfg.get("uda.tpu.store.health.threshold")),
                penalty_s=float(
                    cfg.get("uda.tpu.store.health.penalty.ms")) / 1e3))

    # -- placement ----------------------------------------------------------

    def backend_of(self, path: str) -> str:
        return "blob" if os.path.abspath(path).startswith(
            self.blob_root + os.sep) else "local"

    def manages(self, path: str) -> bool:
        """Should the DataEngine route reads of ``path`` through the
        store? Blob primaries always (range-GET semantics + failover);
        local partitions only once they have a blob twin (replicated —
        the local->blob failover arrangement). Plain never-migrated
        local partitions stay on the classic fd path: byte-identical,
        zero-copy serve preserved."""
        if self.backend_of(path) == "blob":
            return True
        with self._lock:
            return path in self._twin

    def _candidates(self, path: str) -> List[Tuple[str, str]]:
        cands = [(self.backend_of(path), path)]
        with self._lock:
            twin = self._twin.get(path)
        if twin is not None and os.path.exists(twin):
            cands.append((self.backend_of(twin), twin))
        # proactive reroute: a boxed primary with a live twin serves
        # from the surviving tier without burning a failed attempt
        if len(cands) > 1 and self.health.boxed(cands[0][0]):
            metrics.add("store.rerouted", backend=cands[0][0])
            cands.reverse()
        return cands

    # -- the read path ------------------------------------------------------

    def _get(self, backend: str, path: str, file_off: int, length: int,
             key: str) -> bytes:
        t0 = time.perf_counter()
        failpoint("store.get", key=f"{backend}:{key or path}")
        data = self._backends[backend].read(path, file_off, length)
        metrics.observe("store.read.latency_ms",
                        (time.perf_counter() - t0) * 1e3, backend=backend)
        metrics.add("store.read.bytes", len(data), backend=backend)
        return data

    def read(self, path: str, file_off: int, length: int,
             key: str = "") -> bytes:
        """Failover range read: the partition's primary tier first
        (unless boxed with a live twin), the twin on a typed failure.
        Every fault punishes the tier's health and feeds the recovery
        ledger's ``store`` rung; success on a non-primary candidate
        counts ``store.failover``."""
        cands = self._candidates(path)
        primary = self.backend_of(path)
        last: Optional[StorageError] = None
        for backend, p in cands:
            try:
                data = self._get(backend, p, file_off, length, key)
            except StorageError as e:
                last = e
                self._fault(backend, key, e)
                continue
            self.health.forgive(backend)
            if backend != primary:
                metrics.add("store.failover", backend=backend)
                flightrec.record("store.failover", key=key,
                                 backend=backend)
            return data
        raise StoreError(
            f"no surviving store tier for {key or path} "
            f"({len(cands)} candidate(s) failed)", cause="get",
            backend=primary) from last

    def read_ranges(self, path: str, ranges: Sequence[Tuple[int, int]],
                    keys: Optional[Sequence[str]] = None) -> List[object]:
        """Batch read for the DataEngine's submit_batch plane: the
        primary tier's vectored read when healthy, per-range failover
        via :meth:`read` otherwise. Returns one ``bytes`` or
        ``Exception`` per range — per-request error isolation, the
        batch plane's contract."""
        keys = list(keys) if keys is not None else ["" for _ in ranges]
        backend = self.backend_of(path)
        if not self.health.boxed(backend):
            try:
                for k in keys:
                    failpoint("store.get", key=f"{backend}:{k or path}")
                t0 = time.perf_counter()
                data = self._backends[backend].read_ranges(path, ranges)
                metrics.observe("store.read.latency_ms",
                                (time.perf_counter() - t0) * 1e3,
                                backend=backend)
                metrics.add("store.read.bytes",
                            sum(len(b) for b in data), backend=backend)
                self.health.forgive(backend)
                return list(data)
            except StorageError as e:
                self._fault(backend, keys[0] if keys else path, e)
        else:
            metrics.add("store.rerouted", backend=backend)
        out: List[object] = []
        for (off, ln), k in zip(ranges, keys):
            try:
                out.append(self.read(path, off, ln, key=k))
            except StorageError as e:
                out.append(e)  # forwarded to that request's future
        return out

    def _fault(self, backend: str, key: str, error: Exception) -> None:
        metrics.add("store.errors", backend=backend)
        flightrec.record("store.fault", backend=backend, key=key,
                         error=type(error).__name__)
        if self.health.punish(backend):
            log.warn(f"store: backend {backend!r} penalized after "
                     f"repeated faults ({error})")
        if self.recovery is not None:
            self.recovery.record("store", supplier=backend, map_id=key,
                                 error=error)

    # -- the spill ladder ---------------------------------------------------

    def account_write(self, job_id: str, map_id: str,
                      nbytes: int) -> None:
        """Writer hook: ``nbytes`` of MOF just landed on the local
        tier. Crossing the watermark triggers the spill ladder."""
        nbytes = int(nbytes)
        with self._lock:
            key = (job_id, map_id)
            self._retained[key] = self._retained.get(key, 0) + nbytes
            self._retained_total += nbytes
        metrics.gauge_add("store.local.retained.bytes", nbytes)
        self.maybe_spill()

    def retained_bytes(self) -> int:
        with self._lock:
            return self._retained_total

    def maybe_spill(self) -> List[dict]:
        """Migrate oldest-first while retained bytes exceed the
        watermark. Spill is an optimization: a failed migration leaves
        the partition locally servable and the ladder retries at the
        next write."""
        out: List[dict] = []
        while True:
            with self._lock:
                if (self.watermark_bytes <= 0 or not self._retained
                        or self._retained_total <= self.watermark_bytes):
                    return out
                job_id, map_id = next(iter(self._retained))
            try:
                out.append(self.migrate(job_id, map_id, reason="spill",
                                        shadow=self.shadow))
            except StorageError as e:
                metrics.add("errors.swallowed")
                log.warn(f"store: spill of {job_id}/{map_id} failed "
                         f"(partition stays local, retried at the next "
                         f"write): {e}")
                return out

    # -- migration ----------------------------------------------------------

    def _local_dir(self, job_id: str, map_id: str) -> str:
        if isinstance(self.resolver, DirIndexResolver):
            for r in self.resolver.roots:
                if r == self.blob_root:
                    continue
                d = os.path.join(r, job_id, map_id)
                if os.path.exists(os.path.join(d, "file.out.index")):
                    return d
            return os.path.join(self.resolver.root, job_id, map_id)
        raise StoreError(
            f"store: cannot locate local dir of {job_id}/{map_id} "
            f"(resolver has no directory layout)", cause="missing",
            backend="local")

    def migrate(self, job_id: str, map_id: str, *, reason: str = "spill",
                shadow: Optional[bool] = None,
                cutover: bool = True) -> dict:
        """Move one whole MOF partition set to the blob tier: streamed
        CRC-verified object PUT, the v2 UDIX index (stripe locators
        preserved) rewritten at the blob root, then — with ``cutover``
        — the local index unlinked (the resolver's routing key: the
        next resolve finds the blob copy) and the resolver cache
        invalidated. ``shadow`` keeps the local ``file.out`` as the
        blob tier's failover twin; ``cutover=False`` replicates
        instead (blob copy + twin registration, local stays primary —
        the local->blob failover arrangement). All-or-nothing: any
        failure before the cut-over leaves the local copy
        authoritative and servable."""
        shadow = self.shadow if shadow is None else bool(shadow)
        key = f"{job_id}/{map_id}"
        src_dir = self._local_dir(job_id, map_id)
        src_mof = os.path.join(src_dir, "file.out")
        src_idx = os.path.join(src_dir, "file.out.index")
        if not (os.path.exists(src_mof) and os.path.exists(src_idx)):
            raise StoreError(f"store: no local MOF for {key} under "
                             f"{src_dir}", cause="missing",
                             backend="local")
        failpoint("store.migrate", key=f"local:{key}")
        nbytes = os.path.getsize(src_mof)
        dst_dir = os.path.join(self.blob_root, job_id, map_id)
        dst_mof = os.path.join(dst_dir, "file.out")
        dst_idx = os.path.join(dst_dir, "file.out.index")
        # bytes mid-migration are an open obligation (paired gauge,
        # resledger "gauge.store.migrate"): a migration that dies with
        # the gauge up is exactly the leak the chaos rung must see
        metrics.gauge_add("store.migrate.bytes.on_air", nbytes)
        try:
            copied, crc = self.blob.put_file(src_mof, dst_mof, key=key)
            # the index is rewritten (not copied) so the v2 stripe
            # section survives byte-exact through the re-encode — the
            # locators keep addressing the (identical) blob object
            records = read_index_file(src_idx, dst_mof)
            triples = [(r.start_offset, r.raw_length, r.part_length)
                       for r in records]
            stripe = None
            if records and records[0].stripe is not None:
                st = records[0].stripe
                stripe = (st.k, st.n,
                          [list(r.stripe.parity) for r in records])
            write_index_file(dst_idx, triples, stripe=stripe)
        finally:
            metrics.gauge_add("store.migrate.bytes.on_air", -nbytes)
        if cutover:
            os.unlink(src_idx)  # the atomic routing cut-over
            if shadow:
                with self._lock:
                    self._twin[dst_mof] = src_mof
                    self._twin[src_mof] = dst_mof
            else:
                os.unlink(src_mof)
        else:
            with self._lock:
                self._twin[dst_mof] = src_mof
                self._twin[src_mof] = dst_mof
        invalidate = getattr(self.resolver, "invalidate", None)
        if invalidate is not None:
            invalidate(job_id)
        with self._lock:
            retained = self._retained.pop((job_id, map_id), 0)
            self._retained_total -= retained
        if retained:
            metrics.gauge_add("store.local.retained.bytes", -retained)
        metrics.add("store.migrations", reason=reason)
        metrics.add("store.migrated.bytes", copied)
        if reason == "spill":
            metrics.add("store.spilled.bytes", copied)
        entry = {"job": job_id, "map": map_id, "reason": reason,
                 "src": src_mof, "dst": dst_mof, "bytes": copied,
                 "crc": crc, "shadow": shadow, "cutover": cutover}
        # UDA201 (udarace): the migration log is appended on the
        # producer/drain thread and iterated by resume revalidation on
        # the merge thread — every touch goes through self._lock
        with self._lock:
            self._migrations.append(entry)
        flightrec.record("store.migrate", key=key, reason=reason,
                         bytes=copied, shadow=shadow)
        log.info(f"store: migrated {key} -> blob tier ({copied} bytes, "
                 f"reason={reason}, shadow={shadow}, cutover={cutover})")
        return entry

    def replicate(self, job_id: str, map_id: str) -> dict:
        """Blob replica of a local-primary partition (the local->blob
        failover arrangement; reads keep the local fast path until the
        local tier faults)."""
        return self.migrate(job_id, map_id, reason="replicate",
                            shadow=True, cutover=False)

    # -- elasticity: drain + resume revalidation ----------------------------

    def drain(self, job_id: Optional[str] = None) -> List[dict]:
        """The departing supplier's storage handoff: migrate every
        retained partition (optionally one job's) to the blob tier —
        moved, NOT left for parity reconstruction — so its partitions
        stay fetchable after the server stops warm."""
        out: List[dict] = []
        while True:
            with self._lock:
                pending = [k for k in self._retained
                           if job_id is None or k[0] == job_id]
            if not pending:
                break
            j, m = pending[0]
            out.append(self.migrate(j, m, reason="drain", shadow=False))
        if out:
            metrics.add("store.drained.partitions", len(out))
        return out

    def validate_spilled(self, job_id: Optional[str] = None) -> int:
        """Checkpoint-resume hook (merger/checkpoint.py interaction):
        re-verify the streamed CRC of every spilled blob object before
        a resumed task trusts its locators — a blob object damaged
        while the task was down must surface as a typed error at
        resume, not as a late Segment CRC mismatch blamed on the
        wire."""
        n = 0
        with self._lock:
            entries = list(self._migrations)
        for entry in entries:
            if job_id is not None and entry["job"] != job_id:
                continue
            dst = entry["dst"]
            if not os.path.exists(dst):
                raise StoreError(
                    f"store: spilled object {dst} missing at resume "
                    f"revalidation", cause="missing", backend="blob")
            if self.blob.object_crc(dst) != entry["crc"]:
                raise StoreError(
                    f"store: spilled object {dst} failed CRC "
                    f"revalidation at resume", cause="crc",
                    backend="blob")
            n += 1
        if n:
            metrics.add("store.revalidated", n)
        return n

    # -- introspection / lifecycle ------------------------------------------

    def migrations(self) -> List[dict]:
        with self._lock:
            return list(self._migrations)

    def snapshot(self) -> dict:
        """Stats-surface view: health, retention level, migrations."""
        with self._lock:
            retained = dict(self._retained)
            total = self._retained_total
            nmig = len(self._migrations)
        return {"health": self.health.snapshot(),
                "retained_bytes": total,
                "retained_partitions": len(retained),
                "watermark_bytes": self.watermark_bytes,
                "migrations": nmig}

    def close(self) -> None:
        self.local.close()
        self.blob.close()
