"""Index records and map-output path resolution.

Equivalent of the reference's supplier-side index layer (reference
src/MOFServer/IndexInfo.h:98-121 ``index_record_t`` {offset, rawLength,
partLength, path} and ``partition_table_t``; resolution via the
``getPathUda`` up-call into Java's IndexCache, reference
src/MOFServer/IndexInfo.cc:237-251, plugins mlx-2.x UdaPluginSH.java:
107-144).

File formats:

- a *MOF* (map output file, ``file.out``) is the concatenation of one
  IFile segment per reduce partition;
- its *index* (``file.out.index``) is one (start_offset, raw_length,
  part_length) triple of 8-byte big-endian longs per partition — the
  Hadoop spill-index record layout. ``raw_length`` is the uncompressed
  record-bytes length, ``part_length`` the on-disk segment length
  (they differ when compression or the CRC trailer is on).

Erasure-coded layout (``uda.tpu.coding.scheme``, uda_tpu.coding): the
index format is VERSIONED — a v2 index opens with the ``UDIX`` magic
and a stripe header (k, n) and grows a *parity section* after the
triples: per partition, (start, length) locators of that partition's
n-k parity chunks, which the writer appends to ``file.out`` AFTER all
data segments so the data region stays byte-identical to the uncoded
layout. A v1 index (bare triples) keeps meaning exactly what it always
did.

Stripe shards: chunk ``i`` of a partition's k-of-n stripe is
addressable as the pseudo-map ``<map_id>~s<i>``. On a peer supplier
that is a real directory holding a tiny MOF (one segment per reduce
partition: the chunk bytes, written by
``uda_tpu.mofserver.writer.write_striped_map_output``); on the primary
the resolver SYNTHESIZES the shard's records as byte ranges of the
base map's ``file.out`` (data chunks from the data region, parity
chunks from the parity section) — no extra bytes on disk. A shard
record's ``part_length`` is the stored chunk bytes (the serving
domain) while its ``raw_length`` carries the FULL partition's
part_length — the total the decoded stripe trims to (the shard's
"uncompressed" domain IS the decoded partition).

``IndexResolver`` is the pluggable getPath equivalent: the embedding
application (bridge) registers a callback; the default resolver reads
``<dir>/<map_id>/file.out[.index]`` like the reference's LocalDirAllocator
layout.
"""

from __future__ import annotations

import dataclasses
import os
import struct
import threading
from typing import Callable, Dict, Optional, Sequence

from uda_tpu.utils.errors import StorageError

__all__ = ["IndexRecord", "PartitionStripe", "write_index_file",
           "read_index_file", "IndexResolver", "DirIndexResolver",
           "shard_map_id", "parse_shard_id", "synthesize_shard_records",
           "INDEX_MAGIC", "INDEX_VERSION"]

INDEX_MAGIC = b"UDIX"   # v2+ sentinel; v1 files are bare triples
INDEX_VERSION = 2
_V2_HEADER = struct.Struct(">4sHHHI")  # magic, version, k, n, npart
_TRIPLE = struct.Struct(">qqq")
_PARITY_LOC = struct.Struct(">qq")     # (start, length) in file.out

_SHARD_SEP = "~s"  # <map_id>~s<i>: stripe chunk i's pseudo-map id


def shard_map_id(map_id: str, chunk: int) -> str:
    """The pseudo-map id addressing stripe chunk ``chunk`` of
    ``map_id``'s partitions."""
    return f"{map_id}{_SHARD_SEP}{chunk}"


def parse_shard_id(map_id: str):
    """``(base_map_id, chunk_index)`` for a shard pseudo-map id, None
    for an ordinary map id."""
    base, sep, tail = map_id.rpartition(_SHARD_SEP)
    if not sep or not base or not tail.isdigit():
        return None
    return base, int(tail)


@dataclasses.dataclass(frozen=True)
class PartitionStripe:
    """One partition's k-of-n stripe geometry as recorded by a v2
    index on the full-stripe (primary) holder: the parity section
    locators for THIS partition. Data chunks need no locators — they
    are ``chunk_len``-sized slices of the partition's data range."""

    k: int
    n: int
    parity: tuple  # ((start, length), ...) per parity chunk, len n-k

    def chunk_len(self, part_length: int) -> int:
        return (part_length + self.k - 1) // self.k if part_length else 0


@dataclasses.dataclass(frozen=True)
class IndexRecord:
    """One reduce partition of one map output (reference index_record_t,
    IndexInfo.h:98-104). ``stripe`` is the partition's erasure-coding
    geometry when the index is v2 (full-stripe holder), else None."""

    start_offset: int
    raw_length: int
    part_length: int
    path: str  # MOF data file path
    stripe: Optional[PartitionStripe] = None


def write_index_file(path: str, triples: Sequence[tuple[int, int, int]],
                     stripe: Optional[tuple] = None) -> None:
    """Write a spill index: (start, raw_len, part_len) 8-byte BE
    triples. With ``stripe = (k, n, parity_locators)`` — where
    ``parity_locators[r]`` is the list of (start, length) pairs of
    partition r's n-k parity chunks in file.out — the file is written
    in the versioned v2 layout with the parity section appended."""
    with open(path, "wb") as f:
        if stripe is not None:
            k, n, locators = stripe
            if len(locators) != len(triples):
                raise StorageError(
                    f"parity locators for {len(locators)} partitions, "
                    f"{len(triples)} triples")
            f.write(_V2_HEADER.pack(INDEX_MAGIC, INDEX_VERSION, k, n,
                                    len(triples)))
        for start, raw, part in triples:
            f.write(_TRIPLE.pack(start, raw, part))
        if stripe is not None:
            k, n, locators = stripe
            for r, locs in enumerate(locators):
                if len(locs) != n - k:
                    raise StorageError(
                        f"partition {r}: {len(locs)} parity locators, "
                        f"stripe needs {n - k}")
                for start, length in locs:
                    f.write(_PARITY_LOC.pack(start, length))


def read_index_file(path: str, mof_path: str) -> list[IndexRecord]:
    """Read a spill index into IndexRecords pointing at ``mof_path``.
    Both layouts are accepted: v1 (bare triples) and v2 (``UDIX``
    header + triples + parity section); v2 records carry their
    partition's :class:`PartitionStripe`."""
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(INDEX_MAGIC):
        return _read_v1(data, path, mof_path)
    if len(data) < _V2_HEADER.size:
        raise StorageError(f"truncated v2 index header in {path}")
    magic, version, k, n, npart = _V2_HEADER.unpack_from(data, 0)
    if version != INDEX_VERSION:
        raise StorageError(f"index {path}: unsupported version {version} "
                           f"(this build reads v1 and v{INDEX_VERSION})")
    if not (1 <= k <= n <= 255):
        raise StorageError(f"index {path}: bad stripe geometry "
                           f"k={k}, n={n}")
    want = (_V2_HEADER.size + npart * _TRIPLE.size
            + npart * (n - k) * _PARITY_LOC.size)
    if len(data) != want:
        raise StorageError(f"index {path}: v2 length {len(data)} != "
                           f"expected {want} for {npart} partitions")
    out = []
    off = _V2_HEADER.size
    ploff = off + npart * _TRIPLE.size
    for i in range(npart):
        start, raw, part = _TRIPLE.unpack_from(data, off + i * _TRIPLE.size)
        if start < 0 or raw < 0 or part < 0:
            raise StorageError(f"negative field in index record {i} of "
                               f"{path}")
        locs = []
        for j in range(n - k):
            pstart, plen = _PARITY_LOC.unpack_from(
                data, ploff + (i * (n - k) + j) * _PARITY_LOC.size)
            if pstart < 0 or plen < 0:
                raise StorageError(f"negative parity locator {i}/{j} "
                                   f"in {path}")
            locs.append((pstart, plen))
        out.append(IndexRecord(start, raw, part, mof_path,
                               stripe=PartitionStripe(k, n, tuple(locs))))
    return out


def _read_v1(data: bytes, path: str, mof_path: str) -> list[IndexRecord]:
    size = len(data)
    if size % 24 != 0:
        raise StorageError(f"index file {path} length {size} not a "
                           "multiple of 24")
    out = []
    for i in range(size // 24):
        start, raw, part = _TRIPLE.unpack_from(data, i * 24)
        if start < 0 or raw < 0 or part < 0:
            raise StorageError(f"negative field in index record {i} of {path}")
        out.append(IndexRecord(start, raw, part, mof_path))
    return out


def synthesize_shard_records(base: Sequence[IndexRecord],
                             chunk: int) -> list[IndexRecord]:
    """Shard records for stripe chunk ``chunk`` as byte ranges of the
    full-stripe holder's file.out — data chunks from the (unchanged)
    data region, parity chunks from the parity section. Each record's
    ``part_length`` is the stored chunk bytes and ``raw_length`` the
    full partition's part_length (the decode-trim total; see the
    module docstring)."""
    out = []
    for rec in base:
        st = rec.stripe
        if st is None:
            raise StorageError(
                f"{rec.path}: stripe chunk {chunk} requested but the "
                f"index carries no stripe section (not an erasure-coded "
                f"map output)")
        if not 0 <= chunk < st.n:
            raise StorageError(f"stripe chunk {chunk} out of range "
                               f"(n={st.n}) for {rec.path}")
        cl = st.chunk_len(rec.part_length)
        if chunk < st.k:  # data chunk: a slice of the partition range
            start = rec.start_offset + chunk * cl
            length = max(0, min(cl, rec.part_length - chunk * cl))
        else:
            start, length = st.parity[chunk - st.k]
        out.append(IndexRecord(start, rec.part_length, length, rec.path))
    return out


class IndexResolver:
    """(job_id, map_id, reduce_id) -> IndexRecord, with a per-(job,map)
    cache like the reference's first-fetch-only up-call (IndexInfo.cc:
    237-251: the path is resolved once and cached in the partition
    table)."""

    def __init__(self, lookup: Callable[[str, str], list[IndexRecord]]):
        self._lookup = lookup
        self._cache: Dict[tuple[str, str], list[IndexRecord]] = {}
        self._lock = threading.Lock()

    def resolve(self, job_id: str, map_id: str, reduce_id: int) -> IndexRecord:
        key = (job_id, map_id)
        with self._lock:
            records = self._cache.get(key)
        if records is None:
            records = self._lookup(job_id, map_id)
            with self._lock:
                self._cache[key] = records
        if not 0 <= reduce_id < len(records):
            raise StorageError(
                f"reduce {reduce_id} out of range for {map_id} "
                f"({len(records)} partitions)")
        return records[reduce_id]

    def resolve_cached(self, job_id: str, map_id: str, reduce_id: int):
        """Cache-hit-only resolve: the record when the (job, map)
        partition table is already cached, None on a miss — NEVER does
        IO or an upcall, so the event-loop serve path may call it
        inline (the reference's partition_table_t hit path,
        IndexInfo.cc:237-251, without the first-fetch round trip)."""
        with self._lock:
            records = self._cache.get((job_id, map_id))
        if records is None:
            return None
        if not 0 <= reduce_id < len(records):
            raise StorageError(
                f"reduce {reduce_id} out of range for {map_id} "
                f"({len(records)} partitions)")
        return records[reduce_id]

    def invalidate(self, job_id: str) -> None:
        with self._lock:
            for key in [k for k in self._cache if k[0] == job_id]:
                del self._cache[key]


class DirIndexResolver(IndexResolver):
    """Default layout resolver: ``<root>/<job>/<map_id>/file.out[.index]``
    (the reference's usercache/appcache layout shape, UdaPluginSH.java:
    107-144, without the YARN user indirection). Accepts one root or a
    list of roots — map outputs spread across local dirs resolve like
    the reference's LocalDirAllocator search over mapred.local.dir."""

    def __init__(self, root):
        self.roots = [root] if isinstance(root, str) else list(root)
        if not self.roots:
            raise StorageError("DirIndexResolver needs at least one root")
        self.root = self.roots[0]  # primary root (writer default)
        super().__init__(self._from_dir)

    def map_dir(self, job_id: str, map_id: str) -> str:
        """First root holding the map output; the primary root when
        none does (the write-side location)."""
        for r in self.roots:
            d = os.path.join(r, job_id, map_id)
            if os.path.exists(os.path.join(d, "file.out.index")):
                return d
        return os.path.join(self.root, job_id, map_id)

    def _from_dir(self, job_id: str, map_id: str) -> list[IndexRecord]:
        d = self.map_dir(job_id, map_id)
        mof = os.path.join(d, "file.out")
        idx = os.path.join(d, "file.out.index")
        if os.path.exists(idx):
            return read_index_file(idx, mof)
        # a stripe shard with no shard directory of its own: on the
        # full-stripe (primary) holder the chunk is a byte range of the
        # base map's file.out, synthesized from its v2 index — no shard
        # bytes exist on disk (uda_tpu.coding layout contract)
        shard = parse_shard_id(map_id)
        if shard is not None:
            base_id, chunk = shard
            base_dir = self.map_dir(job_id, base_id)
            base_idx = os.path.join(base_dir, "file.out.index")
            if os.path.exists(base_idx):
                return synthesize_shard_records(
                    read_index_file(base_idx,
                                    os.path.join(base_dir, "file.out")),
                    chunk)
        raise StorageError(f"no index file for {job_id}/{map_id} "
                           f"under {self.roots}")
