"""Index records and map-output path resolution.

Equivalent of the reference's supplier-side index layer (reference
src/MOFServer/IndexInfo.h:98-121 ``index_record_t`` {offset, rawLength,
partLength, path} and ``partition_table_t``; resolution via the
``getPathUda`` up-call into Java's IndexCache, reference
src/MOFServer/IndexInfo.cc:237-251, plugins mlx-2.x UdaPluginSH.java:
107-144).

File formats:

- a *MOF* (map output file, ``file.out``) is the concatenation of one
  IFile segment per reduce partition;
- its *index* (``file.out.index``) is one (start_offset, raw_length,
  part_length) triple of 8-byte big-endian longs per partition — the
  Hadoop spill-index record layout. ``raw_length`` is the uncompressed
  record-bytes length, ``part_length`` the on-disk segment length
  (they differ when compression or the CRC trailer is on).

``IndexResolver`` is the pluggable getPath equivalent: the embedding
application (bridge) registers a callback; the default resolver reads
``<dir>/<map_id>/file.out[.index]`` like the reference's LocalDirAllocator
layout.
"""

from __future__ import annotations

import dataclasses
import os
import struct
import threading
from typing import Callable, Dict, Sequence

from uda_tpu.utils.errors import StorageError

__all__ = ["IndexRecord", "write_index_file", "read_index_file",
           "IndexResolver", "DirIndexResolver"]


@dataclasses.dataclass(frozen=True)
class IndexRecord:
    """One reduce partition of one map output (reference index_record_t,
    IndexInfo.h:98-104)."""

    start_offset: int
    raw_length: int
    part_length: int
    path: str  # MOF data file path


def write_index_file(path: str, triples: Sequence[tuple[int, int, int]]) -> None:
    """Write a spill index: (start, raw_len, part_len) 8-byte BE triples."""
    with open(path, "wb") as f:
        for start, raw, part in triples:
            f.write(struct.pack(">qqq", start, raw, part))


def read_index_file(path: str, mof_path: str) -> list[IndexRecord]:
    """Read a spill index into IndexRecords pointing at ``mof_path``."""
    size = os.path.getsize(path)
    if size % 24 != 0:
        raise StorageError(f"index file {path} length {size} not a "
                           "multiple of 24")
    out = []
    with open(path, "rb") as f:
        data = f.read()
    for i in range(size // 24):
        start, raw, part = struct.unpack_from(">qqq", data, i * 24)
        if start < 0 or raw < 0 or part < 0:
            raise StorageError(f"negative field in index record {i} of {path}")
        out.append(IndexRecord(start, raw, part, mof_path))
    return out


class IndexResolver:
    """(job_id, map_id, reduce_id) -> IndexRecord, with a per-(job,map)
    cache like the reference's first-fetch-only up-call (IndexInfo.cc:
    237-251: the path is resolved once and cached in the partition
    table)."""

    def __init__(self, lookup: Callable[[str, str], list[IndexRecord]]):
        self._lookup = lookup
        self._cache: Dict[tuple[str, str], list[IndexRecord]] = {}
        self._lock = threading.Lock()

    def resolve(self, job_id: str, map_id: str, reduce_id: int) -> IndexRecord:
        key = (job_id, map_id)
        with self._lock:
            records = self._cache.get(key)
        if records is None:
            records = self._lookup(job_id, map_id)
            with self._lock:
                self._cache[key] = records
        if not 0 <= reduce_id < len(records):
            raise StorageError(
                f"reduce {reduce_id} out of range for {map_id} "
                f"({len(records)} partitions)")
        return records[reduce_id]

    def resolve_cached(self, job_id: str, map_id: str, reduce_id: int):
        """Cache-hit-only resolve: the record when the (job, map)
        partition table is already cached, None on a miss — NEVER does
        IO or an upcall, so the event-loop serve path may call it
        inline (the reference's partition_table_t hit path,
        IndexInfo.cc:237-251, without the first-fetch round trip)."""
        with self._lock:
            records = self._cache.get((job_id, map_id))
        if records is None:
            return None
        if not 0 <= reduce_id < len(records):
            raise StorageError(
                f"reduce {reduce_id} out of range for {map_id} "
                f"({len(records)} partitions)")
        return records[reduce_id]

    def invalidate(self, job_id: str) -> None:
        with self._lock:
            for key in [k for k in self._cache if k[0] == job_id]:
                del self._cache[key]


class DirIndexResolver(IndexResolver):
    """Default layout resolver: ``<root>/<job>/<map_id>/file.out[.index]``
    (the reference's usercache/appcache layout shape, UdaPluginSH.java:
    107-144, without the YARN user indirection). Accepts one root or a
    list of roots — map outputs spread across local dirs resolve like
    the reference's LocalDirAllocator search over mapred.local.dir."""

    def __init__(self, root):
        self.roots = [root] if isinstance(root, str) else list(root)
        if not self.roots:
            raise StorageError("DirIndexResolver needs at least one root")
        self.root = self.roots[0]  # primary root (writer default)
        super().__init__(self._from_dir)

    def map_dir(self, job_id: str, map_id: str) -> str:
        """First root holding the map output; the primary root when
        none does (the write-side location)."""
        for r in self.roots:
            d = os.path.join(r, job_id, map_id)
            if os.path.exists(os.path.join(d, "file.out.index")):
                return d
        return os.path.join(self.root, job_id, map_id)

    def _from_dir(self, job_id: str, map_id: str) -> list[IndexRecord]:
        d = self.map_dir(job_id, map_id)
        mof = os.path.join(d, "file.out")
        idx = os.path.join(d, "file.out.index")
        if not os.path.exists(idx):
            raise StorageError(f"no index file for {job_id}/{map_id} "
                               f"under {self.roots}")
        return read_index_file(idx, mof)
