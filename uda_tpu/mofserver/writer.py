"""Map-output writing: the producer side of the MOF contract.

The reference consumes MOFs written by Hadoop mappers (``file.out`` +
``file.out.index`` under the per-attempt work dir, resolved via
IndexCache — reference plugins mlx-2.x UdaPluginSH.java:107-144). This
framework also has to *produce* them (its map phase, tests, and the
regression workloads), so the writer lives in the supplier package: one
IFile segment per reduce partition, concatenated, with the (start,
raw_length, part_length) index triples alongside.

Erasure coding (``uda.tpu.coding.scheme=rs:k:n``, uda_tpu.coding): the
writer grows two outputs, both derived from the same per-partition
blobs (post-codec, so coding is byte-agnostic about compression):

- the primary MOF gains a *parity section* — each partition's n-k
  parity chunks appended AFTER all data segments, so the data region
  stays byte-identical to the uncoded layout — recorded by the v2
  index (:func:`uda_tpu.mofserver.index.write_index_file`);
- :func:`write_striped_map_output` additionally fans the stripe out:
  chunk i of every partition goes to the supplier ``stripe_order``
  names (uda_tpu.coding — the positional rotation ``(p + i) % H`` by
  default, the failure-domain interleave when ``uda.tpu.coding.
  domains`` declares domains) as a tiny shard MOF ``<map_id>~s<i>``
  on that supplier's root. Chunks that land back on the primary are
  NOT duplicated — the resolver synthesizes them from the primary's
  file.out byte ranges.

Shard index triples carry ``raw_length = the full partition's
part_length`` (the decode-trim total) and ``part_length = the stored
chunk bytes`` — see the index module docstring.
"""

from __future__ import annotations

import io
import os
from typing import Iterable, Optional, Sequence, Tuple

from uda_tpu.mofserver.index import shard_map_id, write_index_file
from uda_tpu.utils.ifile import IFileWriter

__all__ = ["MOFWriter", "write_map_output", "write_striped_map_output",
           "partition_blobs"]


def partition_blobs(partitions: Sequence[Iterable[Tuple[bytes, bytes]]],
                    codec=None) -> list[tuple[bytes, int]]:
    """Each partition as ``(on-disk bytes, raw record-byte length)``:
    sorted records IFile-framed, then block-compressed when ``codec``
    is given (raw == len(bytes) for uncompressed jobs)."""
    blobs = []
    for records in partitions:
        seg = io.BytesIO()
        w = IFileWriter(seg)
        for k, v in records:
            w.append(k, v)
        w.close()
        raw = seg.getvalue()
        if codec is not None:
            from uda_tpu.compress import compress_block_stream
            blobs.append((compress_block_stream(raw, codec), len(raw)))
        else:
            blobs.append((raw, len(raw)))
    return blobs


def _encode_parities(blobs: list, scheme) -> list[list[bytes]]:
    """Each partition's n-k parity chunks, computed ONCE (the GF(2^8)
    pass is the coded write's dominant CPU cost — both the primary's
    parity section and the peer shard fan-out index into this)."""
    from uda_tpu.coding import rs

    return [rs.encode_parity(blob, scheme.k, scheme.n)
            for blob, _ in blobs]


def _write_primary(map_dir: str, blobs: list, scheme=None,
                   parities=None) -> list[tuple[int, int, int]]:
    """Write one map dir's file.out (+ parity section when coded) and
    its index; returns the data triples."""
    os.makedirs(map_dir, exist_ok=True)
    mof = io.BytesIO()
    triples = []
    for blob, raw_len in blobs:
        start = mof.tell()
        mof.write(blob)
        triples.append((start, raw_len, len(blob)))
    stripe = None
    if scheme is not None:
        if parities is None:
            parities = _encode_parities(blobs, scheme)
        locators = []
        for pchunks in parities:
            locs = []
            for pchunk in pchunks:
                locs.append((mof.tell(), len(pchunk)))
                mof.write(pchunk)
            # rs:k:k (and the empty partition) has no parity chunks;
            # the locator row must still exist per partition
            locs += [(0, 0)] * (scheme.parity - len(locs))
            locators.append(locs)
        stripe = (scheme.k, scheme.n, locators)
    with open(os.path.join(map_dir, "file.out"), "wb") as f:
        f.write(mof.getvalue())
    write_index_file(os.path.join(map_dir, "file.out.index"), triples,
                     stripe=stripe)
    return triples


def _write_shard(shard_dir: str, chunk_bytes: list[bytes],
                 full_parts: list[int]) -> None:
    """One stripe chunk's shard MOF: partition r's segment is the chunk
    bytes; the triple's raw field carries the full partition's
    part_length (decode-trim total)."""
    os.makedirs(shard_dir, exist_ok=True)
    mof = io.BytesIO()
    triples = []
    for ch, full in zip(chunk_bytes, full_parts):
        start = mof.tell()
        mof.write(ch)
        triples.append((start, full, len(ch)))
    with open(os.path.join(shard_dir, "file.out"), "wb") as f:
        f.write(mof.getvalue())
    write_index_file(os.path.join(shard_dir, "file.out.index"), triples)


def write_map_output(map_dir: str,
                     partitions: Sequence[Iterable[Tuple[bytes, bytes]]],
                     codec=None, scheme=None) -> list[tuple[int, int, int]]:
    """Write one map attempt's output: ``partitions[r]`` is the (already
    sorted) record stream for reducer r. Returns the index triples.

    With ``codec`` (a uda_tpu.compress.Codec) each partition's IFile
    bytes are block-compressed; the index triple then carries
    (start, raw_length=uncompressed, part_length=on-disk) like Hadoop's
    spill index for compressed map outputs. With ``scheme`` (a
    uda_tpu.coding.CodingScheme) the parity section and v2 index are
    written too (data region byte-identical either way).
    """
    return _write_primary(map_dir, partition_blobs(partitions, codec),
                          scheme)


def write_striped_map_output(
        supplier_roots: Sequence[str], primary_index: int, job_id: str,
        map_id: str, partitions: Sequence[Iterable[Tuple[bytes, bytes]]],
        scheme, codec=None,
        domains: Optional[dict] = None) -> list[tuple[int, int, int]]:
    """The coded write with cross-supplier fan-out: the primary
    (``supplier_roots[primary_index]``) gets the full MOF + parity
    section; every stripe chunk whose placement lands on a PEER
    supplier gets a shard MOF under that peer's root. ``supplier_roots``
    must be ordered like the reduce side's canonical supplier list
    (sorted unique hosts) for the placement rules to agree, and
    ``domains`` (a {supplier-root: failure domain} map, the writer-side
    spelling of ``uda.tpu.coding.domains``) must name the same domains
    the reduce side declares — the stripe_order interleave spreads a
    stripe's shards across them (uda_tpu.coding)."""
    from uda_tpu.coding import domain_labels, rs, stripe_order

    blobs = partition_blobs(partitions, codec)
    h = len(supplier_roots)
    # encode each partition's stripe ONCE; the primary's parity
    # section AND the placement loop below both index into it (one
    # GF(2^8) pass per blob total)
    parities = _encode_parities(blobs, scheme)
    triples = _write_primary(
        os.path.join(supplier_roots[primary_index], job_id, map_id),
        blobs, scheme, parities=parities)
    full_parts = [len(blob) for blob, _ in blobs]
    stripes = [rs.split_data(blob, scheme.k) + parity
               for (blob, _), parity in zip(blobs, parities)]
    order = stripe_order(h, primary_index,
                         domain_labels(supplier_roots, domains))
    for i in range(scheme.n):
        target = order[i % h]
        if target == primary_index:
            continue  # served off the primary's file.out by synthesis
        _write_shard(os.path.join(supplier_roots[target], job_id,
                                  shard_map_id(map_id, i)),
                     [stripe[i] for stripe in stripes], full_parts)
    return triples


class MOFWriter:
    """Job-scoped writer over the DirIndexResolver layout
    (``<root>/<job>/<map_id>/file.out[.index]``). With a coding scheme
    and the job's supplier-root table it writes the striped layout
    (``supplier_index`` names this writer's position in the canonical
    supplier order)."""

    def __init__(self, root: str, job_id: str, codec=None, scheme=None,
                 supplier_roots: Optional[Sequence[str]] = None,
                 supplier_index: int = 0,
                 domains: Optional[dict] = None, store=None,
                 on_commit=None):
        self.root = root
        self.job_id = job_id
        self.codec = codec
        self.scheme = scheme
        self.supplier_roots = list(supplier_roots or [])
        self.supplier_index = supplier_index
        self.domains = dict(domains or {})
        self.map_ids: list[str] = []
        # the elastic store's spill ladder (mofserver/store.py): each
        # write's on-disk bytes are accounted against the retention
        # watermark so over-budget suppliers spill as they produce
        self.store = store
        # the push plane's commit seam (ISSUE 19): called as
        # ``on_commit(job_id, map_id)`` AFTER the map output is fully
        # on disk and accounted — wire it to
        # ``EvLoopShuffleServer.notify_commit`` and every subscribed
        # reduce connection starts receiving the partitions as
        # MSG_PUSH chunks while the map phase is still running
        self.on_commit = on_commit

    def map_dir(self, map_id: str) -> str:
        return os.path.join(self.root, self.job_id, map_id)

    def add_supplier_root(self, root: str, domain: Optional[str] = None,
                          supplier_index: Optional[int] = None) -> None:
        """Mid-job joiner rebalance (the writer half of CAP_ELASTIC):
        a supplier that registered after the job started joins the
        stripe-placement universe for NOT-yet-written maps — already
        written stripes keep their placement (their indexes are
        immutable); only future ``write`` calls fan shards onto the
        joiner. ``supplier_index`` re-anchors this writer's position
        when the canonical (sorted) supplier order shifted."""
        if root in self.supplier_roots:
            return
        self.supplier_roots.append(root)
        if domain is not None:
            self.domains[root] = domain
        if supplier_index is not None:
            self.supplier_index = supplier_index

    def write(self, map_id: str,
              partitions: Sequence[Iterable[Tuple[bytes, bytes]]]) -> None:
        if self.scheme is not None and len(self.supplier_roots) > 1:
            write_striped_map_output(self.supplier_roots,
                                     self.supplier_index, self.job_id,
                                     map_id, partitions, self.scheme,
                                     self.codec, domains=self.domains)
        else:
            write_map_output(self.map_dir(map_id), partitions, self.codec,
                             self.scheme)
        self.map_ids.append(map_id)
        if self.store is not None:
            mof = os.path.join(self.map_dir(map_id), "file.out")
            try:
                nbytes = os.path.getsize(mof)
            except OSError:
                # striped writers may anchor the primary on a peer
                # root; retention accounting only covers bytes THIS
                # writer landed under its own root
                nbytes = 0
            if nbytes:
                self.store.account_write(self.job_id, map_id, nbytes)
        if self.on_commit is not None:
            self.on_commit(self.job_id, map_id)
