"""Map-output writing: the producer side of the MOF contract.

The reference consumes MOFs written by Hadoop mappers (``file.out`` +
``file.out.index`` under the per-attempt work dir, resolved via
IndexCache — reference plugins mlx-2.x UdaPluginSH.java:107-144). This
framework also has to *produce* them (its map phase, tests, and the
regression workloads), so the writer lives in the supplier package: one
IFile segment per reduce partition, concatenated, with the (start,
raw_length, part_length) index triples alongside.
"""

from __future__ import annotations

import io
import os
from typing import Callable, Iterable, Sequence, Tuple

from uda_tpu.mofserver.index import write_index_file
from uda_tpu.utils.ifile import IFileWriter

__all__ = ["MOFWriter", "write_map_output"]


def write_map_output(map_dir: str,
                     partitions: Sequence[Iterable[Tuple[bytes, bytes]]]
                     ) -> list[tuple[int, int, int]]:
    """Write one map attempt's output: ``partitions[r]`` is the (already
    sorted) record stream for reducer r. Returns the index triples."""
    os.makedirs(map_dir, exist_ok=True)
    mof = io.BytesIO()
    triples = []
    for records in partitions:
        start = mof.tell()
        w = IFileWriter(mof)
        for k, v in records:
            w.append(k, v)
        w.close()
        length = mof.tell() - start
        triples.append((start, length, length))
    with open(os.path.join(map_dir, "file.out"), "wb") as f:
        f.write(mof.getvalue())
    write_index_file(os.path.join(map_dir, "file.out.index"), triples)
    return triples


class MOFWriter:
    """Job-scoped writer over the DirIndexResolver layout
    (``<root>/<job>/<map_id>/file.out[.index]``)."""

    def __init__(self, root: str, job_id: str):
        self.root = root
        self.job_id = job_id
        self.map_ids: list[str] = []

    def map_dir(self, map_id: str) -> str:
        return os.path.join(self.root, self.job_id, map_id)

    def write(self, map_id: str,
              partitions: Sequence[Iterable[Tuple[bytes, bytes]]]) -> None:
        write_map_output(self.map_dir(map_id), partitions)
        self.map_ids.append(map_id)
