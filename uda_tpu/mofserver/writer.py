"""Map-output writing: the producer side of the MOF contract.

The reference consumes MOFs written by Hadoop mappers (``file.out`` +
``file.out.index`` under the per-attempt work dir, resolved via
IndexCache — reference plugins mlx-2.x UdaPluginSH.java:107-144). This
framework also has to *produce* them (its map phase, tests, and the
regression workloads), so the writer lives in the supplier package: one
IFile segment per reduce partition, concatenated, with the (start,
raw_length, part_length) index triples alongside.
"""

from __future__ import annotations

import io
import os
from typing import Iterable, Sequence, Tuple

from uda_tpu.mofserver.index import write_index_file
from uda_tpu.utils.ifile import IFileWriter

__all__ = ["MOFWriter", "write_map_output"]


def write_map_output(map_dir: str,
                     partitions: Sequence[Iterable[Tuple[bytes, bytes]]],
                     codec=None) -> list[tuple[int, int, int]]:
    """Write one map attempt's output: ``partitions[r]`` is the (already
    sorted) record stream for reducer r. Returns the index triples.

    With ``codec`` (a uda_tpu.compress.Codec) each partition's IFile
    bytes are block-compressed; the index triple then carries
    (start, raw_length=uncompressed, part_length=on-disk) like Hadoop's
    spill index for compressed map outputs.
    """
    os.makedirs(map_dir, exist_ok=True)
    mof = io.BytesIO()
    triples = []
    for records in partitions:
        seg = io.BytesIO()
        w = IFileWriter(seg)
        for k, v in records:
            w.append(k, v)
        w.close()
        raw = seg.getvalue()
        start = mof.tell()
        if codec is not None:
            from uda_tpu.compress import compress_block_stream
            blob = compress_block_stream(raw, codec)
        else:
            blob = raw
        mof.write(blob)
        triples.append((start, len(raw), len(blob)))
    with open(os.path.join(map_dir, "file.out"), "wb") as f:
        f.write(mof.getvalue())
    write_index_file(os.path.join(map_dir, "file.out.index"), triples)
    return triples


class MOFWriter:
    """Job-scoped writer over the DirIndexResolver layout
    (``<root>/<job>/<map_id>/file.out[.index]``)."""

    def __init__(self, root: str, job_id: str, codec=None):
        self.root = root
        self.job_id = job_id
        self.codec = codec
        self.map_ids: list[str] = []

    def map_dir(self, map_id: str) -> str:
        return os.path.join(self.root, self.job_id, map_id)

    def write(self, map_id: str,
              partitions: Sequence[Iterable[Tuple[bytes, bytes]]]) -> None:
        write_map_output(self.map_dir(map_id), partitions, self.codec)
        self.map_ids.append(map_id)
