"""The UdaBridge control surface.

Re-creation of the reference's JNI bridge contract (reference
src/UdaBridge.cc) as an embeddable Python API with the same shape:

- down-calls: ``start(is_net_merger, argv, callable)`` (startNative,
  UdaBridge.cc:187-263), ``do_command(cmd)`` (doCommandNative :266-295),
  ``reduce_exit()`` (reduceExitMsgNative :299-314), ``set_log_level``
  (:318-333);
- up-calls on the registered ``UdaCallable``: ``fetch_over_message``,
  ``data_from_uda``, ``get_path_uda``, ``get_conf_data``, ``log_to``
  and ``failure_in_uda`` — the 6 cached callback methods of
  UdaBridge.cc:138-170, 516-522;
- role dispatch: NetMerger (reduce side, MergeManager_main +
  reduce_downcall_handler, reference src/Merger/NetMergerMain.cc:44-88)
  vs MOFSupplier (server side, MOFSupplier_main + mof_downcall_handler,
  reference src/MOFServer/MOFSupplierMain.cc:37-143), selected by the
  ``is_net_merger`` flag exactly like UdaBridge.cc:217-238;
- the fallback contract: any engine failure is reported through
  ``failure_in_uda`` and the bridge goes inert, unless
  ``mapred.rdma.developer.mode`` is set, in which case it re-raises
  (reference UdaBridge.cc:506-530, UdaShuffleConsumerPluginShared.java:
  205-242).

A JNI-loadable C shim over this class (libuda replacement for running
under an actual Hadoop JVM) is planned for a later round; the command
protocol and up-call semantics here are the compatibility layer it will
bind to.
"""

from __future__ import annotations

import json
import threading
from typing import Optional, Protocol, Sequence

from uda_tpu.bridge.protocol import Cmd, parse_cmd
from uda_tpu.merger import LocalFetchClient, MergeManager
from uda_tpu.merger.segment import InputClient
from uda_tpu.mofserver import DataEngine, IndexRecord, IndexResolver
from uda_tpu.utils.budget import MemoryBudget
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import FallbackSignal, ProtocolError, UdaError
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.logging import LogLevel, get_logger
from uda_tpu.utils.metrics import metrics, stats_enabled_from_env
from uda_tpu.utils.resledger import resledger
from uda_tpu.utils.stats import (StatsReporter, reporter_output_from_env,
                                 telemetry_block)

__all__ = ["UdaCallable", "UdaBridge"]

log = get_logger()


class UdaCallable(Protocol):
    """The up-call interface the embedder registers (the reference's
    UdaCallable/UdaPluginRT/UdaPluginSH surface, UdaBridge.java:85-145).
    All methods are optional; missing ones are no-ops (except
    get_path_uda, required on the supplier side when no local root is
    configured)."""

    def fetch_over_message(self) -> None: ...

    def data_from_uda(self, data: memoryview, length: int) -> None: ...

    def get_path_uda(self, job_id: str, map_id: str,
                     reduce_id: int) -> IndexRecord: ...

    def get_conf_data(self, name: str, default: str) -> str: ...

    def log_to(self, level: int, message: str) -> None: ...

    def failure_in_uda(self, error: Exception) -> None: ...


class _UpcallIndexResolver(IndexResolver):
    """Supplier index resolution through the get_path_uda up-call — the
    reference's first-fetch Java IndexCache round trip (IndexInfo.cc:
    237-251, UdaPluginSH.java:107-144), cached per (job, map, reduce)."""

    def __init__(self, callable_obj):
        self._callable = callable_obj
        self._cache: dict[tuple, IndexRecord] = {}
        self._lock = threading.Lock()

    def resolve(self, job_id: str, map_id: str, reduce_id: int) -> IndexRecord:
        key = (job_id, map_id, reduce_id)
        with self._lock:
            rec = self._cache.get(key)
        if rec is None:
            rec = self._callable.get_path_uda(job_id, map_id, reduce_id)
            with self._lock:
                self._cache[key] = rec
        return rec

    def resolve_cached(self, job_id: str, map_id: str,
                       reduce_id: int) -> Optional[IndexRecord]:
        """Cache-hit-only resolve (no upcall): the event-loop serve
        path's inline fast path; a miss returns None and the caller
        falls back to the engine pool, whose resolve() pays the upcall
        off the loop thread."""
        with self._lock:
            return self._cache.get((job_id, map_id, reduce_id))

    def invalidate(self, job_id: str) -> None:
        with self._lock:
            for key in [k for k in self._cache if k[0] == job_id]:
                del self._cache[key]


class UdaBridge:
    """One bridge instance per role process (the reference allows one
    reduce task per NetMerger process, reducer.h:137)."""

    def __init__(self) -> None:
        self.callable: Optional[UdaCallable] = None
        self.is_net_merger = False
        self.cfg = Config()
        self.started = False
        self._failed = False
        self._dev_error: Optional[Exception] = None
        # reduce side
        self._mm: Optional[MergeManager] = None
        self._client: Optional[InputClient] = None
        self._job_id: Optional[str] = None
        self._reduce_id: Optional[int] = None
        self._key_class = "uda.tpu.RawBytes"
        self._pending_maps: list[tuple[str, str]] = []  # (host, attempt)
        self._attempt_by_task: dict[str, str] = {}
        self._merge_started = False
        self._merge_thread: Optional[threading.Thread] = None
        # supplier side
        self._engine: Optional[DataEngine] = None
        self._resolver: Optional[IndexResolver] = None
        self._owned_engine: Optional[DataEngine] = None
        # network data plane (uda.tpu.net.listen): the ShuffleServer
        # serving this role's engine to remote reduce clients
        self._net_server = None
        # multi-tenant registry (uda.tpu.tenant.enable): one per
        # bridge lifetime, shared across re-INITs
        self._tenant_registry = None
        # observability
        self._stats: Optional[StatsReporter] = None

    # -- down-calls ---------------------------------------------------------

    def start(self, is_net_merger: bool, argv: Sequence[str],
              callable_obj: Optional[UdaCallable] = None) -> None:
        """startNative: parse argv (the reference's getopt channel), wire
        the conf pull channel, pick the role (UdaBridge.cc:187-263)."""
        self.callable = callable_obj
        self.is_net_merger = is_net_merger
        self._argv = list(argv)
        self.cfg = self._fresh_cfg()
        if callable_obj is not None and hasattr(callable_obj, "log_to"):
            get_logger().set_sink(callable_obj.log_to)
        get_logger().set_level(self.cfg.get("uda.log.level"))
        # the flight recorder rides both roles from process start
        # (uda.tpu.flightrec.*; the env kill switch still wins)
        from uda_tpu.utils.flightrec import (flightrec,
                                             flightrec_enabled_from_env)
        flightrec.configure(
            enabled=(bool(self.cfg.get("uda.tpu.flightrec.enable"))
                     and flightrec_enabled_from_env()),
            capacity=int(self.cfg.get("uda.tpu.flightrec.events")),
            dump_dir=str(self.cfg.get("uda.tpu.flightrec.dir")))
        if not is_net_merger:
            # MOFSupplier_main: the data engine serves fetches; paths
            # resolve through the up-call (the IndexCache round trip).
            # Reader threads scale with the configured disk count
            # (reference AsyncReaderManager.cc:16-50).
            self._resolver = _UpcallIndexResolver(self.callable)
            dirs = [d for d in str(
                self.cfg.get("mapred.local.dir", default="")).split(",")
                if d.strip()]
            self._engine = DataEngine(self._resolver, self.cfg,
                                      num_disks=max(1, len(dirs)))
        self._start_stats()
        self.started = True
        log.info(f"uda_tpu bridge started as "
                 f"{'NetMerger' if is_net_merger else 'MOFSupplier'}")

    def _start_stats(self) -> None:
        """Observability wiring (UDA_TPU_STATS=1 / uda.tpu.stats.enable):
        switch the optional metrics layers on and run a StatsReporter
        for the life of the bridge role. Off by default — zero threads,
        no histogram/span recording."""
        if self._stats is not None:  # re-start(): recycle the reporter
            self._stats.stop(final=False)
            self._stats = None
        if not (stats_enabled_from_env()
                or self.cfg.get("uda.tpu.stats.enable")):
            return
        metrics.enable_stats()
        self._stats = StatsReporter(
            interval_s=self.cfg.get("uda.tpu.stats.interval.ms") / 1e3,
            out=reporter_output_from_env(
                str(self.cfg.get("uda.tpu.stats.jsonl", default="")))).start()
        # the live telemetry plane rides the same opt-in: rollup ring,
        # anomaly detectors, SLO book, optional OpenMetrics endpoint
        from uda_tpu.utils.timeseries import arm_observability_plane
        arm_observability_plane(self.cfg)

    def _fresh_cfg(self) -> Config:
        """Config rebuilt from the start-time argv + conf up-call. Each
        INIT gets a FRESH one: INIT-derived settings (codec class,
        shrunken buffer size, lpq size) are per-job and must not leak
        into the next re-INIT on the same bridge — a stale
        compress=True would wrap an uncompressed job's fetches in a
        DecompressingClient and hang the merge."""
        cfg = Config.from_argv(list(self._argv))
        if self.callable is not None and hasattr(self.callable,
                                                 "get_conf_data"):
            cfg.conf_source = self.callable.get_conf_data
        return cfg

    def data_engine(self) -> DataEngine:
        """The supplier's engine (for in-process reduce-side clients —
        the single-host wiring where both roles share a process)."""
        if self._engine is None:
            raise UdaError("bridge not started as MOFSupplier")
        return self._engine

    def do_command(self, cmd: str) -> Optional[str]:
        """doCommandNative: dispatch by role (UdaBridge.cc:266-295).
        Most commands return None; GET_STATS returns the current stats
        record as a JSON string."""
        if not self.started:
            raise UdaError("bridge not started")
        if self._dev_error is not None:
            raise self._dev_error  # developer mode: surface the stored
            # background failure loudly on the next synchronous call
        if self._failed:
            return None  # inert after failure (Java fell back to vanilla)
        try:
            header, params = parse_cmd(cmd)
            if header == Cmd.GET_STATS:  # role-independent, like
                return json.dumps(self.get_stats())  # set_log_level
            if self.is_net_merger:
                self._reduce_downcall(header, params)
            else:
                self._mof_downcall(header, params)
        except Exception as e:  # noqa: BLE001 - ANY engine failure must
            # flow through the fallback contract (e.g. a ValueError from
            # a malformed INIT param), not escape into the embedder
            self._fail(e)
        return None

    def get_stats(self) -> dict:
        """The on-demand stats pull (the GET_STATS command body): the
        reporter's latest record when one is running, else a one-shot
        telemetry block computed directly from the metrics hub."""
        if self._stats is not None:
            return self._stats.latest()
        return telemetry_block()

    def _maybe_start_net_server(self, engine: Optional[DataEngine]) -> None:
        """Start the shuffle data plane next to the role's engine when
        ``uda.tpu.net.listen`` is set (the RDMAServer-next-to-DataEngine
        shape, reference MOFSupplierMain.cc:84-143). Idempotent per
        bridge lifetime; torn down with the engine."""
        if self._net_server is not None or engine is None:
            return
        if not self.cfg.get("uda.tpu.net.listen"):
            return
        from uda_tpu.net import ShuffleServer
        registry = None
        if self.cfg.get("uda.tpu.tenant.enable"):
            # the multi-tenant daemon shape (uda_tpu/tenant/): one
            # registry per bridge lifetime — re-INITs on the same
            # bridge keep serving the same tenant books
            from uda_tpu.tenant import TenantRegistry
            if self._tenant_registry is None:
                self._tenant_registry = TenantRegistry.from_config(
                    self.cfg)
            registry = self._tenant_registry
        self._net_server = ShuffleServer(engine, self.cfg,
                                         registry=registry).start()

    def _stop_net_server(self) -> None:
        srv, self._net_server = self._net_server, None
        if srv is not None:
            srv.stop()

    def net_server(self):
        """The running ShuffleServer (None unless uda.tpu.net.listen):
        embedders read its bound port for service registration."""
        return self._net_server

    def reduce_exit(self) -> None:
        """reduceExitMsgNative: synchronous teardown of the reduce task
        (UdaBridge.cc:299-314, finalize_reduce_task reducer.cc:354-410)."""
        t = self._merge_thread
        if t is not None:
            t.join()
        if self._mm is not None:
            self._mm.stop()
            self._mm = None
        self._stop_net_server()  # before its engine goes away
        if self._owned_engine is not None:
            self._owned_engine.stop()
            self._owned_engine = None
        self._merge_thread = None
        if self._stats is not None:
            # the per-reduce-task aggregate record (the reference's
            # teardown-time counter trio, StreamRW.cc:555-569): one
            # final-flagged JSONL record; the reporter keeps running for
            # a possible re-INIT on the same bridge
            self._stats.report_once(final=True)
        if self._dev_error is not None:
            # developer mode: a failure that happened on the merge thread
            # must not vanish with the thread — teardown re-raises it
            err, self._dev_error = self._dev_error, None
            raise err

    def set_log_level(self, level: int) -> None:
        """setLogLevelNative (UdaBridge.cc:318-333)."""
        get_logger().set_level(level)

    # -- reduce side (reduce_downcall_handler, reducer.cc:144-217) ----------

    PAGE = 4096  # buffer page alignment (reference getpagesize())

    def _reduce_downcall(self, header: Cmd, params: list[str]) -> None:
        if header == Cmd.INIT:
            if self._mm is not None or self._owned_engine is not None:
                # re-INIT (a second reduce attempt on the same bridge):
                # tear down the previous task first — the prior engine's
                # thread pool / fd cache must not leak until process exit
                self.reduce_exit()
            self._pending_maps = []
            self._attempt_by_task = {}
            self._merge_started = False
            self.cfg = self._fresh_cfg()  # per-job settings must not leak
            if (len(params) >= 10 and params[0].isdigit()
                    and params[3].isdigit()):
                # reference layout: [0]=num_maps and [3]=lpq_size are
                # numeric; in the short form [0] is the job id and [3]
                # the key CLASS name — never all-digits — so a short
                # form with many local dirs cannot be misrouted here
                local_dirs = self._init_reference_layout(params)
            elif len(params) >= 4:
                # short form (embedder convenience): job_id, reduce_id,
                # num_maps, key_class, then optional local dirs
                self._job_id, rid, _num_maps, self._key_class = params[:4]
                self._reduce_id = int(rid)
                local_dirs = params[4:]
            else:
                raise ProtocolError(
                    f"INIT needs >= 4 params, got {len(params)}")
            # the reduce task's tenant identity (uda.tpu.tenant.id):
            # RemoteFetchClients read their binding from the same cfg;
            # this process-global install feeds the hot-path metric
            # labels (fetch.bytes{tenant=}) and diagnostics
            from uda_tpu.tenant import set_current_tenant
            set_current_tenant(str(self.cfg.get("uda.tpu.tenant.id")))
            # INIT-time admission: the fetch-window + staging working
            # set must fit the host budget (the reducer.cc:56-133
            # buffer validation, generalized; with a tenant budget
            # share configured, the budgets are this job's PARTITION
            # of the machine, not the whole machine). Over budget
            # either shrinks the window in cfg with a warning
            # (enforce=reroute) or raises -> the fallback contract
            # (enforce=reject); an unfittable chunk always raises.
            # Runs BEFORE the MergeManager reads the window.
            MemoryBudget.from_config(self.cfg).validate_init(self.cfg)
            client = self._make_client(local_dirs)
            # data plane (uda.tpu.net.listen): serve THIS host's map
            # outputs to remote reduce clients next to the owned engine
            self._maybe_start_net_server(self._owned_engine)
            # fetch progress -> fetchOverMessage, the reference cadence:
            # one up-call per PROGRESS_INTERVAL fetched segments plus one
            # at fetch completion (MergeManager.cc:124-130); the embedder
            # counts them against numMaps (UdaPlugin.java:351-364). The
            # END of the merge STREAM is signaled in-band by the IFile
            # EOF marker, exactly as the reference's J2CQueue consumed it
            # — so a bounded staging ring (KVBuf) can apply backpressure
            # to the emitter without deadlocking fetchOutputs.
            def _fetch_progress(done: int, total: int) -> None:
                cb = getattr(self.callable, "fetch_over_message", None)
                if cb is not None:
                    cb()

            self._mm = MergeManager(client, self._key_class, self.cfg,
                                    progress=_fetch_progress)
            ckpt_dir = str(self.cfg.get("uda.tpu.ckpt.dir"))
            if ckpt_dir:
                # crash-consistent checkpointing armed
                # (merger/checkpoint.py): a restarted attempt of this
                # reduce resumes from the newest valid manifest there.
                # EXIT deliberately leaves the checkpoint alone — EXIT
                # also follows failed attempts, and the manifest IS the
                # retry's resume state; the manager discards it itself
                # on successful completion
                log.info(f"bridge INIT: crash-consistent checkpointing "
                         f"armed under {ckpt_dir} (interval "
                         f"{self.cfg.get('uda.tpu.ckpt.interval.s')} s)")
        elif header == Cmd.FETCH:
            # reference FETCH: host:jobid:attemptid:partition
            # (UdaPlugin.java:322-334); host rides with the attempt so
            # a HostRoutingClient can route per supplier
            if len(params) < 4:
                raise ProtocolError("FETCH needs 4 params")
            host, job_id, map_attempt, _partition = params[:4]
            self._fetch_attempt(host, map_attempt)
        elif header == Cmd.FINAL:
            if self._mm is None:
                raise UdaError("FINAL before INIT")
            self._merge_started = True
            maps = list(self._pending_maps)
            self._merge_thread = threading.Thread(
                target=self._merge_main, args=(maps,), daemon=True,
                name="uda-merge-thread")
            self._merge_thread.start()
        elif header == Cmd.EXIT:
            self.reduce_exit()  # emits the final-flagged stats record
            if self._stats is not None:
                self._stats.stop(final=False)
                self._stats = None
            # the reduce task is over: EVERY obligation — leases, fd
            # pins, paired-gauge increments, scoped failpoints — must
            # be settled (the process-end full drain, no pair filter)
            resledger.drain("bridge.exit")
        else:
            raise ProtocolError(f"unexpected command {header.name} for "
                                "NetMerger role")

    def _init_reference_layout(self, params: list[str]) -> list[str]:
        """Parse the reference's 10-param INIT and validate the buffer
        budget (handle_init_msg, reducer.cc:56-133):

          0 num_maps, 1 job_id, 2 reduce_task_id, 3 lpq_size,
          4 rdma_buf_size(B), 5 min_buf(B), 6 key class, 7 codec class,
          8 comp block size(B), 9 shuffle memory size(B),
          [10 num_dirs, 11.. dirs]

        Buffer sizing mirrors the reference exactly: shrink the buffer
        when the double-buffered pool would exceed shuffleMemorySize,
        page-align, and fail (-> fallback) when the result drops under
        the configured minimum."""
        num_maps = int(params[0])
        self._job_id = params[1]
        self._reduce_id = int(params[2])
        lpq_size = int(params[3])
        max_buf = int(params[4])
        min_buf = int(params[5])
        self._key_class = params[6]
        comp_alg = params[7]
        comp_block = int(params[8])
        shuffle_mem = int(params[9])

        # buffer pairs the pool will hold: 2 per in-flight segment + the
        # extra staging buffers (RDMA_BUFFERS_PER_SEGMENT=2 /
        # EXTRA_RDMA_BUFFERS=10, reducer.cc:49-50 -> pairs = maps + 5)
        kv_bufs = max(1, num_maps + 5)
        if shuffle_mem < kv_bufs * max_buf * 2:  # 2: double buffering
            max_buf = shuffle_mem // (kv_bufs * 2)
            if max_buf < min_buf:
                raise UdaError(
                    f"Not enough memory for rdma buffers: "
                    f"shuffleMemorySize={shuffle_mem}B with {kv_bufs} "
                    f"double-buffered pairs needs >= "
                    f"{kv_bufs * min_buf * 2}B")
            log.warn(f"shrinking buffer to {max_buf}B to fit "
                     f"shuffleMemorySize={shuffle_mem}B")
        buffer_size = max_buf - max_buf % self.PAGE  # page alignment
        if buffer_size <= 0 or buffer_size < min_buf:
            raise UdaError(
                f"RDMA Buffer is too small: {max_buf}B aligns to "
                f"{buffer_size}B < min {min_buf}B")
        self.cfg.set("mapred.rdma.buf.size", max(1, buffer_size // 1024))
        if lpq_size:
            self.cfg.set("mapred.netmerger.hybrid.lpq.size", lpq_size)
        if comp_alg and comp_alg not in ("0", "null", "None"):
            self.cfg.set("mapred.compress.map.output", True)
            self.cfg.set("mapred.map.output.compression.codec", comp_alg)
            if comp_block:
                self.cfg.set("io.compression.codec.lzo.buffersize",
                             comp_block)
        num_dirs = int(params[10]) if len(params) > 10 else 0
        return params[11:11 + num_dirs]

    @staticmethod
    def _attempt_task(attempt: str) -> str:
        """Map-task identity of an attempt id: attempt_X_m_NNNNNN_A ->
        task X_m_NNNNNN (the dedupe key of the reference's
        GetMapEventsThread, UdaShuffleConsumerPluginShared.java:434-602).
        Ids not shaped like attempts dedupe by full string."""
        parts = attempt.rsplit("_", 1)
        if (len(parts) == 2 and attempt.startswith("attempt_")
                and parts[1].isdigit()):
            return parts[0]
        return attempt

    def _fetch_attempt(self, host: str, map_attempt: str) -> None:
        """Fetch-attempt hygiene (reference UdaShuffleConsumerPluginShared
        .java:568-589): an exact duplicate attempt is dropped; a NEW
        attempt for a map task whose earlier attempt is already merged
        (or merging) cannot be un-merged -> failure_in_uda (the
        obsolete-after-success fallback); before the merge starts the
        newer attempt simply replaces the stale one. ``host`` rides with
        the attempt so the transport can route per supplier
        (HostRoutingClient; reference RDMAClient.cc:498-527)."""
        task = self._attempt_task(map_attempt)
        existing = self._attempt_by_task.get(task)
        if existing == map_attempt:
            log.debug(f"duplicate fetch for {map_attempt}, ignored")
            return
        if self._merge_started:
            raise UdaError(
                f"map attempt {map_attempt} arrived after the merge "
                f"started"
                + (f" (obsoletes already-merged {existing})"
                   if existing else ""))
        if existing is not None:
            log.warn(f"map attempt {existing} obsoleted by {map_attempt}")
            idx = next(i for i, (_, a) in enumerate(self._pending_maps)
                       if a == existing)
            self._pending_maps[idx] = (host, map_attempt)
        else:
            self._pending_maps.append((host, map_attempt))
        self._attempt_by_task[task] = map_attempt

    def _make_client(self, local_dirs: list[str]) -> InputClient:
        """createInputClient: plain or decompressing transport by codec
        class (reference reducer.cc:412-450); with ``uda.tpu.net.fetch``
        set, a host-routing client over the socket data plane instead of
        an in-process engine client."""
        if self._client is not None:
            return self._client
        if self.cfg.get("uda.tpu.net.fetch"):
            from uda_tpu.merger import HostRoutingClient
            # fetches dial each FETCH-carried supplier host's
            # ShuffleServer; a local engine is still built (from the
            # local dirs) when this host also LISTENS — it serves this
            # host's own map outputs to the other reduce hosts
            if local_dirs and self.cfg.get("uda.tpu.net.listen"):
                from uda_tpu.mofserver import DirIndexResolver
                self._owned_engine = DataEngine(
                    DirIndexResolver(local_dirs), self.cfg,
                    num_disks=len(local_dirs))
            client: InputClient = HostRoutingClient(config=self.cfg)
            return self._wrap_codec(client)
        if local_dirs:
            from uda_tpu.mofserver import DirIndexResolver
            # reader threads scale with the disk count, the reference's
            # per-disk AIO pools (AsyncReaderManager.cc:16-50 sized by
            # threads.per.disk x local dirs)
            engine = DataEngine(DirIndexResolver(local_dirs), self.cfg,
                                num_disks=len(local_dirs))
        else:
            engine = DataEngine(_UpcallIndexResolver(self.callable), self.cfg)
        self._owned_engine = engine
        return self._wrap_codec(LocalFetchClient(engine))

    def _wrap_codec(self, client: InputClient) -> InputClient:
        """Decompressing wrap by codec class (reducer.cc:412-450)."""
        if self.cfg.get("mapred.compress.map.output"):
            from uda_tpu.compress import (BLOCK_HEADER, DecompressingClient,
                                          get_codec)
            codec = get_codec(
                self.cfg.get("mapred.map.output.compression.codec") or "zlib")
            # calculateMemPool's buffer split (reducer.cc:453-496): the
            # compressed (wire) sub-buffer gets `ratio` of each pair,
            # the decompressed side the rest — so compressed fetches are
            # sized ratio * buffer while the merge consumes full chunks
            ratio = float(
                self.cfg.get("mapred.rdma.compression.buffer.ratio"))
            buf_bytes = self.cfg.get("mapred.rdma.buf.size") * 1024
            comp_chunk = max(BLOCK_HEADER.size + 1, int(buf_bytes * ratio))
            client = DecompressingClient(client, codec,
                                         comp_chunk_size=comp_chunk)
        return client

    def set_input_client(self, client: InputClient) -> None:
        """Inject a transport (e.g. the mesh exchange client) — the
        createInputClient factory seam (reducer.cc:412-450)."""
        self._client = client

    def _merge_main(self, maps: list[str]) -> None:
        """The merge thread: fetch (progress -> fetchOverMessage) ->
        merge -> stream dataFromUda blocks, the last one carrying the
        IFile EOF marker as the in-band end-of-stream signal
        (merge_thread_main, MergeManager.cc:291-314)."""
        try:
            def consumer(block: memoryview) -> None:
                failpoint("bridge.upcall", key="data_from_uda")
                cb = getattr(self.callable, "data_from_uda", None)
                if cb is not None:
                    cb(block, len(block))

            self._mm.run(self._job_id, maps, self._reduce_id, consumer)
        except Exception as e:  # noqa: BLE001 - the fallback boundary
            self._fail(e, in_thread=True)

    # -- supplier side (mof_downcall_handler, MOFSupplierMain.cc:37-81) -----

    def _mof_downcall(self, header: Cmd, params: list[str]) -> None:
        if header == Cmd.NEW_MAP:
            pass  # map registration is implicit (resolution is pull-based)
        elif header == Cmd.JOB_OVER:
            if params and self._resolver is not None:
                self._resolver.invalidate(params[0])
        elif header == Cmd.INIT:
            # data plane (uda.tpu.net.listen): start serving this
            # supplier's engine to remote reduce clients (the
            # RDMAServer bound next to the DataEngine)
            self._maybe_start_net_server(self._engine)
        elif header == Cmd.EXIT:
            self._stop_net_server()  # drain before the engine stops
            if self._engine is not None:
                self._engine.stop()
                self._engine = None
            if self._stats is not None:
                self._stats.stop(final=True)
                self._stats = None
            # supplier side of the process-end full drain: with the
            # server stopped and the engine shut down, the books must
            # be empty (anything open leaked past both scoped drains)
            resledger.drain("bridge.exit")
        else:
            raise ProtocolError(f"unexpected command {header.name} for "
                                "MOFSupplier role")

    # -- failure contract ---------------------------------------------------

    def _fail(self, error: Exception, in_thread: bool = False) -> None:
        """exceptionInNativeThread -> failureInUda -> inert bridge
        (UdaBridge.cc:506-530); developer mode fails loudly instead of
        falling back (UdaShuffleConsumerPluginShared.java:210-217).

        Developer mode on a BACKGROUND thread cannot usefully re-raise
        (the exception would die in Thread.run and the embedder — which
        gets no failure_in_uda in developer mode — would block on
        fetch_over forever): the error is stored and re-raised by the
        next synchronous call (do_command / reduce_exit), and
        failure_in_uda still fires so waiters wake; the embedder must
        not treat it as a fallback request in developer mode (the
        reference aborts the process outright there, :210-217 — an
        embedded library cannot).

        The embedder is reported the ROOT CAUSE: a FallbackSignal from
        the engine is unwrapped to its ``cause``, whose captured
        backtrace (UdaError.backtrace) and ``__traceback__`` ride along
        on the exception object — the original failure point is never
        lost at the fallback boundary."""
        root = error.cause if isinstance(error, FallbackSignal) else error
        if self.cfg.get("mapred.rdma.developer.mode"):
            if not in_thread:
                raise error
            self._failed = True
            self._dev_error = error
            log.error(f"merge-thread failure (developer mode, will "
                      f"re-raise on next call): {error}")
        else:
            self._failed = True
            log.error(f"engine failure, requesting fallback: {root}")
            bt = getattr(root, "backtrace", "")
            if bt:
                log.debug(f"failure origin backtrace:\n{bt}")
        cb = getattr(self.callable, "failure_in_uda", None)
        if cb is not None:
            cb(root)

    @property
    def failed(self) -> bool:
        return self._failed
