"""The ":"-delimited control command protocol.

Byte-compatible reimplementation of the reference's hadoop_cmd wire
format (reference src/CommUtils/C2JNexus.cc:141-207 ``parse_hadoop_cmd``
and plugins/shared/.../UdaPlugin.java:562-587 ``UdaCmd.formCmd``):
commands are ``"<param_count>:<header>:<p1>:<p2>:..."`` where header is
the command enum and param_count counts the params AFTER the header.
The command enum mirrors reference src/include/C2JNexus.h:36-47.
"""

from __future__ import annotations

import enum

from uda_tpu.utils.errors import ProtocolError

__all__ = ["Cmd", "form_cmd", "parse_cmd"]


class Cmd(enum.IntEnum):
    # reference C2JNexus.h:36-47
    EXIT = 0
    NEW_MAP = 1
    FINAL = 2
    RESULT = 3
    FETCH = 4
    FETCH_OVER = 5
    JOB_OVER = 6
    INIT = 7
    MORE = 8
    RT_LAUNCHED = 9
    # uda_tpu extension (not in the reference enum): pull the current
    # stats record — do_command returns it as a JSON string. Valid for
    # BOTH roles, like set_log_level.
    GET_STATS = 10


def form_cmd(header: Cmd, params: list[str]) -> str:
    """UdaCmd.formCmd (UdaPlugin.java:562-587)."""
    for p in params:
        if ":" in p:
            raise ProtocolError(f"param {p!r} contains the delimiter")
    return ":".join([str(len(params)), str(int(header))] + list(params))


def parse_cmd(cmd: str) -> tuple[Cmd, list[str]]:
    """parse_hadoop_cmd (C2JNexus.cc:141-207): returns (header, params).

    Like the reference, the declared count must match the actual params
    (the reference walks exactly ``count`` tokens and errors on
    truncation).
    """
    parts = cmd.split(":")
    if len(parts) < 2:
        raise ProtocolError(f"malformed command {cmd!r}")
    try:
        count = int(parts[0])
        header = Cmd(int(parts[1]))
    except ValueError as e:
        raise ProtocolError(f"malformed command {cmd!r}: {e}") from e
    params = parts[2:]
    if count != len(params):
        raise ProtocolError(
            f"command {header.name} declares {count} params, got "
            f"{len(params)}")
    return header, params
