"""Control surface (the UdaBridge/C2JNexus layer of SURVEY §1 L4/L3):
command protocol, role dispatch, up-call registry, fallback contract."""

from uda_tpu.bridge.bridge import UdaBridge, UdaCallable
from uda_tpu.bridge.protocol import Cmd, form_cmd, parse_cmd

__all__ = ["UdaBridge", "UdaCallable", "Cmd", "form_cmd", "parse_cmd"]
