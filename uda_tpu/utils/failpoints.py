"""Failpoint injection framework: named fault sites on the data plane.

The reference survived transport faults because every layer had a
reachable failure path (WC-error retries in RDMAClient.cc:215-356, the
``failureInUda`` fallback flip in UdaBridge.cc:506-530) — but offered no
way to *provoke* those paths outside a broken cluster (SURVEY §4.5: no
mocks of the RDMA layer existed). This module fixes that: production
code declares named injection sites::

    data = failpoint("data_engine.pread", data=data, key=req.map_id)

which are zero-cost no-ops until armed — from the ``UDA_FAILPOINTS``
environment variable, the ``uda.tpu.failpoints`` config key, or a test's
``failpoints.scoped(...)`` context — to raise a typed ``UdaError``,
delay by N ms, truncate a chunk, or corrupt bytes.

Spec grammar (comma- or semicolon-separated entries)::

    <site>=<action>[:<arg>][:<trigger>[:<value>]]...

    actions   error[:storage|transport|merge|protocol|config|uda]
              delay:<ms>
              truncate[:<bytes>]         (drops the chunk tail; >= 1 byte kept)
              corrupt[:<bytes>]          (flips bytes at seeded positions)
    triggers  every:<n>                  (every Nth eligible call)
              once                       (first eligible call only)
              prob:<p>                   (seeded RNG, see seed:)
              seed:<s>                   (RNG seed for prob/corrupt)
              match:<substr>             (only calls whose key contains substr)
              (no trigger = every eligible call)

Examples: ``data_engine.pread=error:every:3`` fails every third supplier
read; ``segment.fetch=delay:50:prob:0.1:seed:7`` delays 10% of fetch
issues by 50 ms, reproducibly. Triggers are deterministic: ``every`` and
``once`` count calls under a lock, ``prob`` uses a per-site seeded RNG —
a chaos schedule (``chaos_spec``) replays exactly from its seed.

Known sites: ``data_engine.pread`` (supplier chunk read — carries data,
so truncate/corrupt apply), ``segment.fetch`` (the
InputClient.start_fetch boundary), ``exchange.round`` (one all-to-all
round), ``bridge.upcall`` (the data_from_uda consumer call), and the
network data plane (uda_tpu/net): ``net.frame`` (every outbound wire
frame, server responses and client requests — data-bearing, so
truncate tears a frame mid-stream and the sender then closes the
connection, a deterministic disconnect), ``net.accept`` (per accepted
connection: delay = slow accept, error = dropped at birth) and
``net.connect`` (per client dial). The survivable-shuffle layer adds
``coding.decode`` (the Reed-Solomon reconstruction of one partition,
keyed ``<map>/<reduce>``) and ``net.handoff`` (the warm-restart
handoff record, keyed ``load``/``save`` — an injected save fault
degrades the next start to cold, never breaks the stop). The batched
host-I/O plane adds ``data_engine.preadv`` (per-request bytes after a
coalesced vectored read, keyed ``<fd>@<file offset>`` — damages one
request of a batch, never its batch-mates). Crash-consistent
checkpointing (merger/checkpoint.py) adds ``ckpt.save`` (the assembled
manifest bytes, keyed by task — truncate writes a torn manifest the
next load must skip) and ``ckpt.load`` (the manifest walk, keyed by
task — error degrades to a fresh start). The elastic disaggregated
MOF store (mofserver/store.py) adds ``store.get`` / ``store.put`` /
``store.migrate``, keyed ``<backend>:<key>`` so a spec's ``match:``
trigger can kill exactly one tier (see _SITE_ERRORS below).
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
import zlib
from collections import defaultdict
from typing import Dict, Iterator, Optional

from uda_tpu.utils.errors import (CompressionError, ConfigError, MergeError,
                                  ProtocolError, StorageError, StoreError,
                                  TenantError, TransportError, UdaError)
from uda_tpu.utils.flightrec import flightrec
from uda_tpu.utils.metrics import metrics

__all__ = ["Failpoint", "FailpointRegistry", "failpoints", "failpoint",
           "chaos_spec", "net_chaos_spec", "KNOWN_SITES"]

_ACTIONS = ("error", "delay", "truncate", "corrupt")

_ERROR_KINDS = {
    "storage": StorageError,
    "transport": TransportError,
    "merge": MergeError,
    "protocol": ProtocolError,
    "config": ConfigError,
    "uda": UdaError,
    "compression": CompressionError,
    "tenant": TenantError,
}

# default injected-error class per site: match what the real fault at
# that layer would raise, so recovery paths see realistic types
_SITE_ERRORS = {
    "data_engine.pread": StorageError,
    "segment.fetch": TransportError,
    "exchange.round": TransportError,
    "bridge.upcall": UdaError,
    "net.frame": TransportError,
    "net.accept": TransportError,
    "net.connect": TransportError,
    # survivable-shuffle paths (ISSUE 8), injectable from day one:
    # the RS decode of a reconstruction (key "<map>/<reduce>") and the
    # server's warm-restart handoff persistence (key "load"/"save")
    "coding.decode": StorageError,
    "net.handoff": StorageError,
    # the coded multicast exchange's decode rung (keyed "round<i>"):
    # an injected failure on a CODED window must complete the round
    # byte-correct on the plain coalesced tile (the in-round fallback,
    # counted exchange.decode.fallbacks) — never a hang or data loss
    "exchange.decode": StorageError,
    # block decompression on the staging pipeline's hot path (keyed by
    # "<map>@<offset>"): a corrupt/injected block must abort the fetch
    # cleanly — the stage pool drains, no in-flight budget bytes leak
    "decompress.block": CompressionError,
    # the batched host-I/O plane's per-request site (keyed "<fd>@<file
    # offset>"): fires on each request's bytes AFTER the coalesced
    # vectored read, so an injected error/truncate/corrupt damages
    # exactly ONE request of a batch — its batch-mates must complete
    # byte-correct (the batch-partial-failure chaos rung)
    "data_engine.preadv": StorageError,
    # the multi-tenant service plane (uda_tpu/tenant/), both keyed by
    # TENANT id so chaos can target exactly one tenant's traffic:
    # tenant.register fires per MSG_JOB registration, tenant.validate
    # per bound REQ — an injected TenantError fails ONE tenant's
    # requests with the typed refusal while its neighbors' jobs must
    # complete byte-correct (the abusive-tenant isolation rung)
    "tenant.register": TenantError,
    "tenant.validate": TenantError,
    # crash-consistent checkpointing (merger/checkpoint.py), both keyed
    # by task ("<job>.r<reduce>"): ckpt.save fires on the assembled
    # manifest bytes (truncate = a torn manifest on disk — load must
    # fall back to the previous one; error = a failed snapshot, which
    # maybe_save absorbs: the task never fails for its checkpoint);
    # ckpt.load fires before the manifest walk (error = an unreadable
    # checkpoint store, which degrades to a fresh start, never a crash)
    "ckpt.save": StorageError,
    "ckpt.load": StorageError,
    # the elastic disaggregated MOF store (mofserver/store.py), every
    # site keyed "<backend>:<partition key>" so chaos can target ONE
    # tier (``match:blob`` kills blob reads while the local tier keeps
    # serving — the degraded-backend failover rung): store.get fires
    # per tier read attempt BEFORE the bytes are read (error = that
    # tier is down for this read; the router must re-route to the
    # surviving tier when the partition has a twin copy, typed
    # StoreError otherwise), store.put per blob-tier object write
    # (a failed or torn spill must leave the local copy authoritative
    # — migration is all-or-nothing), store.migrate per whole-MOF
    # tier migration before any byte moves (a spill/drain that fails
    # here leaves the partition where it was, fully servable)
    "store.get": StoreError,
    "store.put": StoreError,
    "store.migrate": StoreError,
    # the push plane (ISSUE 19, net/push.py), both keyed so chaos can
    # target one supplier or one map: net.push fires on every outbound
    # MSG_PUSH frame (keyed by peer; truncate = a torn push frame —
    # the supplier closes the conn after sending the torn bytes, the
    # reducer's staging discards the partial map and the pull path
    # re-fetches byte-identically); push.admit fires inside the
    # reduce-side admission ladder (keyed "<job>:<map>"; an injected
    # error converts the push to a typed PUSH_NACK(budget) — the
    # supplier falls back to serving that map over pull, no bytes lost)
    "net.push": TransportError,
    "push.admit": StorageError,
}

# The registered-site inventory. udalint's UDA003 rule checks every
# ``failpoint("<site>")`` call site in the tree against this tuple, so
# a typo'd site (a failpoint that can never fire) is a lint error.
KNOWN_SITES = tuple(_SITE_ERRORS)


class Failpoint:
    """One armed site: parsed spec + trigger state (calls/fired counters
    and the per-site seeded RNG for prob/corrupt determinism)."""

    def __init__(self, site: str, spec: str):
        self.site = site
        self.spec = spec
        self.action = ""
        self.error_kind: Optional[str] = None
        self.delay_ms = 0.0
        self.nbytes: Optional[int] = None
        self.trigger = "always"
        self.every = 0
        self.prob = 0.0
        self.seed: Optional[int] = None
        self.match = ""
        self.calls = 0
        self.fired = 0
        self._parse(spec)
        self.rng = random.Random(self.seed if self.seed is not None
                                 else zlib.crc32(site.encode()))

    def _parse(self, spec: str) -> None:
        toks = [t for t in spec.split(":") if t != ""]
        if not toks or toks[0] not in _ACTIONS:
            raise ConfigError(
                f"failpoint {self.site}: bad action in {spec!r} "
                f"(want one of {_ACTIONS})")
        self.action = toks[0]
        i = 1
        # positional action argument, when present
        if self.action == "error" and i < len(toks) and toks[i] in _ERROR_KINDS:
            self.error_kind = toks[i]
            i += 1
        elif self.action == "delay":
            if i >= len(toks):
                raise ConfigError(
                    f"failpoint {self.site}: delay needs <ms> in {spec!r}")
            self.delay_ms = float(toks[i])
            i += 1
        elif self.action in ("truncate", "corrupt") and i < len(toks) \
                and toks[i].isdigit():
            self.nbytes = int(toks[i])
            i += 1
        while i < len(toks):
            tok = toks[i]
            if tok == "once":
                self.trigger = "once"
                i += 1
            elif tok in ("every", "prob", "seed", "match"):
                if i + 1 >= len(toks):
                    raise ConfigError(
                        f"failpoint {self.site}: {tok} needs a value "
                        f"in {spec!r}")
                val = toks[i + 1]
                if tok == "every":
                    self.trigger = "every"
                    self.every = max(1, int(val))
                elif tok == "prob":
                    self.trigger = "prob"
                    self.prob = float(val)
                elif tok == "seed":
                    self.seed = int(val)
                else:
                    self.match = val
                i += 2
            else:
                raise ConfigError(
                    f"failpoint {self.site}: unknown token {tok!r} "
                    f"in {spec!r}")

    def should_fire(self) -> bool:
        """Trigger decision for one eligible call; caller holds the
        registry lock (counters and the RNG need serialized access)."""
        self.calls += 1
        if self.trigger == "every":
            return self.calls % self.every == 0
        if self.trigger == "once":
            return self.fired == 0
        if self.trigger == "prob":
            return self.rng.random() < self.prob
        return True

    def make_error(self) -> UdaError:
        cls = (_ERROR_KINDS[self.error_kind] if self.error_kind
               else _SITE_ERRORS.get(self.site, UdaError))
        err = cls(f"failpoint {self.site}: injected "
                  f"{self.error_kind or cls.__name__} fault "
                  f"(hit {self.fired})")
        err.failpoint_site = self.site
        return err


class FailpointRegistry:
    """Process-global site table. Disarmed evaluation is one dict probe;
    armed sites count hits (``hits[site]``) and a ``failpoint.<site>``
    metric per injection."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sites: Dict[str, Failpoint] = {}
        self.hits: Dict[str, int] = defaultdict(int)

    def arm(self, site: str, spec: str) -> None:
        """Arm one site. Re-arming with the IDENTICAL spec is a no-op
        that keeps trigger state: every component built from the same
        config re-arms on construction, and resetting every/once
        counters mid-run would silently change a live schedule. To
        restart a schedule, ``disarm`` first (arming stays process-
        global until then — chaos outlives any one component by
        design)."""
        fp = Failpoint(site, spec)  # parse (and fail) before arming
        with self._lock:
            cur = self._sites.get(site)
            if cur is not None and cur.spec == spec:
                return
            self._sites[site] = fp

    def arm_spec(self, spec: str) -> None:
        """Arm from a full ``site=spec[,site=spec...]`` string (the
        UDA_FAILPOINTS / uda.tpu.failpoints syntax)."""
        for entry in spec.replace(";", ",").split(","):
            entry = entry.strip()
            if not entry:
                continue
            if "=" not in entry:
                raise ConfigError(f"bad failpoint entry {entry!r} "
                                  f"(want site=action[:...])")
            site, _, body = entry.partition("=")
            self.arm(site.strip(), body.strip())

    def disarm(self, site: Optional[str] = None) -> None:
        with self._lock:
            if site is None:
                self._sites.clear()
            else:
                self._sites.pop(site, None)

    def active(self) -> Dict[str, str]:
        """site -> spec of every armed failpoint (repro logging)."""
        with self._lock:
            return {s: fp.spec for s, fp in self._sites.items()}

    def is_armed(self, site: str) -> bool:
        """Cheap hot-path probe: is anything armed at ``site``? (One
        dict lookup, same locking discipline as :func:`failpoint`'s
        fast path.) Used by paths that must DISABLE an optimization
        while a site is armed — e.g. the DataEngine's zero-copy fd
        slices bypass the ``data_engine.pread`` byte mangling, so an
        armed site forces the byte path to keep chaos honest."""
        return site in self._sites

    @contextlib.contextmanager
    def scoped(self, spec: str) -> Iterator["FailpointRegistry"]:
        """Arm ``spec`` for the duration of a with-block, restoring the
        previous arming (including trigger state) on exit."""
        from uda_tpu.utils.resledger import resledger

        with self._lock:
            saved = dict(self._sites)
        try:
            self.arm_spec(spec)
            # an armed scope is an open obligation (ctx.failpoints.
            # scoped): a scope that never unwinds leaves the whole
            # process armed — the leak the drain points must see
            resledger.acquire("ctx.failpoints.scoped", key=spec)
            yield self
        finally:
            with self._lock:
                self._sites = saved
            resledger.settle("ctx.failpoints.scoped", key=spec)

    @contextlib.contextmanager
    def quiesced(self) -> Iterator["FailpointRegistry"]:
        """Suspend every armed failpoint for a with-block, restoring
        the EXACT Failpoint objects (trigger counters included) on
        exit. A deterministic crafted-state scenario inside a chaos
        run uses this so the ambient schedule neither fires during it
        nor shifts phase because of it — ``every:N`` counters see the
        block as zero hits."""
        from uda_tpu.utils.resledger import resledger

        with self._lock:
            saved = self._sites
            self._sites = {}
        try:
            resledger.acquire("ctx.failpoints.scoped", key="<quiesced>")
            yield self
        finally:
            with self._lock:
                self._sites = saved
            resledger.settle("ctx.failpoints.scoped", key="<quiesced>")

    def evaluate(self, site: str, data: Optional[bytes],
                 key: str) -> Optional[bytes]:
        with self._lock:
            fp = self._sites.get(site)
            if fp is None:
                return data
            if fp.match and fp.match not in key:
                return data
            if not fp.should_fire():
                return data
            fp.fired += 1
            self.hits[site] += 1
            # corrupt positions must come from the seeded RNG under the
            # same lock that serializes the trigger decision
            if fp.action == "corrupt" and data:
                n = min(fp.nbytes or 1, len(data))
                positions = [fp.rng.randrange(len(data)) for _ in range(n)]
            else:
                positions = []
        metrics.add(f"failpoint.{site}")
        # the black box records every FIRE (armed sites only — the
        # disarmed hot path never reaches here): a post-mortem dump
        # must show which injected fault preceded the fallback
        flightrec.record("failpoint", site=site, action=fp.action,
                         key=key)
        if fp.action == "delay":
            time.sleep(fp.delay_ms / 1000.0)
            return data
        if fp.action == "error":
            raise fp.make_error()
        if data is None:
            return data  # truncate/corrupt need a data-bearing site
        if fp.action == "truncate":
            drop = fp.nbytes if fp.nbytes is not None else len(data) // 2
            return data[:max(1, len(data) - drop)]
        out = bytearray(data)
        for p in positions:
            out[p] ^= 0xFF
        return bytes(out)


failpoints = FailpointRegistry()


def failpoint(site: str, data: Optional[bytes] = None,
              key: str = "") -> Optional[bytes]:
    """Evaluate one injection site. Returns ``data`` (possibly truncated
    or corrupted); may sleep or raise a typed ``UdaError`` whose message
    names the site. A single dict-emptiness check when nothing is armed —
    cheap enough for per-chunk hot paths."""
    if not failpoints._sites:
        return data
    return failpoints.evaluate(site, data, key)


def chaos_spec(seed: int) -> str:
    """A randomized-but-reproducible *recoverable* failpoint schedule for
    scripts/run_chaos.sh: transport errors, delays and truncations the
    retry/carry machinery must absorb. Corruption is deliberately absent
    (undetectable without ``uda.tpu.fetch.crc``; the CRC path has its own
    deterministic tests). At most ONE restart-inducing action is armed
    per schedule (``segment.fetch`` only ever delays): two independent
    periodic error sites can phase-lock against a multi-call segment and
    livelock the retry loop by construction, which would be a bug in the
    schedule, not in the engine. The ``error:every:N`` shape relies on
    resume for the same reason: a retry that refetched its partition
    from offset 0 would re-hit a periodic error at the same phase
    EVERY attempt once the partition spans >= N chunks (observed: the
    warm-restart completion rung) — the offset-ledger resume across
    remote errors (merger/segment.py) is what lets each attempt bank
    its progress and converge under this schedule."""
    rng = random.Random(seed)
    pread = rng.choice([
        f"error:every:{rng.randint(4, 8)}",
        f"truncate:{rng.randint(4, 16)}:every:{rng.randint(2, 5)}",
        f"delay:{rng.randint(1, 20)}:prob:0.2:seed:{rng.randint(0, 999)}",
    ])
    fetch = (f"delay:{rng.randint(1, 10)}:prob:0.15"
             f":seed:{rng.randint(0, 999)}")
    return f"data_engine.pread={pread},segment.fetch={fetch}"


def net_chaos_spec(seed: int) -> str:
    """A seeded *recoverable* schedule for the network data plane
    (scripts/run_chaos.sh's network rung): torn frames (the sender then
    closes — a mid-stream disconnect the Segment retry machinery must
    absorb by reconnecting), slow accepts and slow dials. Same
    single-restart-inducing-site rule as :func:`chaos_spec`: exactly
    ONE of the error/truncate shapes is armed (on ``net.frame``) while
    ``net.accept``/``net.connect`` only ever delay — two periodic
    connection-killing sites can phase-lock against a multi-fetch
    segment and livelock the retry loop by construction."""
    rng = random.Random(seed)
    frame = rng.choice([
        f"truncate:{rng.randint(4, 64)}:every:{rng.randint(5, 9)}",
        f"error:every:{rng.randint(5, 9)}",
    ])
    accept = f"delay:{rng.randint(1, 25)}:prob:0.3:seed:{rng.randint(0, 999)}"
    connect = (f"delay:{rng.randint(1, 10)}:prob:0.2"
               f":seed:{rng.randint(0, 999)}")
    return (f"net.frame={frame},net.accept={accept},"
            f"net.connect={connect}")


def _load_env(env=None) -> None:
    spec = (env if env is not None else os.environ).get("UDA_FAILPOINTS")
    if spec:
        failpoints.arm_spec(spec)


_load_env()
