"""Persistent XLA compilation cache management.

The reference pays its startup costs once per daemon (RDMA device
discovery + ~1 GB memory registration at MOFSupplier start, reference
src/DataNet/RDMAComm.cc:314-370): every later request reuses the warm
state. The TPU analogue of that warm state is the compiled XLA
executable. On tunneled/remote-compile TPU backends a cold compile of a
big program can take minutes (the remote service compiles per-program),
so uda_tpu persists executables to an on-disk cache shared by every
process — bench runs, tests, and the bridge daemon all hit the same
cache, and only the first process ever pays for a given program.

``enable()`` is idempotent, cheap, and safe to call before or after
backend initialization; every uda_tpu entry point calls it.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")

_enabled = False

# The one copy of the sitecustomize-override rule: the TPU deployment
# force-selects its backend via jax.config at interpreter start, which
# silently overrides the JAX_PLATFORMS env var — so CPU smoke runs must
# re-apply it through jax.config BEFORE any device use. Import-level
# callers use apply_platform_env(); `python -c` snippets (bench probes,
# tpu_return stages) embed PLATFORM_PRELUDE.
PLATFORM_PRELUDE = (
    "import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
    "p and p != 'axon' and jax.config.update('jax_platforms', p); ")


def apply_platform_env() -> None:
    """Re-apply an explicit ``JAX_PLATFORMS`` over the deployment's
    sitecustomize backend selection (no-op when unset or already the
    deployment platform). Must run before any jax device use."""
    p = os.environ.get("JAX_PLATFORMS")
    if p and p != "axon":
        import jax

        jax.config.update("jax_platforms", p)


def cache_dir() -> str:
    """The cache directory: ``$UDA_TPU_COMPILE_CACHE`` or
    ``<repo>/.jax_cache``. Empty string disables."""
    return os.environ.get("UDA_TPU_COMPILE_CACHE", _DEFAULT_DIR)


def enable() -> bool:
    """Turn on the persistent compilation cache for this process.

    Returns True when the cache is active. Honors
    ``UDA_TPU_COMPILE_CACHE=`` (empty) as an explicit opt-out.

    CPU backends are excluded by default (set ``UDA_TPU_COMPILE_CACHE``
    to opt in): CPU compiles are fast, and XLA:CPU AOT cache entries pin
    the compile machine's feature set — reloading them on a host with a
    different detected feature set risks SIGILL. The cache's purpose is
    accelerator backends, where a cold remote compile costs minutes.
    """
    global _enabled
    if _enabled:
        return True
    d = cache_dir()
    if not d:
        return False
    import jax

    # Detect a CPU-only configuration WITHOUT instantiating a backend:
    # calling jax.default_backend() here would lock in platform
    # selection and break callers (dryrun_multichip) that re-force CPU
    # after enable(). jax.config.jax_platforms covers the ambient
    # setups this repo runs under (sitecustomize sets it); JAX_PLATFORMS
    # covers plain environments. An unset value (auto-detect) enables
    # the cache — the accelerator case is the one that matters.
    platforms = (jax.config.jax_platforms
                 or os.environ.get("JAX_PLATFORMS", ""))
    if (platforms.strip().lower() == "cpu"
            and "UDA_TPU_COMPILE_CACHE" not in os.environ):
        return False
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    # Cache everything that took real compile time; the remote-compile
    # fixed cost alone (~10 s on tunneled backends) justifies an entry.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _enabled = True
    return True
