"""L0 runtime: codecs, record streams, comparators, config, logging,
errors, metrics (the IOUtility/UdaUtil layer of SURVEY §1)."""

from uda_tpu.utils import vint, ifile, comparators, config, errors, metrics
from uda_tpu.utils.logging import LogLevel, get_logger

__all__ = ["vint", "ifile", "comparators", "config", "errors", "metrics",
           "LogLevel", "get_logger"]
