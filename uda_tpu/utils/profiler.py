"""Span-attributed sampling profiler: WHERE the wall-clock goes.

The metrics layer counts WHAT happened (bytes, chunks, retries) and the
tracer records WHEN each phase ran; after the evloop data plane, the
staging pipeline and the two-phase merge, neither says which *code*
burns the time inside a phase. This module is the missing layer: one
daemon thread walks ``sys._current_frames()`` at ``uda.tpu.profile.hz``
(``UDA_TPU_PROFILE=<hz>`` env; 0 = off) and attributes every thread's
stack sample to that thread's *active span* via the tracer's
thread-span registry (``metrics.active_span_of_thread`` — mirrored by
``span()``/``use_span()`` only while the profiler is armed), so a
sample inside the merge consumer lands under ``reduce_task`` ->
``merge.wait``/``overlap_device_merge``, not just "thread 7".

Outputs, all derived from one aggregation:

- **folded stacks** (:meth:`SamplingProfiler.folded`): flamegraph-ready
  ``span;frame;frame count`` text;
- **per-span self/total sample counts**
  (:meth:`SamplingProfiler.span_summary`): *self* = samples whose
  innermost active span is this one; *total* = self + samples of any
  descendant span (via the span's root->self name chain);
- **live counters**: every tick flushes ``profile.samples`` (labeled by
  span) and ``profile.ticks`` into the metrics hub, so
  ``Metrics.snapshot()`` / MSG_STATS / the StatsReporter records carry
  the attribution with zero extra plumbing;
- **span-file lanes** (:meth:`export_records`):
  ``Metrics.export_spans_jsonl`` appends the summaries as
  ``kind: "profile"`` records and ``scripts/trace_merge.py`` renders
  them as a profile lane next to the span lanes;
- **post-mortem slices** (:meth:`recent_summary`): the last-N-seconds
  attribution embedded in watchdog stall dumps and flight-recorder
  post-mortems when the profiler is armed (never armed BY them; any
  profiler error degrades to omission — a dump must stay total).

Design constraints (the flightrec discipline):

- **off = free**: no sampling thread exists and every hook is one
  module-global check (the span-path registry writes are gated on
  :func:`metrics.enable_thread_span_registry`, toggled only by
  start/stop here);
- **on = cheap**: the sampler owns all aggregation state under its own
  leaf lock; the only cross-thread traffic is the GIL-atomic registry
  dict read and a per-tick counter flush taken OUTSIDE that lock;
- **never fatal**: a sampling error (a frame dying mid-walk, a
  half-torn-down interpreter) is counted (``errors.swallowed``) and
  the loop continues — profiling must not take down the job, the
  device_trace contract.

Span attribution needs the span layer recording (``UDA_TPU_STATS=1`` /
``metrics.enable_spans()``); with spans off, samples still aggregate
under the ``(unattributed)`` pseudo-span (the flamegraph is intact,
only the span column degrades).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from uda_tpu.utils.locks import TrackedLock
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import (active_span_of_thread,
                                   enable_thread_span_registry, metrics)

__all__ = ["SamplingProfiler", "profiler", "profile_hz_from_env",
           "DEFAULT_HZ", "UNATTRIBUTED"]

log = get_logger()

# default rate when armed without an explicit hz (UDA_TPU_PROFILE=1):
# a prime near 100 so the sampler cannot phase-lock with 10ms-grained
# pollers (the py-spy convention)
DEFAULT_HZ = 97.0
_MAX_STACK_DEPTH = 48
UNATTRIBUTED = "(unattributed)"


def profile_hz_from_env() -> float:
    """``UDA_TPU_PROFILE``: unset/0/false = off; a number = that
    sampling rate in Hz; bare truthy (1/true/yes/on) = DEFAULT_HZ. An
    unparsable value arms the default with a warning — an operator who
    asked for profiling should get it, not a silent no-op."""
    raw = os.environ.get("UDA_TPU_PROFILE", "").strip().lower()
    if raw in ("", "0", "false", "no", "off"):
        return 0.0
    if raw in ("1", "true", "yes", "on"):
        return DEFAULT_HZ
    try:
        return max(0.0, float(raw))
    except ValueError:
        log.warn(f"UDA_TPU_PROFILE={raw!r} is not a rate; "
                 f"profiling at the default {DEFAULT_HZ:g} Hz")
        return DEFAULT_HZ


class SamplingProfiler:
    """The sampler + aggregation. One global instance (:data:`profiler`)
    serves the process; tests needing isolation construct private ones
    (a private instance never toggles the global thread-span registry
    unless started)."""

    def __init__(self) -> None:
        self._hz = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # leaf lock over the aggregates: only the sampler writes, and
        # the metrics flush happens OUTSIDE it
        self._mu = TrackedLock("profiler")
        self._agg: Dict[tuple, int] = {}        # (span, frames) -> n
        self._self: Dict[str, int] = {}         # span -> self samples
        self._total: Dict[str, int] = {}        # span -> self+descendant
        self._window: Dict[str, list] = {}      # span -> [first, last] wall ts
        self._ring: deque = deque(maxlen=8192)  # (wall_ts, span)
        self._samples = 0
        self._ticks = 0

    # -- lifecycle -----------------------------------------------------------

    @property
    def armed(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    @property
    def hz(self) -> float:
        return self._hz if self.armed else 0.0

    def start(self, hz: float = DEFAULT_HZ) -> "SamplingProfiler":
        """Arm at ``hz`` samples/s. Idempotent: already armed at any
        rate keeps the running sampler (first arm wins — a second
        MergeManager must not restart mid-task aggregation)."""
        if hz <= 0 or self.armed:
            return self
        self._hz = float(hz)
        self._stop.clear()
        # keep roughly two minutes of attribution for recent_summary,
        # bounded both ways
        self._ring = deque(self._ring,
                           maxlen=int(min(65536, max(1024, hz * 120))))
        enable_thread_span_registry(True)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="uda-profiler")
        self._thread.start()
        metrics.gauge("profile.hz", self._hz)
        return self

    def stop(self) -> None:
        """Disarm (idempotent). Aggregates survive for post-run reads;
        ``reset()`` clears them."""
        t = self._thread
        if t is None:
            return
        self._stop.set()
        if threading.current_thread() is not t:
            t.join(timeout=2.0)
        self._thread = None
        enable_thread_span_registry(False)
        metrics.gauge("profile.hz", 0.0)

    def reset(self) -> None:
        with self._mu:
            self._agg.clear()
            self._self.clear()
            self._total.clear()
            self._window.clear()
            self._ring.clear()
            self._samples = 0
            self._ticks = 0

    # -- the sampling loop ---------------------------------------------------

    def _run(self) -> None:
        period = 1.0 / self._hz
        next_t = time.monotonic() + period
        while not self._stop.wait(max(0.0, next_t - time.monotonic())):
            try:
                self._sample()
            except Exception as e:  # noqa: BLE001 - a dying frame or a
                # half-torn-down interpreter must not kill the sampler
                metrics.add("errors.swallowed")
                log.debug(f"profiler: sample failed: {e}")
            now = time.monotonic()
            next_t += period
            if next_t < now:  # overran: skip missed ticks, don't burst
                next_t = now + period

    def _sample(self) -> None:
        now = time.time()
        me = threading.get_ident()
        frames = sys._current_frames()
        pending: Dict[str, int] = {}
        with self._mu:
            self._ticks += 1
            for tid, frame in frames.items():
                if tid == me:
                    continue
                stack: List[str] = []
                f = frame
                while f is not None and len(stack) < _MAX_STACK_DEPTH:
                    co = f.f_code
                    stack.append(f"{co.co_name} "
                                 f"({os.path.basename(co.co_filename)})")
                    f = f.f_back
                stack.reverse()  # folded convention: root first
                span = active_span_of_thread(tid)
                name = span.name if span is not None else UNATTRIBUTED
                key = (name, tuple(stack))
                self._agg[key] = self._agg.get(key, 0) + 1
                self._self[name] = self._self.get(name, 0) + 1
                for nm in (set(span.chain) if span is not None
                           else (UNATTRIBUTED,)):
                    self._total[nm] = self._total.get(nm, 0) + 1
                w = self._window.get(name)
                if w is None:
                    self._window[name] = [now, now]
                else:
                    w[1] = now
                self._ring.append((now, name))
                self._samples += 1
                pending[name] = pending.get(name, 0) + 1
        # counter flush OUTSIDE the aggregation lock (metrics holds its
        # own leaf lock; never nest the two)
        metrics.add("profile.ticks")
        for name, k in pending.items():
            metrics.add("profile.samples", k, span=name)

    # -- views ---------------------------------------------------------------

    def folded(self) -> str:
        """Flamegraph-ready folded-stack text: one
        ``span;frame;frame count`` line per distinct (span, stack)
        pair, heaviest first."""
        with self._mu:
            items = sorted(self._agg.items(), key=lambda kv: -kv[1])
        return "\n".join(
            ";".join((name,) + stack) + f" {n}"
            for (name, stack), n in items)

    def span_summary(self) -> Dict[str, Dict[str, int]]:
        """Per-span sample attribution: ``{span: {"self", "total"}}``."""
        with self._mu:
            names = set(self._self) | set(self._total)
            return {nm: {"self": self._self.get(nm, 0),
                         "total": max(self._total.get(nm, 0),
                                      self._self.get(nm, 0))}
                    for nm in sorted(names)}

    def summary(self, top_stacks: int = 10) -> Dict:
        """The one-block view stats records and MSG_STATS embed."""
        with self._mu:
            samples, ticks = self._samples, self._ticks
            top = sorted(self._agg.items(), key=lambda kv: -kv[1])
            top = top[:max(0, top_stacks)]
        return {"hz": self.hz, "samples": samples, "ticks": ticks,
                "spans": self.span_summary(),
                "top_stacks": [{"span": name,
                                "stack": list(stack)[-6:],
                                "samples": n}
                               for (name, stack), n in top]}

    def recent_summary(self, seconds: float = 30.0) -> Dict:
        """Per-span attribution of the last ``seconds`` only — the
        'what was it doing just before it wedged' slice watchdog stall
        dumps and flightrec post-mortems embed."""
        cutoff = time.time() - max(0.0, seconds)
        counts: Dict[str, int] = {}
        with self._mu:
            ring = list(self._ring)
        for ts, name in ring:
            if ts >= cutoff:
                counts[name] = counts.get(name, 0) + 1
        return {"window_s": seconds, "samples": sum(counts.values()),
                "spans": dict(sorted(counts.items(),
                                     key=lambda kv: -kv[1]))}

    def export_records(self, pid: Optional[int] = None) -> List[Dict]:
        """The ``kind: "profile"`` records appended to span JSONL
        exports (one per attributed span, carrying self/total counts,
        the observed wall window and the span's heaviest stacks) —
        scripts/trace_merge.py renders them as a profile lane."""
        if not self._samples:
            return []
        pid = os.getpid() if pid is None else pid
        with self._mu:
            windows = {nm: tuple(w) for nm, w in self._window.items()}
            agg = sorted(self._agg.items(), key=lambda kv: -kv[1])
        summary = self.span_summary()
        recs = []
        for nm, counts in summary.items():
            t0, t1 = windows.get(nm, (0.0, 0.0))
            stacks = [";".join(stack) + f" {n}"
                      for (span, stack), n in agg if span == nm][:5]
            recs.append({"kind": "profile", "span": nm, "pid": pid,
                         "self": counts["self"], "total": counts["total"],
                         "t0_unix": t0, "t1_unix": t1,
                         "hz": self.hz, "stacks": stacks})
        return recs


profiler = SamplingProfiler()
