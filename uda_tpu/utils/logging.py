"""Dual-mode log facility.

Equivalent of the reference logger (reference src/CommUtils/IOUtility.cc:
406-557): severity enum lsNONE..lsTRACE, either routed to the embedding
application through a registered up-call (the ``logToJava`` path,
UdaBridge.cc:440-452) or written to a private per-role log file
(``mapred.uda.log.to.unique.file``). Log level can be re-synced at runtime
(the reference re-reads log4j's level once per second,
plugins/shared/.../UdaPlugin.java:99-143; here ``set_level`` is just
called directly by the bridge's SET_LOG_LEVEL command).

Every message carries a ``(file:line)`` suffix like the reference
(IOUtility.cc:514-536).
"""

from __future__ import annotations

import enum
import inspect
import os
import sys
import threading
import time
from typing import Callable, Optional

__all__ = ["LogLevel", "Logger", "get_logger", "log"]


class LogLevel(enum.IntEnum):
    # Mirrors the severity enum in reference src/include/IOUtility.h
    NONE = 0
    FATAL = 1
    ERROR = 2
    WARN = 3
    INFO = 4
    DEBUG = 5
    TRACE = 6


class Logger:
    """Process-wide logger with an optional up-call sink.

    ``sink`` receives ``(level, message)``; when unset, messages go to a
    file (if ``open_file`` was called) or stderr.
    """

    def __init__(self) -> None:
        self.level = LogLevel.INFO
        self.sink: Optional[Callable[[int, str], None]] = None
        self._file = None
        self._lock = threading.Lock()

    def set_level(self, level: int) -> None:
        self.level = LogLevel(max(0, min(6, int(level))))

    def set_sink(self, sink: Optional[Callable[[int, str], None]]) -> None:
        self.sink = sink

    def open_file(self, path: str) -> None:
        """Private log file mode (reference startLogNetMerger/MOFSupplier,
        IOUtility.cc:406-466)."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with self._lock:
            if self._file:
                self._file.close()
            self._file = open(path, "a", buffering=1)

    def close(self) -> None:
        with self._lock:
            if self._file:
                self._file.close()
                self._file = None

    def log(self, level: LogLevel, msg: str) -> None:
        if level > self.level or self.level == LogLevel.NONE:
            return
        # attribute to the first frame outside this module, whatever the
        # call depth (direct .log(), level helpers, or module-level log())
        caller = inspect.currentframe()
        this_file = __file__
        while caller is not None and caller.f_code.co_filename == this_file:
            caller = caller.f_back
        where = ""
        if caller:
            where = f" ({os.path.basename(caller.f_code.co_filename)}:{caller.f_lineno})"
        text = f"{msg}{where}"
        if self.sink is not None:
            self.sink(int(level), text)
            return
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        line = f"{stamp} {level.name:5s} uda_tpu: {text}\n"
        with self._lock:
            out = self._file or sys.stderr
            out.write(line)

    def fatal(self, msg: str) -> None:
        self.log(LogLevel.FATAL, msg)

    def error(self, msg: str) -> None:
        self.log(LogLevel.ERROR, msg)

    def warn(self, msg: str) -> None:
        self.log(LogLevel.WARN, msg)

    def info(self, msg: str) -> None:
        self.log(LogLevel.INFO, msg)

    def debug(self, msg: str) -> None:
        self.log(LogLevel.DEBUG, msg)

    def trace(self, msg: str) -> None:
        self.log(LogLevel.TRACE, msg)


_LOGGER = Logger()


def get_logger() -> Logger:
    return _LOGGER


def log(level: LogLevel, msg: str) -> None:
    _LOGGER.log(level, msg)
