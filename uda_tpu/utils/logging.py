"""Dual-mode log facility.

Equivalent of the reference logger (reference src/CommUtils/IOUtility.cc:
406-557): severity enum lsNONE..lsTRACE, either routed to the embedding
application through a registered up-call (the ``logToJava`` path,
UdaBridge.cc:440-452) or written to a private per-role log file
(``mapred.uda.log.to.unique.file``). Log level can be re-synced at runtime
(the reference re-reads log4j's level once per second,
plugins/shared/.../UdaPlugin.java:99-143; here ``set_level`` is just
called directly by the bridge's SET_LOG_LEVEL command).

Every message carries a ``(file:line)`` suffix like the reference
(IOUtility.cc:514-536). The frame walk that computes it runs only when
the message actually emits (behind the level check) and caches the
per-file basename, so hot call sites pay one ``sys._getframe`` walk per
EMITTED message and nothing at a silenced level.

Named loggers: ``get_logger("uda.stats")`` returns a child logger that
shares the root's sink/file but owns its OWN level, so subsystems (the
StatsReporter progress stream) can be silenced independently of the
engine log. A child with no explicit level inherits the root's.
"""

from __future__ import annotations

import enum
import os
import sys
import threading
import time
from typing import Callable, Dict, Optional

__all__ = ["LogLevel", "Logger", "get_logger", "log"]


class LogLevel(enum.IntEnum):
    # Mirrors the severity enum in reference src/include/IOUtility.h
    NONE = 0
    FATAL = 1
    ERROR = 2
    WARN = 3
    INFO = 4
    DEBUG = 5
    TRACE = 6


_THIS_FILE = __file__
_BASENAME_CACHE: Dict[str, str] = {}


def _caller_suffix() -> str:
    """`` (file:line)`` of the first frame outside this module, whatever
    the call depth (direct .log(), level helpers, or module-level
    log()). Only called for messages that will actually emit."""
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename == _THIS_FILE:
        f = f.f_back
    if f is None:
        return ""
    fn = f.f_code.co_filename
    base = _BASENAME_CACHE.get(fn)
    if base is None:
        base = _BASENAME_CACHE[fn] = os.path.basename(fn)
    return f" ({base}:{f.f_lineno})"


class Logger:
    """Process-wide logger with an optional up-call sink.

    ``sink`` receives ``(level, message)``; when unset, messages go to a
    file (if ``open_file`` was called) or stderr. Child loggers (named,
    created via :func:`get_logger`) delegate output to the root and only
    carry their own level override.
    """

    def __init__(self, name: str = "uda_tpu",
                 parent: Optional["Logger"] = None) -> None:
        self.name = name
        self.parent = parent
        # root default INFO; children inherit until set_level is called
        self.level: Optional[LogLevel] = None if parent else LogLevel.INFO
        self.sink: Optional[Callable[[int, str], None]] = None
        self._file = None
        self._lock = threading.Lock()

    def effective_level(self) -> LogLevel:
        node: Optional[Logger] = self
        while node is not None:
            if node.level is not None:
                return node.level
            node = node.parent
        return LogLevel.INFO

    def set_level(self, level: int) -> None:
        self.level = LogLevel(max(0, min(6, int(level))))

    def clear_level(self) -> None:
        """Child loggers only: drop the override, inherit the root's."""
        if self.parent is not None:
            self.level = None

    def set_sink(self, sink: Optional[Callable[[int, str], None]]) -> None:
        self.sink = sink

    def open_file(self, path: str) -> None:
        """Private log file mode (reference startLogNetMerger/MOFSupplier,
        IOUtility.cc:406-466)."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with self._lock:
            if self._file:
                self._file.close()
            self._file = open(path, "a", buffering=1)

    def close(self) -> None:
        with self._lock:
            if self._file:
                self._file.close()
                self._file = None

    def _emitter(self) -> "Logger":
        """The logger whose sink/file actually writes (the root, unless
        this logger was given its own sink/file)."""
        node: Logger = self
        while node.parent is not None and node.sink is None \
                and node._file is None:
            node = node.parent
        return node

    def log(self, level: LogLevel, msg: str) -> None:
        eff = self.effective_level()
        if level > eff or eff == LogLevel.NONE:
            return
        # file:line attribution is computed only on this emit path (a
        # silenced message costs just the level check above)
        text = f"{msg}{_caller_suffix()}"
        out = self._emitter()
        if out.sink is not None:
            out.sink(int(level), text)
            return
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        line = f"{stamp} {level.name:5s} {self.name}: {text}\n"
        with out._lock:
            stream = out._file or sys.stderr
            stream.write(line)

    def fatal(self, msg: str) -> None:
        self.log(LogLevel.FATAL, msg)

    def error(self, msg: str) -> None:
        self.log(LogLevel.ERROR, msg)

    def warn(self, msg: str) -> None:
        self.log(LogLevel.WARN, msg)

    def info(self, msg: str) -> None:
        self.log(LogLevel.INFO, msg)

    def debug(self, msg: str) -> None:
        self.log(LogLevel.DEBUG, msg)

    def trace(self, msg: str) -> None:
        self.log(LogLevel.TRACE, msg)


_LOGGER = Logger()
_NAMED: Dict[str, Logger] = {}
_NAMED_LOCK = threading.Lock()


def get_logger(name: Optional[str] = None) -> Logger:
    """The root logger (no name, back-compat) or a named child sharing
    the root's output but with an independently settable level."""
    if name is None or name == _LOGGER.name:
        return _LOGGER
    with _NAMED_LOCK:
        lg = _NAMED.get(name)
        if lg is None:
            lg = _NAMED[name] = Logger(name, parent=_LOGGER)
        return lg


def log(level: LogLevel, msg: str) -> None:
    _LOGGER.log(level, msg)
