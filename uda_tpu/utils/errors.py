"""Typed error hierarchy + fallback signalling.

Equivalent of the reference's ``UdaException`` (backtrace-carrying C++
exception rethrown into Java, reference src/CommUtils/IOUtility.cc:561-569)
and the fallback-to-vanilla machinery (any native failure flips the Java
side back to Hadoop's stock shuffle, reference src/UdaBridge.cc:506-530,
plugins/shared/.../UdaShuffleConsumerPluginShared.java:205-242).

In the TPU build, ``FallbackSignal`` plays the role of
``failureInUda``: the bridge catches any ``UdaError`` raised inside the
engine, reports it through the registered failure up-call, and the caller
decides whether to fall back to its vanilla path (unless developer mode is
set, in which case we re-raise — mirroring ``mapred.rdma.developer.mode``).
"""

from __future__ import annotations

import traceback

__all__ = [
    "UdaError",
    "ConfigError",
    "ProtocolError",
    "TransportError",
    "MergeError",
    "StorageError",
    "StoreError",
    "CompressionError",
    "TenantError",
    "FallbackSignal",
    "attribute_supplier",
]


def attribute_supplier(exc: BaseException, supplier: str) -> None:
    """Stamp the structured failing-supplier attribution onto ``exc``
    (see :attr:`UdaError.supplier`): first writer wins, and foreign
    exception types without attribute slots are tolerated — the ONE
    implementation of the attribution contract every stamping site
    shares."""
    if getattr(exc, "supplier", None) is None:
        try:
            exc.supplier = supplier
        except AttributeError:  # udalint: disable=UDA006
            pass  # foreign exception type without attribute slots


class UdaError(Exception):
    """Base error. Captures a formatted backtrace at construction, like the
    reference's UdaException embeds a C++ backtrace in its message
    (IOUtility.cc:561-569, print_backtrace :479-498).

    ``supplier`` is the STRUCTURED failing-source attribution (None =
    unattributed): the fetch ladder stamps the supplier whose attempt
    produced the error so the recovery ledger, penalty box and
    speculation can key on it without parsing reason strings (udalint
    UDA005). First writer wins — an error shared across segments (a
    stop-path drain) keeps its original attribution."""

    supplier = None  # failing supplier host/label, when attributable

    def __init__(self, message: str):
        self.backtrace = "".join(traceback.format_stack()[:-1])
        super().__init__(message)


class ConfigError(UdaError):
    """Bad or missing configuration (reference parse_options failures,
    src/CommUtils/C2JNexus.cc:43-137)."""


class ProtocolError(UdaError):
    """Malformed control-plane command (reference parse_hadoop_cmd,
    src/CommUtils/C2JNexus.cc:141-207)."""


class TransportError(UdaError):
    """Exchange/collective-plane failure (reference RDMA WC errors and
    connect failures, src/DataNet/RDMAClient.cc:215-356)."""


class MergeError(UdaError):
    """Merge-engine invariant violation (reference merge-thread failures,
    src/Merger/MergeManager.cc)."""


class StorageError(UdaError):
    """Segment IO failure (reference AIOHandler/DataEngine read errors,
    src/MOFServer/IndexInfo.cc:304-376)."""


class StoreError(StorageError):
    """Disaggregated MOF-store failure (uda_tpu/mofserver/store.py):
    a backend tier (local fd / blob) errored, failed CRC verification,
    or every tier a partition lives on is unhealthy. ``cause`` is the
    STRUCTURED failure class (``get``/``put``/``migrate``/``crc``/
    ``short_read``/``missing`` — compare these, never the message
    text, per udalint UDA005) and ``backend`` the tier that produced
    it, so the RecoveryLedger and the chaos gates can key the storage
    rung without reason strings. Both default empty so the failpoint
    runtime's one-positional-message construction stays legal."""

    def __init__(self, message: str, cause: str = "", backend: str = ""):
        super().__init__(message)
        self.cause = cause
        self.backend = backend


class CompressionError(UdaError):
    """Codec failure (reference DecompressorWrapper paths,
    src/Merger/DecompressorWrapper.cc)."""


class TenantError(UdaError):
    """Multi-tenant service-plane refusal (uda_tpu/tenant/): unknown or
    retired job, stale epoch (a restarted job's fetches fenced off a
    predecessor's chunks), or a failed MSG_JOB authentication. Rides
    the wire as a typed ERR frame and is TERMINAL on the reduce side —
    retrying cannot legalize a fenced epoch, so the Segment machinery
    must fail the task into the fallback contract instead of pacing a
    retry storm against the registry."""


class FallbackSignal(Exception):
    """Raised to the embedding application to request fallback-to-vanilla.

    Wraps the originating ``UdaError`` as ``cause`` — the root-cause
    error the consumer should report when it falls back (its message
    names the failing site for injected faults) — and carries the
    cause's captured backtrace so the failure point survives the trip
    across the fallback boundary. Raise it ``from cause`` so
    ``__cause__``/``__traceback__`` chain too. Matches the contract of
    ``UdaBridge_exceptionInNativeThread`` -> Java ``failureInUda``
    (reference src/UdaBridge.cc:506-530)."""

    def __init__(self, cause: UdaError):
        self.cause = cause
        self.backtrace = getattr(cause, "backtrace", "")
        super().__init__(f"uda_tpu failure, fallback requested: "
                         f"[{type(cause).__name__}] {cause}")
