"""Key comparators: Hadoop RawComparator semantics + device normalization.

The reference maps a Java key *class name* to a native compare function
(reference src/Merger/CompareFunc.cc:70-113):

- ``org.apache.hadoop.io.Text``: skip the VInt length-prefix bytes, then
  bytewise compare (CompareFunc.cc:82-86);
- fixed-width byte-comparables (Boolean/Byte/Short/Int/Long Writable):
  plain memcmp over the serialized bytes (CompareFunc.cc:70-78);
- ``BytesWritable`` / ``ImmutableBytesWritable``: skip the 4-byte length,
  then bytewise (CompareFunc.cc:89-91);
- anything else raises (-> Java falls back to vanilla shuffle,
  CompareFunc.cc:95-113).

TPU-first design: instead of calling a comparator per heap adjustment
(the reference's hot loop, src/Merger/MergeQueue.h:151-270), we
*normalize* every key once at staging time into a fixed-width big-endian
byte string plus a content-length column; the pair (prefix bytes, length)
memcmp-orders exactly like the comparator for keys that fit the carried
width, and ties beyond the width are broken by a full-key overflow rank
computed on host for the rare long-key case. Normalized keys pack into
uint32 lanes and sort on device via lexicographic ``lax.sort`` (see
uda_tpu.ops.sort).

Note on memcmp vs numeric order: the reference deliberately uses memcmp
for Int/Long writables, which orders negative keys after positive ones
(two's-complement high bit). We reproduce that exactly for parity; the
additional ``*_numeric`` key types flip the sign bit during
normalization for users who want true numeric order on device.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict

from uda_tpu.utils import vint
from uda_tpu.utils.errors import UdaError

__all__ = ["KeyType", "get_key_type", "register_key_type", "memcmp",
           "uses_default_bytewise"]


def memcmp(a: bytes, b: bytes) -> int:
    """Bytewise compare with shorter-is-smaller tiebreak (memcmp + length)."""
    if a == b:
        return 0
    return -1 if a < b else 1


@dataclasses.dataclass(frozen=True)
class KeyType:
    """Per-key-class behavior.

    ``content(serialized)`` extracts the comparable content bytes from the
    serialized key (e.g. strips Text's VInt prefix). ``compare`` is the
    host-side comparator over *serialized* keys. ``normalize(serialized,
    width)`` returns exactly ``width`` bytes whose memcmp order equals
    ``compare`` order for keys whose content fits in ``width`` bytes
    (longer keys additionally need the overflow tiebreak, see
    uda_tpu.ops.sort.overflow_ranks).
    """

    name: str
    content: Callable[[bytes], bytes]
    fixed_width: int = 0  # >0 when every key has this serialized width

    def compare(self, a: bytes, b: bytes) -> int:
        return memcmp(self.content(a), self.content(b))

    def normalize(self, serialized: bytes, width: int) -> tuple[bytes, int]:
        """Returns ``(padded_prefix, content_length)``.

        The full device sort key is (prefix bytes, overflow rank,
        content length) — see uda_tpu.ops.sort._as_columns for why rank
        precedes length. For keys whose content fits in ``width`` the
        (prefix, length) columns order exactly like ``compare``
        (zero-padding alone would collapse e.g. b"a" and b"a\\x00"; the
        length column restores the shorter-is-smaller memcmp rule); keys
        longer than ``width`` with equal prefixes are ordered by the rank
        column (uda_tpu.ops.packing.overflow_ranks).
        """
        c = self.content(serialized)
        if len(c) >= width:
            return c[:width], len(c)
        return c + b"\x00" * (width - len(c)), len(c)


def uses_default_bytewise(kt: KeyType) -> bool:
    """True when ``kt.compare`` is the stock bytewise order — memcmp
    over ``content()`` with the shorter-is-smaller tiebreak — i.e. the
    method was not overridden by a subclass. For such key types the
    comparator order equals a (zero-padded content bytes, content
    length) lexicographic order, so hot paths may replace per-record
    ``cmp_to_key`` Python comparisons with one vectorized
    ``np.lexsort`` (uda_tpu.merger.overlap's oversize-key spool path).
    A subclass with a custom ``compare`` always gets the comparator-
    faithful slow path."""
    return type(kt).compare is KeyType.compare


def _text_content(serialized: bytes) -> bytes:
    # Text serializes as VInt(len) + utf8 bytes; comparator skips the VInt
    # (reference CompareFunc.cc:82-86).
    n, off = vint.decode_vlong(serialized, 0)
    return bytes(serialized[off:off + n])


def _bytes_writable_content(serialized: bytes) -> bytes:
    # BytesWritable serializes as 4-byte big-endian length + bytes;
    # comparator skips the length (reference CompareFunc.cc:89-91).
    return bytes(serialized[4:])


def _identity(serialized: bytes) -> bytes:
    return bytes(serialized)


def _flip_sign_bit(width: int) -> Callable[[bytes], bytes]:
    def content(serialized: bytes) -> bytes:
        b = bytearray(serialized[:width])
        b[0] ^= 0x80
        return bytes(b)
    return content


_REGISTRY: Dict[str, KeyType] = {}


def register_key_type(java_class: str, kt: KeyType) -> None:
    _REGISTRY[java_class] = kt


def get_key_type(java_class: str) -> KeyType:
    """Key class name -> KeyType; raises UdaError for unsupported classes
    (matching reference get_compare_func -> UdaException -> fallback,
    CompareFunc.cc:95-113)."""
    kt = _REGISTRY.get(java_class)
    if kt is None:
        raise UdaError(f"unsupported key class for native merge: {java_class}")
    return kt


# Reference-supported classes (CompareFunc.cc:70-91):
register_key_type("org.apache.hadoop.io.Text",
                  KeyType("text", _text_content))
register_key_type("org.apache.hadoop.io.BooleanWritable",
                  KeyType("boolean", _identity, fixed_width=1))
register_key_type("org.apache.hadoop.io.ByteWritable",
                  KeyType("byte", _identity, fixed_width=1))
register_key_type("org.apache.hadoop.io.ShortWritable",
                  KeyType("short", _identity, fixed_width=2))
register_key_type("org.apache.hadoop.io.IntWritable",
                  KeyType("int", _identity, fixed_width=4))
register_key_type("org.apache.hadoop.io.LongWritable",
                  KeyType("long", _identity, fixed_width=8))
register_key_type("org.apache.hadoop.io.BytesWritable",
                  KeyType("bytes", _bytes_writable_content))
register_key_type("org.apache.hadoop.hbase.io.ImmutableBytesWritable",
                  KeyType("ibytes", _bytes_writable_content))

# New in this framework: numeric-order variants (sign-bit flip makes
# memcmp order == numeric order on device).
register_key_type("uda.tpu.IntNumeric",
                  KeyType("int_numeric", _flip_sign_bit(4), fixed_width=4))
register_key_type("uda.tpu.LongNumeric",
                  KeyType("long_numeric", _flip_sign_bit(8), fixed_width=8))
# Raw bytes with no framing (TeraSort-style fixed 10-byte keys etc).
register_key_type("uda.tpu.RawBytes", KeyType("raw", _identity))
