"""Per-phase timers and counters.

Equivalent of the reference's per-task counters (``total_wait_mem_time``,
``total_fetch_time``, ``total_merge_time``, reference
src/Merger/reducer.h:80-90, accumulated in StreamRW.cc:555-569) and the
AIO on-air counters (src/CommUtils/AIOHandler.cc:129-141). The reference
had no dedicated tracer (SURVEY §5); here we add a lightweight span/trace
export so profiles can be correlated with device profiles.

Failure-domain counters (dotted namespace, maintained by the fetch
recovery layer and the failpoint framework): ``fetch.retries``,
``fetch.timeouts``, ``fetch.stale_completions``, ``fetch.backoff_seconds``,
``fetch.deadline_exceeded``, ``fetch.crc_mismatch``, ``fetch.crc_refetch``,
``fetch.penalties``, ``fetch.deprioritized``, ``fallback.signals`` and
``failpoint.<site>`` per armed injection site.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator

__all__ = ["Metrics", "metrics", "device_trace"]


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = defaultdict(float)
        self.spans: list[dict] = []
        self.record_spans = False

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += value

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.counters[name + "_time"] += dt
                if self.record_spans:
                    self.spans.append({"name": name, "ts": t0, "dur": dt,
                                       "tid": threading.get_ident()})

    def get(self, name: str) -> float:
        """One counter's current value (0.0 when never incremented)."""
        with self._lock:
            return self.counters.get(name, 0.0)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.counters)

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.spans.clear()

    def export_chrome_trace(self, path: str) -> None:
        """Write spans in Chrome trace-event format (load in perfetto)."""
        with self._lock:
            events = [
                {"name": s["name"], "ph": "X", "pid": 0, "tid": s["tid"],
                 "ts": s["ts"] * 1e6, "dur": s["dur"] * 1e6}
                for s in self.spans
            ]
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)


@contextlib.contextmanager
def device_trace(log_dir: str | None = None) -> Iterator[None]:
    """Capture a device (Xprof) profile around a block, correlating the
    host-side spans above with on-device timelines — the SURVEY §7
    stage-8 'Xprof hooks'. Enabled by passing ``log_dir`` or setting
    ``UDA_TPU_XPROF=<dir>``; a no-op otherwise (and when the ambient
    backend does not support jax.profiler, e.g. relay backends — the
    failure is logged, never raised: profiling must not take down the
    job)."""
    import os

    d = log_dir or os.environ.get("UDA_TPU_XPROF")
    if not d:
        yield
        return
    import jax

    try:
        jax.profiler.start_trace(d)
    except Exception as e:  # noqa: BLE001 - profiling is best-effort
        from uda_tpu.utils.logging import get_logger

        get_logger().warn(f"device trace unavailable: {e}")
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            from uda_tpu.utils.logging import get_logger

            get_logger().warn(f"device trace stop failed: {e}")


metrics = Metrics()
