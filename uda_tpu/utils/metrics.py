"""Labeled metrics + span-tree tracing.

Equivalent of the reference's per-task counters (``total_wait_mem_time``,
``total_fetch_time``, ``total_merge_time``, reference
src/Merger/reducer.h:80-90, accumulated in StreamRW.cc:555-569) and the
AIO on-air counters (src/CommUtils/AIOHandler.cc:129-141), grown into a
full observability layer (the reference had no tracer at all, SURVEY §5):

- **counters** (``metrics.add``): monotone sums, optionally labeled —
  ``metrics.add("fetch.bytes", n, supplier=sid)`` accumulates BOTH the
  unlabeled total ``fetch.bytes`` and the per-label series
  ``fetch.bytes{supplier=sid}``;
- **gauges** (``metrics.gauge`` / ``metrics.gauge_add``): point-in-time
  levels — on-air fetches, arena occupancy — mirroring the reference's
  AIO on-air counters;
- **histograms** (``metrics.observe``): fixed power-of-two buckets with
  p50/p95/p99 estimation; recorded only while stats are enabled
  (``UDA_TPU_STATS=1`` / ``uda.tpu.stats.enable`` /
  :meth:`Metrics.enable_stats`), a no-op otherwise;
- **spans**: a tree tracer — every span carries trace/span/parent ids
  and free-form attributes (reduce task, supplier, map id, offset,
  attempt), propagates through threads either implicitly (contextvar)
  or explicitly (``start_span(parent=...)``), and exports to Chrome
  trace-event format with ``args`` so host lanes line up with
  ``device_trace`` Xprof captures. Off by default; idempotent
  ``enable_spans()``/``disable_spans()``.

Metric names use a dotted ``domain.metric`` namespace and must appear in
:data:`METRICS_REGISTRY` (or start with a :data:`REGISTRY_PREFIXES`
prefix) — linted by ``scripts/check_metrics_names.py``, which runs in
tier-1 via ``tests/test_metrics.py``.

Counter reference parity: :meth:`Metrics.snapshot` aliases the timer
sums ``wait_mem_time`` / ``fetch_time`` / ``merge_time`` under the
reference's exact per-task names ``total_wait_mem_time`` /
``total_fetch_time`` / ``total_merge_time`` (reducer.h:80-90).
"""

from __future__ import annotations

import bisect
import contextlib
import contextvars
import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

from uda_tpu.utils.locks import TrackedLock
from uda_tpu.utils.resledger import resledger as _resledger

__all__ = ["Metrics", "Span", "metrics", "device_trace",
           "METRICS_REGISTRY", "REGISTRY_PREFIXES", "NAME_RE",
           "SPAN_REGISTRY", "PARITY_ALIASES", "stats_enabled_from_env",
           "percentile_from_summary", "active_span_of_thread",
           "enable_thread_span_registry"]

# Dotted namespace every metrics.add/gauge/observe name must match
# (scripts/check_metrics_names.py enforces this over uda_tpu/).
NAME_RE = r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+"

# The metrics registry: every statically-named counter/gauge/histogram
# call site in uda_tpu/ must be listed here (kind, what it measures,
# labels if any). scripts/check_metrics_names.py greps the call sites
# and fails on names missing from this table.
METRICS_REGISTRY: Dict[str, tuple] = {
    # -- counters: fetch path (reduce side) ------------------------------
    "fetch.bytes": ("counter", "record bytes fetched [labels: supplier]"),
    "fetch.chunks": ("counter", "chunks fetched [labels: supplier]"),
    "fetch.retries": ("counter", "whole-segment re-fetches after a "
                                 "transport fault [labels: supplier]"),
    "fetch.timeouts": ("counter", "per-attempt fetch timeouts "
                                  "[labels: supplier]"),
    "fetch.stale_completions": ("counter", "completions dropped as stale "
                                           "(superseded attempt epoch)"),
    "fetch.backoff_seconds": ("counter", "seconds spent in retry backoff"),
    "fetch.deadline_exceeded": ("counter", "segments abandoned at the "
                                           "per-segment deadline"),
    "fetch.failed_admin": ("counter", "segments administratively failed "
                                      "(watchdog rescue / stop drain)"),
    "fetch.crc_mismatch": ("counter", "chunk CRC validation failures"),
    "fetch.crc_refetch": ("counter", "single-chunk CRC re-fetches"),
    "fetch.penalties": ("counter", "suppliers boxed after repeated "
                                   "faults [labels: supplier]"),
    "fetch.deprioritized": ("counter", "schedule rotations past a boxed "
                                       "supplier"),
    # -- counters: survivable shuffle (speculation / resume / coding) ----
    "fetch.speculated": ("counter", "straggler chunks that got a "
                                    "speculative duplicate fetch "
                                    "[labels: supplier (the alternate "
                                    "source)]"),
    "fetch.speculation.won": ("counter", "speculative duplicates that "
                                         "completed first (the segment "
                                         "switches to the faster "
                                         "source) [labels: supplier]"),
    "fetch.speculation.lost": ("counter", "speculative duplicates the "
                                          "primary beat (the loser's "
                                          "completion is discarded as "
                                          "stale)"),
    "fetch.resumed": ("counter", "transport retries that kept the "
                                 "offset ledger and resumed "
                                 "mid-partition (uda.tpu.fetch.resume) "
                                 "[labels: supplier]"),
    "fetch.resumed.bytes": ("counter", "already-served bytes a resumed "
                                       "retry did NOT refetch"),
    "fetch.resume.invalidated": ("counter", "resumed fetches whose "
                                            "first chunk failed the "
                                            "partition-identity check "
                                            "(full restart from zero)"),
    "coding.recover.attempts": ("counter", "segments that entered the "
                                           "k-of-n reconstruction rung "
                                           "after exhausting retries "
                                           "[labels: supplier (the "
                                           "failed primary)]"),
    "coding.recover.failures": ("counter", "reconstructions that failed "
                                           "(fewer than k chunks "
                                           "reachable, or decode "
                                           "error)"),
    "coding.reconstructed.partitions": ("counter", "partitions rebuilt "
                                        "from stripe chunks instead of "
                                        "the dead/penalized primary"),
    "coding.reconstructed.bytes": ("counter", "on-disk partition bytes "
                                              "produced by the RS "
                                              "decoder"),
    "coding.shard.fetches": ("counter", "stripe shard streams fetched "
                                        "to completion [labels: "
                                        "supplier]"),
    "coding.shard.failures": ("counter", "stripe shard streams that "
                                         "failed (next candidate is "
                                         "promoted) [labels: "
                                         "supplier]"),
    "fallback.signals": ("counter", "terminal engine failures converted "
                                    "to FallbackSignal"),
    # -- counters: memory admission / pressure response ------------------
    "budget.admitted": ("counter", "admission decisions that kept the "
                                   "requested path (utils/budget.py)"),
    "budget.rerouted": ("counter", "over-budget tasks rerouted to a "
                                   "bounded path (streaming / shrunken "
                                   "window)"),
    "budget.rejected": ("counter", "tasks refused before allocation "
                                   "(hard ceiling / unfittable INIT)"),
    "watchdog.stalls": ("counter", "stall-watchdog firings (diagnostic "
                                   "dump + optional fallback)"),
    "arena.pressure_events": ("counter", "arena acquires that waited "
                                         "past the soft-pressure "
                                         "threshold"),
    "supplier.admission.rejections": ("counter", "ShuffleRequests "
                                      "rejected by the read-pool "
                                      "admission budget"),
    # -- counters: error accounting / lock discipline --------------------
    "errors.swallowed": ("counter", "exceptions intentionally absorbed "
                                    "by a best-effort path (every such "
                                    "site logs too; udalint UDA006 "
                                    "forbids silent swallows)"),
    "lockdep.cycles": ("counter", "lock-order cycles (potential "
                                  "deadlocks) detected by the runtime "
                                  "validator (utils/locks.py, "
                                  "UDA_TPU_LOCKDEP=1)"),
    "racedet.races": ("counter", "data races (shared-modified field "
                                 "with an empty candidate lockset) "
                                 "detected by the runtime Eraser "
                                 "machine (utils/locks.py, "
                                 "UDA_TPU_RACEDET=1)"),
    "resledger.leaks": ("counter", "obligations (leases, fd pins, "
                                   "admission charges, paired-gauge "
                                   "increments) still open at a drain "
                                   "point (utils/resledger.py, "
                                   "UDA_TPU_RESLEDGER=1)"),
    # -- counters: supplier / emit / merge / exchange --------------------
    "supplier.bytes": ("counter", "bytes served by the DataEngine"),
    "emit.bytes": ("counter", "framed bytes handed to the consumer"),
    "merge.records": ("counter", "records through the merge "
                                 "(staged or device-merged)"),
    "spool.bytes": ("counter", "bytes spooled to sorted run files "
                               "(streaming online mode)"),
    # -- counters: staging pipeline (merger/overlap stage pool) ----------
    "stage.bytes": ("counter", "record content bytes through the "
                               "staging path (pack + row build)"),
    "stage.backpressure_events": ("counter", "feed() calls that blocked "
                                            "on the in-flight staging "
                                            "byte budget "
                                            "(uda.tpu.stage.inflight.mb)"),
    "stage.buffer.reuses": ("counter", "row-matrix builds served from "
                                       "the pre-allocated host buffer "
                                       "pool instead of a fresh "
                                       "allocation"),
    "merge.pipeline.runs": ("counter", "staged runs consumed by the "
                                       "pipeline's merge consumer "
                                       "(device_put overlapped with "
                                       "the previous run's merges)"),
    "merge.pipeline.two_phase": ("counter", "non-overlapped merges "
                                            "routed to the two-phase "
                                            "device sort (partial "
                                            "sort + HBM merge tree) "
                                            "instead of the "
                                            "concatenation re-sort"),
    "exchange.rounds": ("counter", "all-to-all exchange rounds executed"),
    "exchange.rounds.skipped": ("counter", "planned exchange windows the "
                                           "host round planner dropped "
                                           "because no device had "
                                           "in-window records"),
    "exchange.ici.bytes": ("counter", "record bytes the round planner "
                                      "routed over intra-pod ICI links "
                                      "(off-device rows; hierarchical "
                                      "mode includes the egress/"
                                      "delivery staging hops)"),
    "exchange.dcn.bytes": ("counter", "record bytes crossing a pod "
                                      "boundary over DCN [labels: pod "
                                      "(source pod)]"),
    "exchange.dcn.messages": ("counter", "per-round DCN transfers: "
                                         "cross-pod (src, dst) device "
                                         "pairs with traffic (flat "
                                         "exchange) vs coalesced pod "
                                         "pairs (hierarchical) [labels: "
                                         "pod (source pod)]"),
    "exchange.dcn.coded.bytes": ("counter", "multicast-model DCN charge "
                                            "of coded windows: one "
                                            "L-row coded packet per "
                                            "pod pair serving every "
                                            "member reducer (equals "
                                            "the window's exchange."
                                            "dcn.bytes when coded) "
                                            "[labels: pod (source "
                                            "pod)]"),
    "exchange.dcn.saved.bytes": ("counter", "DCN payload bytes the "
                                            "coded stage B removed vs "
                                            "the plain coalesced tile "
                                            "(invariant: coded + "
                                            "saved == the uncoded "
                                            "payload) [labels: pod "
                                            "(source pod)]"),
    "exchange.decode.fallbacks": ("counter", "coded windows whose "
                                             "decode failed (failpoint "
                                             "exchange.decode) and "
                                             "completed byte-correct "
                                             "on the plain coalesced "
                                             "tile"),
    "coding.scrub.stripes": ("counter", "map-output stripes whose "
                                        "parity section was verified "
                                        "against the data region by "
                                        "the background scrub"),
    "coding.scrub.repairs": ("counter", "lost/corrupt stripe shards "
                                        "the scrub rebuilt (repair "
                                        "mode) or reported (dump-only "
                                        "default)"),
    "decompress.bytes": ("counter", "uncompressed bytes produced by the "
                                    "decompressing fetch client"),
    # -- counters: network data plane (uda_tpu/net/) ---------------------
    "net.accepts": ("counter", "connections accepted by the shuffle "
                               "server"),
    "net.requests": ("counter", "REQ frames handed to the engine by "
                                "the server"),
    "net.errors": ("counter", "typed ERR frames completed to clients"),
    "net.bytes.in": ("counter", "wire bytes received [labels: role="
                                "server|client]"),
    "net.bytes.out": ("counter", "wire bytes sent [labels: role="
                                 "server|client]"),
    "net.connects": ("counter", "client connections established "
                                "[labels: host]"),
    "net.connect.failures": ("counter", "client dials that failed "
                                        "[labels: host]"),
    "net.disconnects": ("counter", "connections torn down on error/"
                                   "EOF/torn frame [labels: role]"),
    "net.frames.orphaned": ("counter", "frames for no-longer-pending "
                                       "request ids (stale epoch)"),
    "net.serve.fd": ("counter", "DATA responses served zero-copy from "
                                "the fd cache via os.sendfile (event-"
                                "loop core)"),
    "net.serve.copy": ("counter", "DATA responses served through the "
                                  "byte path (CRC on, pread failpoint "
                                  "armed, zerocopy off, or sendfile "
                                  "fallback)"),
    "net.sendfile.bytes": ("counter", "chunk bytes that went disk->"
                                      "socket via os.sendfile without "
                                      "transiting the Python heap"),
    "net.mmap.bytes": ("counter", "chunk bytes that went page-cache->"
                                  "socket via sendmsg over the MOF's "
                                  "mmap (the zerocopy mmap mode) "
                                  "without transiting the Python "
                                  "heap"),
    "net.generation.changes": ("counter", "reconnects that observed a "
                                          "DIFFERENT server generation "
                                          "in the accept banner (a "
                                          "supplier restart) [labels: "
                                          "host, warm]"),
    "net.handoff.persisted": ("counter", "handoff records written by "
                                         "stop(drain=True)"),
    "net.handoff.loaded": ("counter", "warm restarts that resumed a "
                                      "persisted handoff record "
                                      "(generation continuity)"),
    "net.stats.requests": ("counter", "MSG_STATS introspection "
                                      "snapshots served to remote "
                                      "peers (uncredited, like the "
                                      "HELLO banner)"),
    "flightrec.dumps": ("counter", "flight-recorder black-box dumps "
                                   "written (FallbackSignal, stall, "
                                   "resledger leak — "
                                   "utils/flightrec.py)"),
    # -- counters: batched host-I/O plane (mofserver/data_engine.py) -----
    "io.batch.submits": ("counter", "request batches handed to the "
                                    "DataEngine batch worker (one pool "
                                    "handoff each, however many chunks "
                                    "ride it)"),
    "io.batch.requests": ("counter", "ShuffleRequests served through "
                                     "the batched read plane"),
    "io.batch.reads": ("counter", "kernel read operations the batch "
                                  "plane issued (coalesced vectored "
                                  "reads / native batch submits) — "
                                  "the O(files)-not-O(chunks) figure "
                                  "[labels: backend]"),
    "io.coalesce.runs": ("counter", "coalesced runs built from "
                                    "adjacent/near-adjacent request "
                                    "ranges (each is one vectored "
                                    "read)"),
    "io.coalesce.gap.bytes": ("counter", "gap bytes read into scratch "
                                         "and discarded to merge "
                                         "near-adjacent ranges "
                                         "(uda.tpu.read.coalesce."
                                         "gap.kb)"),
    "io.backend": ("counter", "batch-read backend rung selected at "
                              "engine construction (the io_uring -> "
                              "preadv -> pread fallback ladder) "
                              "[labels: backend]"),
    "io.native.unavailable": ("counter", "DataEngine constructions "
                                         "that wanted the native "
                                         "reader but fell back to "
                                         "os.pread (warned once per "
                                         "process, counted every "
                                         "time)"),
    # -- counters: online tuning cache (utils/tuncache.py) ---------------
    "tune.cache.hits": ("counter", "routing decisions served from a "
                                   "persisted fly-off winner "
                                   "[labels: domain]"),
    "tune.cache.misses": ("counter", "routing decisions that found no "
                                     "cached winner (built-in "
                                     "defaults used) [labels: domain]"),
    "tune.cache.invalid": ("counter", "tuning-cache files ignored as "
                                      "corrupt/truncated/version-"
                                      "bumped (never fatal)"),
    "tune.cache.writes": ("counter", "winner records persisted to the "
                                     "tuning cache"),
    "tune.probes": ("counter", "fly-off probes executed "
                               "(scripts/tune_probe.py) "
                               "[labels: domain]"),
    "tune.reprobes": ("counter", "stale winners re-measured by the "
                                 "background re-probe rung"),
    # -- counters: multi-tenant service plane (uda_tpu/tenant/) ----------
    "tenant.registered": ("counter", "jobs registered in the tenant "
                                     "registry (MSG_JOB) [labels: "
                                     "tenant]"),
    "tenant.retired": ("counter", "jobs retired [labels: tenant]"),
    "tenant.heartbeats": ("counter", "registry heartbeats (repeat "
                                     "MSG_JOB at the same epoch)"),
    "tenant.epoch.fenced": ("counter", "registrations that superseded "
                                       "a lower epoch (the restarted-"
                                       "job fence)"),
    "tenant.expired": ("counter", "idle jobs dropped by the TTL sweep "
                                  "(uda.tpu.tenant.ttl.s)"),
    "tenant.rejected": ("counter", "registry refusals -> typed "
                                   "TenantError [labels: cause="
                                   "unknown|retired|stale_epoch|auth|"
                                   "capacity]"),
    "tenant.bind.errors": ("counter", "client-side MSG_JOB refusals "
                                      "(fire-and-forget binds whose "
                                      "reply was a typed ERR)"),
    "tenant.sched.grants": ("counter", "credits granted by the "
                                       "weighted-fair scheduler "
                                       "[labels: tenant]"),
    "tenant.sched.parked": ("counter", "requests parked in a tenant's "
                                       "WDRR queue (no credit at "
                                       "arrival)"),
    "tenant.penalties": ("counter", "tenants penalty-boxed by the "
                                    "scheduler (repeated faults) "
                                    "[labels: tenant]"),
    "tenant.admission.rejections": ("counter", "ShuffleRequests "
                                    "rejected by a TENANT's read-"
                                    "budget share (the global "
                                    "supplier.admission.rejections "
                                    "also advances) [labels: tenant]"),
    # -- counters: crash-consistent checkpoints (merger/checkpoint.py) ---
    "ckpt.snapshots": ("counter", "checkpoint manifests durably "
                                  "written (one per successful save)"),
    "ckpt.bytes": ("counter", "bytes written by checkpoint saves "
                              "(manifest + ledger part files; run "
                              "files are spooled by the RunStore and "
                              "charged to stage.bytes, not here)"),
    "ckpt.save.errors": ("counter", "checkpoint saves that failed and "
                                    "were absorbed (best-effort "
                                    "contract: the task continues on "
                                    "its previous resume point)"),
    "ckpt.resumed": ("counter", "reduce tasks that resumed from a "
                                "checkpoint manifest instead of "
                                "starting fresh"),
    "ckpt.runs.adopted": ("counter", "checkpointed run files adopted "
                                     "on resume (CRC-verified, re-"
                                     "joined the merge forest with "
                                     "zero refetch)"),
    "ckpt.invalidated": ("counter", "checkpoint state dropped by the "
                                    "revalidation ladder [labels: "
                                    "cause=load|torn|epoch|maps|crc|"
                                    "generation|ledger]"),
    # -- counters: time-accounting plane (profiler + critpath) -----------
    "profile.samples": ("counter", "sampling-profiler stack samples, "
                                   "attributed to the sampled thread's "
                                   "active span (utils/profiler.py) "
                                   "[labels: span]"),
    "profile.ticks": ("counter", "sampling-profiler wakeups (one walk "
                                 "of sys._current_frames per tick)"),
    "critpath.analyses": ("counter", "critical-path/time-accounting "
                                     "analyses computed over the span "
                                     "tree (utils/critpath.py)"),
    # -- gauges ----------------------------------------------------------
    "fetch.on_air": ("gauge", "fetch attempts currently in flight "
                              "(reference AIO on-air counter)"),
    "supplier.reads.on_air": ("gauge", "DataEngine reads currently "
                                       "queued or executing"),
    "arena.slots_in_use": ("gauge", "staging-arena slots currently "
                                    "acquired"),
    "supplier.read.bytes.on_air": ("gauge", "ShuffleRequest bytes "
                                           "queued or being read "
                                           "(the admission level)"),
    "net.server.connections": ("gauge", "shuffle-server connections "
                                        "currently open"),
    "net.client.connections": ("gauge", "RemoteFetchClient connections "
                                        "currently open"),
    "net.server.inflight": ("gauge", "requests inside the server "
                                     "pipeline (engine + outbound "
                                     "queue; bounded per conn by "
                                     "mapred.rdma.wqe.per.conn)"),
    "net.server.generation": ("gauge", "this process's shuffle-server "
                                       "generation (advertised in the "
                                       "accept banner; warm restarts "
                                       "increment the persisted one)"),
    "stage.inflight.bytes": ("gauge", "bytes fed to the overlap merger "
                                      "but not yet merged/spooled (the "
                                      "staging-pipeline admission "
                                      "level; bounded by "
                                      "uda.tpu.stage.inflight.mb)"),
    "io.batch.inflight": ("gauge", "requests inside the batched read "
                                   "plane (submitted to a batch "
                                   "worker, future not yet resolved); "
                                   "paired — every +1 must meet its "
                                   "-1 at settlement"),
    "tenant.read.bytes.on_air": ("gauge", "tenant-stamped admission "
                                          "bytes queued or being read "
                                          "(the per-tenant partition "
                                          "level; paired — the "
                                          "unlabeled total rides the "
                                          "ledger, the tenant series "
                                          "is observability) [labels: "
                                          "tenant]"),
    "tenant.jobs.active": ("gauge", "active jobs in the tenant "
                                    "registry (absolute, set at "
                                    "register/retire — not paired)"),
    "tenant.sched.backlog": ("gauge", "requests parked across every "
                                      "tenant's WDRR queue (absolute, "
                                      "set at each grant sweep — not "
                                      "paired)"),
    "profile.hz": ("gauge", "sampling-profiler rate currently armed "
                            "(0 = off; set absolutely at start/stop, "
                            "deliberately NOT a paired gauge — the "
                            "profiler is process-scoped, not an "
                            "obligation)"),
    # -- histograms (recorded only while stats are enabled) --------------
    "fetch.latency_ms": ("histogram", "per-chunk fetch latency "
                                      "[labels: supplier, tenant — "
                                      "tenant stamped when the "
                                      "process carries an identity]"),
    "fetch.chunk.bytes": ("histogram", "fetched chunk sizes [labels: "
                                       "tenant when stamped]"),
    "supplier.read.latency_ms": ("histogram", "DataEngine chunk read+"
                                              "resolve latency [labels:"
                                              " tenant when the "
                                              "request is tenant-"
                                              "stamped]"),
    "merge.wait_ms": ("histogram", "how long the merge waited for a "
                                   "run to become mergeable after its "
                                   "segment was fed (queue wait + "
                                   "decompress tail + pack + spool) — "
                                   "the device-starvation signal; its "
                                   "complement is the feed() "
                                   "backpressure block "
                                   "(stage.backpressure_events)"),
    "merge.pipeline.put_ms": ("histogram", "merge-consumer wait for a "
                                           "jax.device_put transfer to "
                                           "release its leased host "
                                           "buffer (the pipeline's one "
                                           "per-run accounting block)"),
    "net.frame.latency_ms": ("histogram", "request->response frame "
                                          "latency [labels: role — "
                                          "server: REQ read to reply "
                                          "written; client: request "
                                          "sent to completion "
                                          "dispatched]"),
    "ckpt.save_ms": ("histogram", "wall time of one checkpoint save "
                                  "(collect + part files + manifest "
                                  "write + fsync + prune) — the "
                                  "snapshot-overhead signal perfwatch "
                                  "gates on"),
    # -- the live telemetry plane (ISSUE 17) -----------------------------
    "ts.listener.errors": ("counter", "rollup-listener callbacks "
                                      "(anomaly detectors, SLI book) "
                                      "that raised — the one timer "
                                      "keeps ticking for the others"),
    "anomaly.fired": ("counter", "anomalies fired (inactive->active "
                                 "edges across every detector; the "
                                 "per-kind anomaly.<kind> family "
                                 "carries the labeled breakdown)"),
    "anomaly.throughput": ("counter", "throughput-collapse detections "
                                      "[labels: key — the collapsed "
                                      "counter]"),
    "anomaly.p99": ("counter", "p99-inflation detections [labels: key "
                               "— the inflated histogram]"),
    "anomaly.leak": ("counter", "gauge leak-slope detections [labels: "
                                "key — the rising gauge]"),
    "anomaly.starvation": ("counter", "tenant-starvation detections "
                                      "(the WDRR fairness audit's "
                                      "alarm) [labels: key — the "
                                      "starved tenant]"),
    "anomaly.dumps": ("counter", "proactive flight-recorder dumps "
                                 "(cause=anomaly, rate-limited by "
                                 "uda.tpu.anomaly.dump.interval.s)"),
    "sli.slo.breach": ("counter", "per-interval SLO compliance misses "
                                  "[labels: tenant, sli]"),
    "tenant.queue.wait_ms": ("histogram", "parked time of a WDRR-"
                                          "queued request, enqueue to "
                                          "grant (the queue-wait SLI) "
                                          "[labels: tenant]"),
    # -- the elastic disaggregated MOF store (mofserver/store.py) --------
    "store.read.bytes": ("counter", "bytes served through the store "
                                    "router [labels: backend]"),
    "store.blob.reads": ("counter", "blob-tier vectored read syscalls "
                                    "(the PR 13 coalescer riding the "
                                    "blob range-GET path)"),
    "store.errors": ("counter", "store-tier read/put faults (typed "
                                "StoreError; the failover router's "
                                "input) [labels: backend]"),
    "store.failover": ("counter", "reads served by the SURVIVING tier "
                                  "after the partition's primary tier "
                                  "faulted or was boxed [labels: "
                                  "backend — the tier that served]"),
    "store.rerouted": ("counter", "reads proactively routed around a "
                                  "penalty-boxed tier (no failed "
                                  "attempt burned) [labels: backend — "
                                  "the boxed tier]"),
    "store.penalties": ("counter", "store backends penalty-boxed after "
                                   "repeated faults (BackendHealth) "
                                   "[labels: backend]"),
    "store.migrations": ("counter", "whole-partition tier migrations "
                                    "completed [labels: reason="
                                    "spill|drain|replicate]"),
    "store.migrated.bytes": ("counter", "MOF bytes moved between tiers "
                                        "(CRC-verified streamed "
                                        "copies)"),
    "store.spilled.bytes": ("counter", "migrated bytes attributed to "
                                       "the retention-watermark spill "
                                       "ladder (the bounded-RSS "
                                       "contract's ledger)"),
    "store.drained.partitions": ("counter", "partitions migrated off a "
                                            "departing supplier by the "
                                            "drain handoff"),
    "store.revalidated": ("counter", "spilled blob objects CRC-"
                                     "re-verified by the checkpoint-"
                                     "resume locator revalidation"),
    "elastic.joins": ("counter", "suppliers that joined mid-job "
                                 "(CAP_ELASTIC HELLO; in-flight "
                                 "segments adopt them as speculation/"
                                 "replica candidates) [labels: "
                                 "supplier]"),
    "elastic.drains": ("counter", "suppliers that announced departure "
                                  "(CAP_DRAINING HELLO / server "
                                  "announce_drain)"),
    "store.local.retained.bytes": ("gauge", "MOF bytes retained on the "
                                           "local tier and counted "
                                           "against the spill "
                                           "watermark (absolute "
                                           "level, not paired)"),
    "store.migrate.bytes.on_air": ("gauge", "bytes mid-migration "
                                           "between store tiers; "
                                           "paired — every +N must "
                                           "meet its -N at migration "
                                           "settle (resledger "
                                           "gauge.store.migrate)"),
    "store.read.latency_ms": ("histogram", "store-router range-read "
                                           "latency per tier attempt "
                                           "[labels: backend]"),
    # -- push plane (ISSUE 19, uda_tpu/net/push.py) ----------------------
    "push.commits": ("counter", "map commits announced to the push "
                                "scheduler (MOFWriter on_commit)"),
    "push.subs": ("counter", "MSG_PUSH_SUB subscriptions accepted"),
    "push.chunks": ("counter", "MSG_PUSH chunks sent (supplier side)"),
    "push.bytes": ("counter", "MSG_PUSH payload bytes sent"),
    "push.acks": ("counter", "pushes the receiver accepted (PUSH_ACK)"),
    "push.nacks": ("counter", "pushes the receiver refused "
                              "[labels: reason]"),
    "push.errors": ("counter", "push chunk reads/encodes that failed "
                               "supplier-side (partition -> pull-only)"),
    "push.accepted": ("counter", "pushed chunks admitted into staging "
                                 "[labels: tier]"),
    "push.accepted.bytes": ("counter", "pushed bytes admitted into "
                                       "staging"),
    "push.refused": ("counter", "pushed chunks refused by the staging "
                                "admission ladder [labels: reason]"),
    "push.spilled.bytes": ("counter", "staged push bytes diverted to "
                                      "the spill tier"),
    "push.adopted": ("counter", "segments that started from a staged "
                                "push prefix (ckpt_preload adoption)"),
    "push.adopted.bytes": ("counter", "staged bytes adopted into "
                                      "segment offset ledgers"),
    "push.invalidated": ("counter", "staged push prefixes that failed "
                                    "re-crack/preload validation "
                                    "(degraded to a fresh fetch)"),
    "push.dial.failures": ("counter", "eager push-subscription dials "
                                      "that failed [labels: supplier]"),
    "push.on_air": ("gauge", "un-ACKed MSG_PUSH chunks in flight; "
                             "paired — every +1 must meet its -1 at "
                             "ACK/NACK/error/conn-drop (resledger "
                             "gauge.push.on_air)"),
    "push.staged.bytes": ("gauge", "bytes staged reduce-side awaiting "
                                   "adoption; paired — every +N must "
                                   "meet its -N at take()/close() "
                                   "(resledger gauge.push.staged)"),
}

# Dynamically-named families (f-string call sites): the static prefix
# must be listed here.
REGISTRY_PREFIXES = ("failpoint.", "anomaly.")

# The span-name registry: every literal name passed to
# ``metrics.start_span``/``metrics.span`` must be listed here (udalint
# UDA009 — the span contract of UDA002's metrics-name rule). Spans are
# cross-PROCESS identifiers since the wire carries (trace_id,
# parent_span_id) on REQ/SIZE_REQ frames, so a typo'd name is not just
# an ugly trace: it breaks scripts/trace_merge.py's stitching and any
# dashboard keying on the inventory below. Timer spans
# (``metrics.timer``) are named by their timer counter and documented
# at the call site; they are not part of this literal-name inventory.
SPAN_REGISTRY: Dict[str, str] = {
    "reduce_task": "root of one reduce task's trace tree "
                   "(merger/merge_manager.py)",
    "fetch.segment": "one partition's whole fetch, child of "
                     "reduce_task (merger/segment.py)",
    "net.fetch": "one chunk request on the wire, reduce side "
                 "(net/client.py); its (trace, span) ids ride the REQ "
                 "frame",
    "net.size_probe": "partition size probe over the wire "
                      "(net/client.py)",
    "net.serve": "one REQ served, supplier side (net/server.py); "
                 "adopts the wire-carried trace context so it is a "
                 "child of the remote net.fetch",
    "net.stats": "one MSG_STATS introspection poll, client side "
                 "(net/client.py)",
    "net.job_bind": "one MSG_JOB tenant registration round trip, "
                    "client side (net/client.py)",
    "engine.pread": "one DataEngine chunk read/plan, child of the "
                    "serve (or local fetch) span "
                    "(mofserver/data_engine.py)",
    "engine.read_batch": "one batched read submission: per-fd "
                         "grouping + coalescing + vectored reads for "
                         "a whole request burst on one pool worker "
                         "(mofserver/data_engine.py submit_batch); "
                         "per-request engine.pread children adopt "
                         "each request's own serve span",
    "merge.wait": "the overlap merge consumer blocked waiting for the "
                  "next staged run (merger/overlap.py); the span twin "
                  "of the merge.wait_ms histogram — critpath's 'wait' "
                  "bucket",
    "merge.device_put": "host->device transfer of one staged run plus "
                        "the buffer-recycle completion wait "
                        "(merger/overlap.py); critpath's 'device_put' "
                        "bucket",
}

# snapshot() aliases for the reference's per-reduce-task aggregate trio
# (reducer.h:80-90): alias name -> source timer counter.
PARITY_ALIASES = {
    "total_wait_mem_time": "wait_mem_time",
    "total_fetch_time": "fetch_time",
    "total_merge_time": "merge_time",
}

# Fixed histogram buckets: powers of two from 1/16 to 2^30, shared by
# every histogram (latencies in ms and sizes in bytes both fit; fixed
# buckets keep observe() O(log buckets) with no per-histogram config).
_BUCKET_EDGES = tuple(float(2.0 ** e) for e in range(-4, 31))


class _Hist:
    """One fixed-bucket histogram series (caller holds the metrics
    lock)."""

    __slots__ = ("counts", "count", "total", "vmin", "vmax")

    def __init__(self) -> None:
        self.counts = [0] * (len(_BUCKET_EDGES) + 1)
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(_BUCKET_EDGES, value)] += 1
        self.count += 1
        self.total += value
        self.vmin = min(self.vmin, value)
        self.vmax = max(self.vmax, value)

    def percentile(self, p: float) -> float:
        """Bucket-interpolated percentile estimate (exact min/max at the
        tails; linear within the containing bucket)."""
        if self.count == 0:
            return 0.0
        target = self.count * p / 100.0
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= target:
                lo = _BUCKET_EDGES[i - 1] if i > 0 else 0.0
                hi = (_BUCKET_EDGES[i] if i < len(_BUCKET_EDGES)
                      else self.vmax)
                frac = (target - seen) / c
                return min(max(lo + (hi - lo) * frac, self.vmin), self.vmax)
            seen += c
        return self.vmax

    def summary(self) -> Dict[str, float]:
        if self.count == 0:
            return {"count": 0, "sum": 0.0}
        # "buckets": the non-empty bucket boundaries+counts as
        # [upper_edge, count] pairs (upper_edge None = the overflow
        # bucket past 2^30), so exported summaries carry enough to
        # recompute ARBITRARY percentiles offline
        # (percentile_from_summary — perfwatch/critpath consume it);
        # p50/p95/p99 stay inline for existing consumers
        buckets = [[(_BUCKET_EDGES[i] if i < len(_BUCKET_EDGES)
                     else None), c]
                   for i, c in enumerate(self.counts) if c]
        return {"count": self.count, "sum": self.total,
                "min": self.vmin, "max": self.vmax,
                "p50": self.percentile(50), "p95": self.percentile(95),
                "p99": self.percentile(99), "buckets": buckets}


def percentile_from_summary(summary: Dict, p: float) -> float:
    """Recompute an arbitrary percentile OFFLINE from an exported
    histogram summary's ``buckets`` boundaries+counts — the exact
    estimator :meth:`_Hist.percentile` runs live, so perfwatch and
    critpath read the same numbers from a BENCH_*.json telemetry block
    that a live poll would have returned. Returns 0.0 for an empty or
    bucket-less summary (a pre-bucket export degrades to its inline
    p50/p95/p99 only)."""
    count = summary.get("count", 0)
    buckets = summary.get("buckets")
    if not count or not buckets:
        return 0.0
    vmin = summary.get("min", 0.0)
    vmax = summary.get("max", 0.0)
    target = count * p / 100.0
    seen = 0
    for le, c in buckets:
        if seen + c >= target:
            if le is None:  # the overflow bucket past the last edge
                lo, hi = _BUCKET_EDGES[-1], vmax
            else:
                i = bisect.bisect_left(_BUCKET_EDGES, le)
                lo = _BUCKET_EDGES[i - 1] if i > 0 else 0.0
                hi = le
            frac = (target - seen) / c
            return min(max(lo + (hi - lo) * frac, vmin), vmax)
        seen += c
    return vmax


def _series_key(name: str, labels: dict) -> str:
    """Stable series key: ``name{k=v,...}`` with sorted label keys."""
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


# -- thread -> active span registry (the sampling profiler's view) -----------
# The contextvar above is readable only from its own thread; the
# sampling profiler (utils/profiler.py) attributes another thread's
# stack samples, so span()/use_span() ALSO mirror the current span into
# this plain dict — but only while a profiler has asked for it
# (enable_thread_span_registry), keeping the unprofiled span path at
# one module-global check. Dict get/set/del are GIL-atomic; the sampler
# reads racily by design (a sample landing one span early/late is
# sampling noise, not corruption).
_THREAD_SPANS: Dict[int, "Span"] = {}
_THREAD_REG_ON = False


def enable_thread_span_registry(on: bool) -> None:
    global _THREAD_REG_ON
    _THREAD_REG_ON = bool(on)
    if not on:
        _THREAD_SPANS.clear()


def active_span_of_thread(tid: int) -> Optional["Span"]:
    """The span currently adopted by thread ``tid`` (None when the
    thread runs outside any span, or the registry is off)."""
    return _THREAD_SPANS.get(tid)


_current_span: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("uda_tpu_current_span", default=None)


class Span:
    """One span of the trace tree. ``end()`` records it (idempotent);
    attributes may be added at end time (e.g. error status). A span is
    safe to end from a different thread than the one that started it —
    the recorded ``tid`` is the *starting* thread (that's the lane the
    work queued on)."""

    __slots__ = ("_metrics", "name", "trace_id", "span_id", "parent_id",
                 "t0", "attrs", "tid", "_ended", "chain")

    def __init__(self, metrics_obj: "Metrics", name: str,
                 trace_id: int, span_id: int, parent_id: Optional[int],
                 attrs: dict, chain: tuple = ()):
        self._metrics = metrics_obj
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = time.perf_counter()
        self.attrs = attrs
        self.tid = threading.get_ident()
        self._ended = False
        # root->self name chain: lets the profiler charge a sample to
        # every enclosing span ("total" attribution) without needing
        # live parent object references
        self.chain = chain or (name,)

    def end(self, **attrs) -> None:
        if self._ended:
            return
        self._ended = True
        dur = time.perf_counter() - self.t0
        if attrs:
            self.attrs.update(attrs)
        self._metrics._record_span(self, dur)


class _NoopSpan:
    """Returned by start_span while spans are disabled: absorbing
    end()/attrs at zero recording cost."""

    __slots__ = ()
    name = ""
    trace_id = span_id = parent_id = None
    attrs: dict = {}
    chain: tuple = ()

    def end(self, **attrs) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


class _RemoteParent:
    """A parent that lives in ANOTHER process: the (trace_id,
    parent_span_id) pair a REQ/SIZE_REQ frame carried over the wire
    (uda_tpu/net/wire.py). Quacks enough like a Span for
    ``start_span(parent=...)`` — the supplier-side serve span then
    joins the reduce-side fetch span's tree, and
    ``scripts/trace_merge.py`` stitches the two processes' span files
    on exactly these ids."""

    __slots__ = ("trace_id", "span_id")
    parent_id = None
    attrs: dict = {}

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id


class Metrics:
    """Process-wide metrics hub. Counters and gauges are always live
    (two dict writes under one lock); histograms and spans cost nothing
    until enabled."""

    def __init__(self, stats: Optional[bool] = None,
                 ledger=None) -> None:
        # lockdep-tracked (utils/locks.py): the metrics hub is a LEAF
        # lock — every layer counts under its own locks, so an edge
        # OUT of "metrics" would itself be a design smell
        self._lock = TrackedLock("metrics")
        # the ResourceLedger mirroring paired gauges (utils/resledger):
        # only the global hub carries one — private Metrics() fixtures
        # must never feed the process-wide obligation books
        self._ledger = ledger
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[str, float] = {}
        self.histograms: Dict[str, _Hist] = {}
        self.spans: list[dict] = []
        # construction-time default, restored by reset(): the global
        # instance takes it from UDA_TPU_STATS so a whole process can be
        # switched on from the environment
        self._default_stats = (stats_enabled_from_env() if stats is None
                               else bool(stats))
        self._hist_enabled = self._default_stats
        self._spans_enabled = self._default_stats
        self._next_id = 0
        # span/trace ids must be unique ACROSS processes (they cross
        # the wire and are merged by scripts/trace_merge.py): ids are
        # base + counter with a random per-process 32-bit base in the
        # high half of a u64 — collisions between two processes of one
        # job are 2^-32-grade, and ids still fit the wire's u64 fields
        self._id_base = int.from_bytes(os.urandom(4), "big") << 32
        # wall-clock anchor: spans record perf_counter() timestamps
        # (monotonic, process-local); exports convert through this
        # (wall, perf) pair so two processes' spans land on one
        # comparable timeline
        self._anchor = (time.time(), time.perf_counter())

    # -- enablement ---------------------------------------------------------

    def enable_stats(self) -> None:
        """Turn on the optional layers (histograms + spans). Idempotent."""
        self._hist_enabled = True
        self._spans_enabled = True

    def disable_stats(self) -> None:
        self._hist_enabled = False
        self._spans_enabled = False

    def enable_spans(self) -> None:
        """Idempotent: span recording on (histograms untouched)."""
        self._spans_enabled = True

    def disable_spans(self) -> None:
        self._spans_enabled = False

    @property
    def stats_enabled(self) -> bool:
        return self._hist_enabled

    @property
    def record_spans(self) -> bool:
        # legacy attribute-style toggle, kept as a property so existing
        # `m.record_spans = True` call sites still work
        return self._spans_enabled

    @record_spans.setter
    def record_spans(self, on: bool) -> None:
        self._spans_enabled = bool(on)

    # -- counters -----------------------------------------------------------

    def add(self, name: str, value: float = 1.0, **labels) -> None:
        """Accumulate a counter. With labels, BOTH the unlabeled total
        ``name`` and the series ``name{k=v,...}`` advance, so existing
        total-based assertions and dashboards keep working."""
        if labels:
            skey = _series_key(name, labels)
            with self._lock:
                self.counters[name] += value
                self.counters[skey] += value
        else:
            with self._lock:
                self.counters[name] += value

    # -- gauges -------------------------------------------------------------

    def gauge(self, name: str, value: float, **labels) -> None:
        """Set a gauge to an absolute level."""
        key = _series_key(name, labels) if labels else name
        with self._lock:
            self.gauges[key] = value

    def gauge_add(self, name: str, delta: float, **labels) -> None:
        """Adjust a gauge by ``delta`` (the on-air increment/decrement
        idiom of the reference's AIO counters). Paired gauges (the
        increment-must-meet-decrement set, resledger.PAIRED_GAUGES)
        additionally flow through the armed ResourceLedger, so a +1
        whose -1 never lands is reported with the +1's stack at the
        next drain point."""
        key = _series_key(name, labels) if labels else name
        with self._lock:
            self.gauges[key] = self.gauges.get(key, 0.0) + delta
        led = self._ledger
        if led is not None and led.enabled and not labels:
            led.note_gauge(name, delta)

    # -- histograms ---------------------------------------------------------

    def observe(self, name: str, value: float, **labels) -> None:
        """Record one histogram sample. No-op until stats are enabled —
        the disabled fast path is a single attribute check."""
        if not self._hist_enabled:
            return
        keys = [name]
        if labels:
            keys.append(_series_key(name, labels))
        with self._lock:
            for key in keys:
                h = self.histograms.get(key)
                if h is None:
                    h = self.histograms[key] = _Hist()
                h.observe(value)

    def histogram_summaries(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: h.summary() for k, h in self.histograms.items()}

    def percentile(self, name: str, p: float,
                   **labels) -> Optional[float]:
        """A live percentile estimate of one histogram series, or None
        when the series has no samples (stats disabled, or nothing
        observed yet) — callers degrade to their own floor. Used by the
        fetch straggler detector (SpeculationPolicy.threshold_ms)."""
        key = _series_key(name, labels) if labels else name
        with self._lock:
            h = self.histograms.get(key)
            if h is None or h.count == 0:
                return None
            return h.percentile(p)

    # -- spans --------------------------------------------------------------

    def _new_ids(self, parent: Optional[Span]) -> tuple[int, int, Optional[int]]:
        with self._lock:
            self._next_id += 1
            sid = self._id_base + self._next_id
        if parent is not None and parent.span_id is not None:
            return parent.trace_id, sid, parent.span_id
        return sid, sid, None  # root: trace id = own span id

    @staticmethod
    def remote_parent(trace_id: int, span_id: int):
        """Wrap a wire-carried (trace_id, parent_span_id) pair as a
        ``start_span(parent=...)`` argument — the supplier side of
        cross-process trace propagation. (The CLIENT side stamps its
        own request span's ids onto the frame, gated by the peer's
        CAP_TRACE — EvLoopFetchClient._trace_of — so there is
        deliberately no context-var convenience here that could bypass
        the capability gate.)"""
        return _RemoteParent(trace_id, span_id)

    def start_span(self, name: str, parent: Optional[Span] = None,
                   **attrs) -> Span:
        """Begin a span. ``parent`` defaults to the calling thread's
        current span (contextvar); pass an explicit parent to propagate
        the tree across threads (e.g. a transport completion thread
        ending work that a merge-thread span fathered). Returns a no-op
        span while recording is disabled."""
        if not self._spans_enabled:
            return _NOOP_SPAN
        if parent is None:
            parent = _current_span.get()
        trace_id, span_id, parent_id = self._new_ids(parent)
        chain = (parent.chain + (name,)
                 if isinstance(parent, Span) else (name,))
        return Span(self, name, trace_id, span_id, parent_id, attrs,
                    chain=chain)

    @contextlib.contextmanager
    def span(self, name: str, parent: Optional[Span] = None,
             **attrs) -> Iterator[Span]:
        """Context-managed span that also becomes the thread's current
        span for the duration, so nested spans/timers parent under it."""
        s = self.start_span(name, parent=parent, **attrs)
        if s is _NOOP_SPAN:
            yield s
            return
        token = _current_span.set(s)
        tid = prev = None
        if _THREAD_REG_ON:
            tid = threading.get_ident()
            prev = _THREAD_SPANS.get(tid)
            _THREAD_SPANS[tid] = s
        try:
            yield s
        finally:
            if tid is not None:
                if prev is not None:
                    _THREAD_SPANS[tid] = prev
                else:
                    _THREAD_SPANS.pop(tid, None)
            _current_span.reset(token)
            s.end()

    @contextlib.contextmanager
    def use_span(self, span: Optional[Span]) -> Iterator[None]:
        """Make an existing span the current one on THIS thread (without
        ending it on exit) — the cross-thread propagation shim: a worker
        adopts the span its work item was fathered under."""
        if span is None or isinstance(span, _NoopSpan) \
                or not self._spans_enabled:
            yield
            return
        token = _current_span.set(span)
        tid = prev = None
        if _THREAD_REG_ON:
            tid = threading.get_ident()
            prev = _THREAD_SPANS.get(tid)
            _THREAD_SPANS[tid] = span
        try:
            yield
        finally:
            if tid is not None:
                if prev is not None:
                    _THREAD_SPANS[tid] = prev
                else:
                    _THREAD_SPANS.pop(tid, None)
            _current_span.reset(token)

    def current_span(self) -> Optional[Span]:
        """The calling thread's innermost open span (None outside any)."""
        if not self._spans_enabled:
            return None
        return _current_span.get()

    def _record_span(self, span: Span, dur: float) -> None:
        rec = {"name": span.name, "ts": span.t0, "dur": dur,
               "tid": span.tid, "trace": span.trace_id, "id": span.span_id,
               "parent": span.parent_id}
        if span.attrs:
            rec["attrs"] = dict(span.attrs)
        with self._lock:
            if self._spans_enabled:  # disabled mid-flight: drop
                self.spans.append(rec)

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        """Phase timer: accumulates ``<name>_time`` seconds and (when
        spans are on) records a span parented under the thread's current
        span."""
        if self._spans_enabled:
            with self.span(name):
                t0 = time.perf_counter()
                try:
                    yield
                finally:
                    dt = time.perf_counter() - t0
                    with self._lock:
                        self.counters[name + "_time"] += dt
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.counters[name + "_time"] += dt

    # -- reads --------------------------------------------------------------

    def get(self, name: str, **labels) -> float:
        """One counter's current value (0.0 when never incremented);
        with labels, the labeled series' value."""
        key = _series_key(name, labels) if labels else name
        with self._lock:
            return self.counters.get(key, 0.0)

    def get_gauge(self, name: str, **labels) -> float:
        key = _series_key(name, labels) if labels else name
        with self._lock:
            return self.gauges.get(key, 0.0)

    def snapshot(self) -> Dict[str, float]:
        """Counters (labeled series included), plus the reference-parity
        per-task aggregate aliases (PARITY_ALIASES) whenever their
        source timers have fired."""
        with self._lock:
            snap = dict(self.counters)
        for alias, source in PARITY_ALIASES.items():
            if source in snap:
                snap[alias] = snap[source]
        return snap

    def gauges_snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.gauges)

    def reset(self) -> None:
        """Restore a fully pristine state: counters, gauges, histograms
        and spans cleared; histogram/span enablement back to the
        construction-time default (so a test that called enable_spans()
        cannot leak recording into the next test)."""
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()
            self.spans.clear()
            self._hist_enabled = self._default_stats
            self._spans_enabled = self._default_stats

    # -- export -------------------------------------------------------------

    def export_chrome_trace(self, path: str) -> None:
        """Write spans in Chrome trace-event format (load in Perfetto).
        Span attributes plus trace/span/parent ids ride in ``args`` so
        host lanes can be correlated with ``device_trace`` captures and
        the tree reconstructed."""
        with self._lock:
            spans = list(self.spans)
        events = []
        for s in spans:
            args = dict(s.get("attrs") or {})
            for k, arg in (("trace", "trace_id"), ("id", "span_id"),
                           ("parent", "parent_id")):
                if s.get(k) is not None:
                    args[arg] = s[k]
            events.append({"name": s["name"], "ph": "X", "pid": 0,
                           "tid": s["tid"], "ts": s["ts"] * 1e6,
                           "dur": s["dur"] * 1e6, "args": args})
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)

    def export_spans_jsonl(self, path: str, append: bool = False) -> int:
        """Write the recorded spans as JSON lines — the PER-PROCESS
        half of cross-process tracing. Each line carries the span
        record plus ``pid`` and ``ts_unix`` (the perf_counter start
        converted through this process's wall-clock anchor), so
        ``scripts/trace_merge.py`` can stitch several processes' files
        into one Perfetto-loadable timeline keyed by trace id. Returns
        the number of spans written."""
        anchor_wall, anchor_perf = self._anchor
        with self._lock:
            spans = list(self.spans)
        pid = os.getpid()
        with open(path, "a" if append else "w") as f:
            for s in spans:
                rec = dict(s)
                rec["pid"] = pid
                rec["ts_unix"] = anchor_wall + (s["ts"] - anchor_perf)
                f.write(json.dumps(rec) + "\n")
            # the profiler's per-span sample summaries ride the same
            # file as `kind: "profile"` records (scripts/trace_merge.py
            # renders them as a profile lane next to the span lanes);
            # lazy import + total: an unprofiled or half-torn-down
            # process still exports its spans
            try:
                from uda_tpu.utils.profiler import profiler
                for rec in profiler.export_records(pid=pid):
                    f.write(json.dumps(rec) + "\n")
            except Exception:  # udalint: disable=UDA006 - profile
                pass  # lanes are additive; span export must not fail
        return len(spans)


def stats_enabled_from_env() -> bool:
    """UDA_TPU_STATS=1 (or true/yes/on) turns the optional layers on for
    the whole process."""
    return os.environ.get("UDA_TPU_STATS", "").strip().lower() in (
        "1", "true", "yes", "on")


@contextlib.contextmanager
def device_trace(log_dir: str | None = None) -> Iterator[None]:
    """Capture a device (Xprof) profile around a block, correlating the
    host-side spans above with on-device timelines — the SURVEY §7
    stage-8 'Xprof hooks'. Enabled by passing ``log_dir`` or setting
    ``UDA_TPU_XPROF=<dir>``; a no-op otherwise (and when the ambient
    backend does not support jax.profiler, e.g. relay backends — the
    failure is logged, never raised: profiling must not take down the
    job)."""
    d = log_dir or os.environ.get("UDA_TPU_XPROF")
    if not d:
        yield
        return
    import jax

    try:
        jax.profiler.start_trace(d)
    except Exception as e:  # noqa: BLE001 - profiling is best-effort
        from uda_tpu.utils.logging import get_logger

        get_logger().warn(f"device trace unavailable: {e}")
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            from uda_tpu.utils.logging import get_logger

            get_logger().warn(f"device trace stop failed: {e}")


metrics = Metrics(ledger=_resledger)
