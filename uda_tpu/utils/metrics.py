"""Per-phase timers and counters.

Equivalent of the reference's per-task counters (``total_wait_mem_time``,
``total_fetch_time``, ``total_merge_time``, reference
src/Merger/reducer.h:80-90, accumulated in StreamRW.cc:555-569) and the
AIO on-air counters (src/CommUtils/AIOHandler.cc:129-141). The reference
had no dedicated tracer (SURVEY §5); here we add a lightweight span/trace
export so profiles can be correlated with device profiles.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator

__all__ = ["Metrics", "metrics"]


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = defaultdict(float)
        self.spans: list[dict] = []
        self.record_spans = False

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += value

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.counters[name + "_time"] += dt
                if self.record_spans:
                    self.spans.append({"name": name, "ts": t0, "dur": dt,
                                       "tid": threading.get_ident()})

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.counters)

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.spans.clear()

    def export_chrome_trace(self, path: str) -> None:
        """Write spans in Chrome trace-event format (load in perfetto)."""
        with self._lock:
            events = [
                {"name": s["name"], "ph": "X", "pid": 0, "tid": s["tid"],
                 "ts": s["ts"] * 1e6, "dur": s["dur"] * 1e6}
                for s in self.spans
            ]
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)


metrics = Metrics()
