"""OpenMetrics / Prometheus text exposition of the metrics hub.

The fleet layer (scripts/udafleet.py) speaks the native MSG_STATS
wire; this module is the ecosystem bridge: an optional stdlib HTTP
endpoint (``uda.tpu.metrics.http.port``; 0 = off, the default) serving
``GET /metrics`` in the Prometheus text format, so standard scrapers
consume the SAME registry the wire exports — counters (labeled series
included), gauges, and histogram summaries as ``_count``/``_sum`` +
quantile gauges.

Name mangling follows the exposition rules: dots become underscores
(``fetch.bytes`` -> ``uda_fetch_bytes``; the ``uda_`` prefix
namespaces the job), label pairs are re-parsed from the hub's
``name{k=v,...}`` series keys. The server is a daemon thread around
``http.server.ThreadingHTTPServer`` — no third-party client library,
per the stdlib-only constraint."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import METRICS_REGISTRY, Metrics
from uda_tpu.utils.metrics import metrics as global_metrics

__all__ = ["render_openmetrics", "MetricsHTTP", "metrics_http"]

log = get_logger()


def _mangle(name: str) -> str:
    return "uda_" + name.replace(".", "_")


def _labels_of(key: str) -> tuple:
    """Split ``name{k=v,...}`` -> (name, rendered label string)."""
    if not key.endswith("}") or "{" not in key:
        return key, ""
    name, _, inner = key.partition("{")
    pairs = []
    for kv in inner[:-1].split(","):
        if "=" in kv:
            k, _, v = kv.partition("=")
            v = v.replace("\\", "\\\\").replace('"', '\\"')
            pairs.append(f'{k}="{v}"')
    return name, "{" + ",".join(pairs) + "}"


def render_openmetrics(m: Optional[Metrics] = None) -> str:
    """The whole hub as Prometheus text exposition format."""
    m = m or global_metrics
    lines = []
    seen_help = set()

    def _help(name: str, kind: str) -> None:
        if name in seen_help:
            return
        seen_help.add(name)
        reg = METRICS_REGISTRY.get(name)
        doc = (reg[1] if reg else "").replace("\n", " ")
        lines.append(f"# HELP {_mangle(name)} {doc}")
        lines.append(f"# TYPE {_mangle(name)} {kind}")

    for key, val in sorted(m.snapshot().items()):
        name, labels = _labels_of(key)
        _help(name, "counter")
        lines.append(f"{_mangle(name)}_total{labels} {val:g}")
    for key, val in sorted(m.gauges_snapshot().items()):
        name, labels = _labels_of(key)
        _help(name, "gauge")
        lines.append(f"{_mangle(name)}{labels} {val:g}")
    for key, s in sorted(m.histogram_summaries().items()):
        name, labels = _labels_of(key)
        _help(name, "summary")
        base, inner = _mangle(name), labels[1:-1] if labels else ""
        for q, p in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            if p in s:
                qlabels = (f'{{quantile="{q}"'
                           + (f",{inner}" if inner else "") + "}")
                lines.append(f"{base}{qlabels} {s[p]:g}")
        lines.append(f"{base}_count{labels} {s.get('count', 0):g}")
        lines.append(f"{base}_sum{labels} {s.get('sum', 0.0):g}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - http.server API
        if self.path.split("?", 1)[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        try:
            body = render_openmetrics().encode("utf-8")
        except Exception as e:  # noqa: BLE001 - a scrape must answer
            # 500, never kill the handler thread
            self.send_error(500, str(e)[:200])
            return
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # scrapes are not log lines
        pass


class MetricsHTTP:
    """Lifecycle wrapper: one exposition endpoint per process
    (module singleton :data:`metrics_http`)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        with self._lock:
            return self._server.server_address[1] if self._server else 0

    def start(self, port: int, host: str = "127.0.0.1") -> int:
        """Bind + serve in a daemon thread (idempotent; port 0 = any).
        Returns the bound port."""
        with self._lock:
            if self._server is not None:
                return self._server.server_address[1]
            srv = ThreadingHTTPServer((host, int(port)), _Handler)
            srv.daemon_threads = True
            self._server = srv
            self._thread = threading.Thread(
                target=srv.serve_forever, kwargs={"poll_interval": 0.2},
                daemon=True, name="uda-openmetrics")
            self._thread.start()
            log.info(f"OpenMetrics exposition on "
                     f"http://{host}:{srv.server_address[1]}/metrics")
            return srv.server_address[1]

    def stop(self) -> None:
        with self._lock:
            srv, self._server = self._server, None
            t, self._thread = self._thread, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if t is not None:
            t.join(timeout=5.0)


metrics_http = MetricsHTTP()
