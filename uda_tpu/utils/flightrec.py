"""Flight recorder: an always-on black box for post-mortem forensics.

PR 10's livelock hunt reconstructed "what happened in the seconds
before the fallback" by hand from counters and log lines; this module
records it as it happens. A bounded, lock-cheap ring buffer of
structured events — segment state transitions, admission/routing
decisions with their structured ``cause``, recovery-ledger events,
failpoint fires, watchdog samples — that costs one deque append per
event while the job is healthy and is dumped automatically when it is
not: on ``FallbackSignal`` (MergeManager.run), on a watchdog stall, on
a ResourceLedger leak report, and per chaos rung
(``scripts/run_chaos.sh`` archives the dumps into
``CHAOS_TELEMETRY.json``).

Design constraints, in order:

- **cheap on the hot path**: nothing here is called per chunk — the
  instrumented sites are per-segment / per-decision / per-fault
  events, and ``record()`` is an enabled-flag check plus one
  ``deque.append`` (atomic under the GIL, maxlen-bounded, no lock on
  the writer path). Disabled (``UDA_TPU_FLIGHTREC=0`` /
  ``uda.tpu.flightrec.enable=false``), every hook is one attribute
  check.
- **always on by default**: a black box that must be switched on
  before the crash records nothing; the ring's memory bound
  (``uda.tpu.flightrec.events``, default 4096 events) is the price of
  admission and it is small.
- **import-light**: this module imports only the stdlib at module
  scope, so every layer (failpoints, resledger, watchdog, segment) can
  hook it without cycles; the metrics snapshot embedded in a dump is
  imported lazily and best-effort.

A dump is one JSON file — ``flightrec_<pid>_<seq>_<cause>.json`` under
``uda.tpu.flightrec.dir`` / ``UDA_TPU_FLIGHTREC_DIR`` — carrying the
cause, the event stream (oldest first), and a counters/gauges snapshot.
With no directory configured the report is kept in-memory only
(:attr:`FlightRecorder.reports`, bounded) so unit tests and ad-hoc runs
never litter the working tree. Every dump counts ``flightrec.dumps``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["FlightRecorder", "flightrec", "flightrec_enabled_from_env"]

_DEFAULT_EVENTS = 4096
_MAX_REPORTS = 16  # in-memory dump reports kept (newest wins)


def flightrec_enabled_from_env() -> bool:
    """UDA_TPU_FLIGHTREC=0 (or false/no/off) disables the recorder;
    anything else — including unset — leaves it on (black boxes
    default to recording)."""
    return os.environ.get("UDA_TPU_FLIGHTREC", "").strip().lower() not in (
        "0", "false", "no", "off")


class FlightRecorder:
    """The ring + dump machinery. One global instance
    (:data:`flightrec`) serves every instrumented site; tests that
    need isolation construct private instances."""

    def __init__(self, capacity: int = _DEFAULT_EVENTS,
                 enabled: Optional[bool] = None,
                 dump_dir: str = "") -> None:
        self.enabled = (flightrec_enabled_from_env() if enabled is None
                        else bool(enabled))
        self._ring: deque = deque(maxlen=max(16, int(capacity)))
        self._dump_dir = dump_dir
        # dump bookkeeping only; record() never takes this lock
        self._mu = threading.Lock()
        self._seq = 0
        self.dump_paths: List[str] = []
        self.reports: List[dict] = []

    # -- configuration -------------------------------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None,
                  dump_dir: Optional[str] = None) -> None:
        """Apply the ``uda.tpu.flightrec.*`` knobs (bridge start /
        MergeManager construction). Growing/shrinking the ring keeps
        the newest events."""
        if enabled is not None:
            self.enabled = bool(enabled)
        if capacity is not None and int(capacity) != self._ring.maxlen:
            with self._mu:
                self._ring = deque(self._ring,
                                   maxlen=max(16, int(capacity)))
        if dump_dir is not None and dump_dir != "":
            self._dump_dir = dump_dir

    def _resolved_dir(self) -> str:
        return self._dump_dir or os.environ.get(
            "UDA_TPU_FLIGHTREC_DIR", "")

    # -- the hot hook --------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        """Append one structured event. The writer path is one flag
        check + one bounded ``deque.append`` — no lock, no I/O."""
        if not self.enabled:
            return
        self._ring.append((time.time(), kind, fields))

    # -- inspection / dump ---------------------------------------------------

    def events(self) -> List[dict]:
        """Snapshot of the ring, oldest first. The writer path is
        deliberately lock-free, so a concurrent append can roll the
        bounded deque mid-iteration (RuntimeError) — retry the copy; a
        torn snapshot under sustained mutation degrades to the newest
        consistent copy rather than an exception on a FAILURE path."""
        items: list = []
        for _ in range(8):
            try:
                items = list(self._ring)
                break
            except RuntimeError:  # deque mutated during iteration
                continue
        return [{"ts": ts, "kind": kind, **fields}
                for ts, kind, fields in items]

    def dump(self, cause: str, extra: Optional[Dict[str, Any]] = None
             ) -> Optional[str]:
        """Write one black-box report. Returns the file path (or None
        when no dump directory is configured — the report then lives
        only in :attr:`reports`). Dump failures are swallowed after
        logging: the recorder must never turn a failing job's unwind
        into a second failure."""
        if not self.enabled:
            return None
        try:
            return self._dump(cause, extra)
        except Exception as e:  # noqa: BLE001 - dump() runs inside
            # failure unwinds (the FallbackSignal re-raise, the
            # watchdog thread): a recorder bug must never replace the
            # real failure or kill its thread
            try:
                from uda_tpu.utils.logging import get_logger
                get_logger().warn(f"flightrec: dump failed: {e}")
            except Exception:  # udalint: disable=UDA006 - teardown:
                pass  # deliberately silent, the job's unwind wins
            return None

    def _dump(self, cause: str, extra: Optional[Dict[str, Any]]
              ) -> Optional[str]:
        report: Dict[str, Any] = {
            "ts": time.time(),
            "pid": os.getpid(),
            "cause": cause,
            "extra": dict(extra or {}),
            "events": self.events(),
        }
        try:  # best-effort context; never a hard dependency
            from uda_tpu.utils.metrics import metrics
            report["counters"] = {k: v for k, v in
                                  metrics.snapshot().items() if v}
            report["gauges"] = {k: v for k, v in
                                metrics.gauges_snapshot().items() if v}
            metrics.add("flightrec.dumps")
        except Exception:  # udalint: disable=UDA006 - half-imported
            pass  # metrics during interpreter teardown: deliberately
            # silent (logging may be half-dead too); the events
            # themselves still dump, which is the whole point
        try:  # the last-30s span-attributed profile slice — ARMED
            # profiler only (a post-mortem never arms sampling), and
            # any profiler error degrades to omission: this runs
            # inside failure unwinds where the dump must stay total
            from uda_tpu.utils.profiler import profiler
            if profiler.armed:
                report["profile"] = profiler.recent_summary(30.0)
        except Exception:  # udalint: disable=UDA006 - omission, never
            pass  # a second failure inside the unwind
        try:  # where the wall went (span-derived; spans on only)
            from uda_tpu.utils.critpath import time_accounting_block
            ta = time_accounting_block()
            if ta is not None:
                report["time_accounting"] = ta
        except Exception:  # udalint: disable=UDA006 - omission, never
            pass  # a second failure inside the unwind
        with self._mu:
            self._seq += 1
            seq = self._seq
            self.reports.append(report)
            del self.reports[:-_MAX_REPORTS]
        path = None
        out_dir = self._resolved_dir()
        if out_dir:
            fname = f"flightrec_{os.getpid()}_{seq}_" \
                    f"{_slug(cause)}.json"
            path = os.path.join(out_dir, fname)
            try:
                os.makedirs(out_dir, exist_ok=True)
                with open(path, "w") as f:
                    json.dump(report, f, default=repr)
            except OSError as e:
                path = None
                try:
                    from uda_tpu.utils.logging import get_logger
                    get_logger().warn(
                        f"flightrec: cannot write dump under "
                        f"{out_dir!r}: {e}")
                except Exception:  # noqa: BLE001 - teardown
                    print(f"flightrec: cannot write dump: {e}")
        if path is not None:
            with self._mu:
                self.dump_paths.append(path)
        return path

    def reset(self) -> None:
        """Forget events, reports and dump bookkeeping (tests)."""
        with self._mu:
            self._ring.clear()
            self.dump_paths.clear()
            self.reports.clear()
            self._seq = 0


def _slug(cause: str) -> str:
    return "".join(c if c.isalnum() or c in "._-" else "_"
                   for c in cause)[:48] or "dump"


flightrec = FlightRecorder()
