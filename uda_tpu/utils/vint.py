"""Hadoop zero-compressed VInt/VLong codec.

Byte-exact reimplementation of the Hadoop ``WritableUtils.writeVLong`` /
``readVLong`` wire format, which the reference implements natively in
``StreamUtility::serialize/deserializeLong`` (reference
src/CommUtils/IOUtility.cc:167-332, getVIntSize :367-382, decodeVIntSize
:389-397). Every IFile record is framed with two VInts (key length, value
length) in this encoding, and the EOF marker is the pair (-1, -1), so this
codec is the byte-level contract the whole framework shares.

Wire format recap:

- values in [-112, 127] are encoded as a single byte (the value itself);
- otherwise the first byte encodes sign and byte-count:
  ``-113..-120`` => positive value of (``-b - 112``) big-endian bytes,
  ``-121..-128`` => negative value, stored as ``~v`` in (``-b - 120``)
  big-endian bytes;
- multi-byte bodies never have a leading zero byte (minimal length).

Besides the scalar codec this module provides numpy-vectorized bulk
decode/encode used by the host staging path to convert whole IFile
segments into columnar arrays in one pass (the Python analogue of the hot
loop in reference src/Merger/StreamRW.cc:334-449 ``nextKV``); the C++
native library (uda_tpu/native) accelerates the same entry points.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "encode_vlong",
    "decode_vlong",
    "vlong_size",
    "decode_vint_size",
    "encode_vlong_array",
    "decode_vlong_stream",
]


def vlong_size(value: int) -> int:
    """Number of bytes ``encode_vlong(value)`` produces.

    Mirror of ``StreamUtility::getVIntSize`` (reference
    src/CommUtils/IOUtility.cc:367-382).
    """
    if -112 <= value <= 127:
        return 1
    if value < 0:
        value = ~value
    # body bytes needed for the magnitude, plus the tag byte
    n = 0
    while value:
        value >>= 8
        n += 1
    return n + 1


def decode_vint_size(first_byte: int) -> int:
    """Total encoded length given the (signed) first byte.

    Mirror of ``StreamUtility::decodeVIntSize`` (reference
    src/CommUtils/IOUtility.cc:389-397).
    """
    if first_byte >= -112:
        return 1
    if first_byte >= -120:
        return -111 - first_byte
    return -119 - first_byte


def encode_vlong(value: int) -> bytes:
    """Encode one integer in Hadoop zero-compressed VLong format."""
    if -112 <= value <= 127:
        return bytes([value & 0xFF])
    tag = -112
    if value < 0:
        value = ~value
        tag = -120
    body = []
    tmp = value
    while tmp:
        body.append(tmp & 0xFF)
        tmp >>= 8
    tag -= len(body)
    return bytes([tag & 0xFF]) + bytes(reversed(body))


def decode_vlong(buf, offset: int = 0) -> tuple[int, int]:
    """Decode one VLong from ``buf`` at ``offset``.

    Returns ``(value, new_offset)``. Raises ``IndexError`` on a truncated
    buffer (the caller implements rewind-on-partial, matching the
    reference's deserialize rewind semantics, IOUtility.cc:228-332).
    """
    first = buf[offset]
    if first > 127:
        first -= 256
    size = decode_vint_size(first)
    if size == 1:
        return first, offset + 1
    end = offset + size
    if end > len(buf):
        raise IndexError("truncated VLong")
    value = 0
    for i in range(offset + 1, end):
        value = (value << 8) | buf[i]
    if first < -120:
        value = ~value
    return value, end


# ---------------------------------------------------------------------------
# Vectorized bulk codec (numpy). Used by host staging to crack whole IFile
# segments; the C++ library in uda_tpu/native provides the same operations
# at native speed and is preferred when built.
# ---------------------------------------------------------------------------


def encode_vlong_array(values: np.ndarray) -> bytes:
    """Encode an int64 array as concatenated VLongs (scalar loop, host)."""
    out = bytearray()
    for v in values.tolist():
        out += encode_vlong(int(v))
    return bytes(out)


def decode_vlong_stream(buf: np.ndarray, count: int = -1) -> tuple[np.ndarray, np.ndarray]:
    """Decode consecutive VLongs from a uint8 array.

    Returns ``(values, offsets)`` where ``offsets[i]`` is the byte offset
    of the i-th VLong and ``values`` is int64. If ``count`` is -1, decodes
    until the buffer is exhausted. This is a scalar Python loop kept as
    the reference implementation for parity-testing the C++ bulk codec in
    uda_tpu/native; hot paths should use the native library.
    """
    buf = np.asarray(buf, dtype=np.uint8)
    values: list[int] = []
    offsets: list[int] = []
    pos = 0
    n = len(buf)
    mem = memoryview(buf)
    while pos < n and (count < 0 or len(values) < count):
        offsets.append(pos)
        first = mem[pos]
        signed_first = first - 256 if first > 127 else first
        size = decode_vint_size(signed_first)
        if size == 1:
            values.append(signed_first)
            pos += 1
        else:
            v, pos = decode_vlong(mem, pos)
            values.append(v)
    if count >= 0 and len(values) < count:
        raise IndexError("truncated VLong stream")
    return np.asarray(values, dtype=np.int64), np.asarray(offsets, dtype=np.int64)
