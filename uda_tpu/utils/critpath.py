"""Critical-path analyzer: partition a task's wall-clock into stages.

The reference accounts every reduce task into exactly three buckets —
``total_wait_mem_time`` / ``total_fetch_time`` / ``total_merge_time``
(reducer.h:80-90) — which PR 2 mirrored as counter aliases. After the
evloop data plane, the staging pipeline and the two-phase merge, three
numbers cannot say which STAGE owns the wall-clock: fetch overlaps
decompress overlaps device merges, so the timer sums legitimately
exceed the wall. This module answers the real question over the
recorded span tree of a completed task:

- **wall partition** ("critical share"): sweep the root span's
  timeline; at every instant the active spans map to stage *buckets*
  and exactly ONE bucket is charged, by a fixed gating-priority order
  (``merge`` > ``device_put`` > ``decompress_pack`` > ``serve`` >
  ``fetch`` > ``other`` > ``wait``) — nested spans naturally resolve
  to the most specific stage, and instants where only waiting is
  active charge ``wait``. Unclaimed instants are ``idle``. By
  construction the buckets + idle sum EXACTLY to the root's wall time
  (the 5%% acceptance gate holds with margin).
- **busy time**: per bucket, the plain sum of its spans' durations —
  can exceed the wall (that is the overlap working); ``overlap`` =
  busy / critical says how much parallel work each critical second of
  the bucket bought.
- **critical path**: the root->leaf span chain that maximizes summed
  child duration at every step — the longest dependency chain a
  latency optimization must shorten.

Reference-trio reconciliation: bucket ``fetch`` maps onto
``total_fetch_time``, ``wait`` onto ``total_wait_mem_time``, and
``merge`` + ``device_put`` + ``decompress_pack`` onto
``total_merge_time`` — the finer decomposition is the extension
(PARITY.md row). :func:`buckets_from_counters` provides the coarse
counter-derived fallback (busy seconds only) used where no span tree
exists (the chaos-telemetry rungs).

Consumers: the StatsReporter final record (``time_accounting`` block),
the MSG_STATS introspection plane via :func:`install_stats_provider`
(scripts/udatop.py renders the dominant bucket as a where-time-goes
column), watchdog stall dumps and flightrec post-mortems (best-effort,
omission on any error), and ``scripts/critpath.py`` standalone over
exported span JSONL files.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from uda_tpu.utils.metrics import Metrics
from uda_tpu.utils.metrics import metrics as global_metrics

__all__ = ["analyze", "time_accounting_block", "buckets_from_counters",
           "install_stats_provider", "SPAN_BUCKETS", "BUCKET_PRIORITY",
           "TRIO_MAP"]

# span name -> stage bucket. Timer spans carry their timer name
# (metrics.timer); names absent here land in "other". Kept in lockstep
# with the timer call sites and SPAN_REGISTRY by tests/test_timeacct.
SPAN_BUCKETS: Dict[str, str] = {
    # fetch: getting bytes from suppliers (RPC + wire + scheduling;
    # the MSG_JOB tenant registration is fetch-plane control traffic)
    "fetch": "fetch", "fetch.segment": "fetch", "net.fetch": "fetch",
    "net.size_probe": "fetch", "net.job_bind": "fetch",
    # wait: blocked-on-memory / blocked-on-staging idle
    "wait_mem": "wait", "merge.wait": "wait",
    # decompress+pack: host staging compute (materialize, vint-decode,
    # pack, row build, run spooling)
    "overlap_pack": "decompress_pack", "pack": "decompress_pack",
    "run_spool": "decompress_pack",
    # device-put: host->device transfer + buffer-recycle wait
    "overlap_stage": "device_put", "merge.device_put": "device_put",
    # merge: device/host merge + sort compute
    "merge": "merge", "overlap_device_merge": "merge",
    "device_sort": "merge", "lpq_spill": "merge", "lpq_phase": "merge",
    "rpq_phase": "merge",
    # serve: supplier-side reads + emission to the consumer
    "net.serve": "serve", "engine.pread": "serve",
    "engine.read_batch": "serve",
    "supplier_read": "serve", "emit": "serve",
}

# who gets charged when several buckets are active at one instant:
# earlier = the stage gating completion. "wait" is LAST on purpose — a
# merge.wait overlapping a live fetch is caused by the fetch, so the
# instant charges fetch; wait wins only when nothing else runs.
BUCKET_PRIORITY = ("merge", "device_put", "decompress_pack", "serve",
                   "fetch", "other", "wait")

# bucket -> the reference trio alias it reconciles onto (reducer.h:80-90)
TRIO_MAP: Dict[str, str] = {
    "fetch": "total_fetch_time",
    "wait": "total_wait_mem_time",
    "merge": "total_merge_time",
    "device_put": "total_merge_time",
    "decompress_pack": "total_merge_time",
}

_MAX_CHAIN = 32


def _bucket_of(name: str) -> str:
    return SPAN_BUCKETS.get(name, "other")


def _pick_root(spans: Sequence[Dict], root_name: str) -> Optional[Dict]:
    roots = [s for s in spans if s.get("name") == root_name]
    if not roots:
        return None
    # the LAST completed task wins (ties: the longest)
    return max(roots, key=lambda s: (s.get("ts", 0.0) + s.get("dur", 0.0),
                                     s.get("dur", 0.0)))


def analyze(spans: Sequence[Dict], root_name: str = "reduce_task"
            ) -> Optional[Dict]:
    """Compute the time-accounting block over recorded span dicts
    (the ``Metrics.spans`` / ``export_spans_jsonl`` shape: name, ts,
    dur, id, parent, trace). Scope: the last completed ``root_name``
    span and every span sharing its trace id; with no such root (e.g.
    a supplier-side process that only serves), the whole recorded set
    over its own [min, max] window. Returns None when there are no
    spans at all."""
    spans = [s for s in spans
             if s.get("kind") is None and s.get("dur") is not None]
    if not spans:
        return None
    root = _pick_root(spans, root_name)
    if root is not None:
        t0 = root["ts"]
        t1 = t0 + root["dur"]
        scope = [s for s in spans if s.get("trace") == root.get("trace")]
    else:
        t0 = min(s["ts"] for s in spans)
        t1 = max(s["ts"] + s["dur"] for s in spans)
        scope = list(spans)
    wall = max(t1 - t0, 0.0)
    buckets = {b: {"busy_s": 0.0, "critical_s": 0.0}
               for b in BUCKET_PRIORITY}

    # busy: plain per-bucket duration sums, clipped to the window
    events = []  # (time, +1 open / -1 close, bucket)
    for s in scope:
        if root is not None and s is root:
            continue  # the root frames the window, it is not a stage
        lo = max(s["ts"], t0)
        hi = min(s["ts"] + s["dur"], t1)
        if hi <= lo:
            continue
        b = _bucket_of(s["name"])
        buckets[b]["busy_s"] += hi - lo
        events.append((lo, 1, b))
        events.append((hi, -1, b))

    # critical: sweep elementary intervals, charge the highest-priority
    # active bucket; nothing active = idle. Sums to wall EXACTLY.
    idle = 0.0
    if events:
        events.sort(key=lambda e: (e[0], -e[1]))
        active = {b: 0 for b in BUCKET_PRIORITY}
        prev = t0
        i = 0
        while i < len(events):
            t = events[i][0]
            if t > prev:
                charged = next((b for b in BUCKET_PRIORITY if active[b]),
                               None)
                if charged is None:
                    idle += t - prev
                else:
                    buckets[charged]["critical_s"] += t - prev
                prev = t
            while i < len(events) and events[i][0] == t:
                active[events[i][2]] += events[i][1]
                i += 1
        if t1 > prev:
            charged = next((b for b in BUCKET_PRIORITY if active[b]), None)
            if charged is None:
                idle += t1 - prev
            else:
                buckets[charged]["critical_s"] += t1 - prev
    else:
        idle = wall

    for b, rec in buckets.items():
        rec["share"] = (rec["critical_s"] / wall) if wall > 0 else 0.0
        rec["overlap"] = (rec["busy_s"] / rec["critical_s"]
                          if rec["critical_s"] > 0 else 0.0)

    # the longest dependency chain root->leaf (greedy by child duration
    # at each level — the chain a latency fix must shorten)
    children: Dict = {}
    known = {s.get("id") for s in scope}
    for s in scope:
        parent = s.get("parent")
        if parent is not None and parent not in known:
            parent = None  # remote/un-ended parent: local root
        children.setdefault(parent, []).append(s)
    chain: List[Dict] = []
    node = root if root is not None else None
    node_id = node.get("id") if node is not None else None
    if node is not None:
        chain.append({"name": node["name"],
                      "dur_s": round(node["dur"], 6)})
    for _ in range(_MAX_CHAIN):
        kids = children.get(node_id, [])
        if node is None and not kids:
            break
        if not kids:
            break
        nxt = max(kids, key=lambda s: s.get("dur", 0.0))
        chain.append({"name": nxt["name"],
                      "dur_s": round(nxt["dur"], 6)})
        node, node_id = nxt, nxt.get("id")

    trio: Dict[str, float] = {}
    for b, alias in TRIO_MAP.items():
        trio[alias] = round(trio.get(alias, 0.0)
                            + buckets[b]["critical_s"], 6)
    return {
        "root": root["name"] if root is not None else None,
        "wall_s": round(wall, 6),
        "spans": len(scope),
        "buckets": {b: {k: round(v, 6) if isinstance(v, float) else v
                        for k, v in rec.items()}
                    for b, rec in buckets.items()},
        "idle_s": round(idle, 6),
        "critical_path": chain,
        # reconciliation onto the reference trio (critical seconds;
        # the counter aliases in Metrics.snapshot stay busy-seconds)
        "trio": trio,
    }


def time_accounting_block(m: Optional[Metrics] = None,
                          root_name: str = "reduce_task"
                          ) -> Optional[Dict]:
    """The live-process view: analyze the metrics hub's recorded spans
    (None when span recording is off or nothing recorded yet)."""
    m = m or global_metrics
    spans = list(m.spans)  # GIL-atomic copy; contents are immutable dicts
    block = analyze(spans, root_name=root_name)
    if block is not None:
        global_metrics.add("critpath.analyses")
    return block


def buckets_from_counters(counters: Dict[str, float]) -> Dict:
    """Coarse busy-seconds bucketing from the ``<timer>_time`` counters
    alone — the fallback where no span tree exists (chaos-rung session
    telemetry, stats-off runs). These are BUSY sums (overlap not
    removed), so they do not sum to wall; the block says so."""
    table = (("fetch", ("fetch_time",)),
             ("wait", ("wait_mem_time",)),
             ("decompress_pack", ("overlap_pack_time", "pack_time",
                                  "run_spool_time")),
             ("device_put", ("overlap_stage_time",)),
             ("merge", ("merge_time", "overlap_device_merge_time",
                        "device_sort_time", "lpq_spill_time",
                        "lpq_phase_time", "rpq_phase_time")),
             ("serve", ("supplier_read_time", "emit_time")))
    out = {b: round(sum(counters.get(k, 0.0) for k in keys), 6)
           for b, keys in table}
    return {"kind": "busy_seconds_from_counters", "buckets": out,
            "trio": {"total_fetch_time": out["fetch"],
                     "total_wait_mem_time": out["wait"],
                     "total_merge_time": round(out["merge"]
                                               + out["device_put"]
                                               + out["decompress_pack"],
                                               6)}}


# providers run on the server dispatcher thread per MSG_STATS poll and
# must be cheap; the analysis is O(n log n) over an ever-growing span
# list, so the block is cached and recomputed only when spans were
# appended since (the list is append-only between resets). [count,
# block]; GIL-atomic list mutation, a racy off-by-a-few recompute is
# harmless.
_provider_cache: list = [-1, None]


def _provider() -> Dict:
    n = len(global_metrics.spans)
    if n == _provider_cache[0]:
        block = _provider_cache[1]
    else:
        block = time_accounting_block()
        _provider_cache[0] = n
        _provider_cache[1] = block
    return block if block is not None else {"available": False}


def install_stats_provider() -> None:
    """Register the ``time_accounting`` MSG_STATS provider (idempotent;
    process-scoped, never unregistered) — how udatop gets its
    where-time-goes column. Called by MergeManager construction and
    ShuffleServer start, so both roles answer."""
    from uda_tpu.utils.stats import register_stats_provider

    register_stats_provider("time_accounting", _provider)
