"""Live stats reporting over the metrics hub.

The reference exposed its per-task aggregates only post-mortem (the
counter trio logged at reduce teardown, reference StreamRW.cc:555-569);
there was no way to watch a running shuffle. :class:`StatsReporter` is
the missing live channel: a background thread that snapshots counters
and gauges every interval, computes deltas and rates (fetch MB/s, merge
records/s, retry rate), and emits

- one **JSON-lines record** per interval (machine-readable stream —
  schema below), and
- one **human one-liner** through the dedicated ``uda.stats`` logger
  (silence it independently with
  ``get_logger("uda.stats").set_level(0)``).

The final record (``"final": true``, emitted by ``stop()`` or the
bridge's ``reduce_exit``) carries the reference-parity per-task trio
``total_wait_mem_time`` / ``total_fetch_time`` / ``total_merge_time``
plus histogram p50/p95/p99 summaries — the same block ``bench.py``
embeds in its JSON output (``telemetry_block``).

JSON-lines schema (one object per line)::

    {"ts": <unix seconds>, "uptime_s": ..., "interval_s": ...,
     "counters": {<name or name{label=v}>: <total>, ...},
     "gauges": {...},
     "rates": {"fetch_mb_s": ..., "merge_records_s": ...,
               "retry_per_s": ..., "emit_mb_s": ...},
     "histograms": {<name>: {"count","sum","min","max","p50","p95","p99"}},
     "percentiles": {<name>: {"p50","p95","p99"}},
     "profile": {...},         # armed sampling profiler only
                               # (utils/profiler.py summary)
     "final": true,            # last record only, which also carries:
     "recovery": {"recovery.r<id>": {penalty_box, ledger, admission}},
     "resledger": {"armed","outstanding","by_pair","leak_reports"},
     "time_accounting": {...}} # span-derived wall partition
                               # (utils/critpath.py; spans on only)

This module is also the **introspection registry**: components with
process-local state register snapshot providers
(:func:`register_stats_provider`) and
:func:`introspection_snapshot` folds them — with counters, gauges,
percentiles and the ResourceLedger summary — into the record the
shuffle server answers ``MSG_STATS`` wire requests with
(``scripts/udatop.py`` is the console over it).

Configuration: ``uda.tpu.stats.enable`` / ``UDA_TPU_STATS=1`` switch the
whole observability layer on; ``uda.tpu.stats.interval.ms`` paces the
reporter; ``uda.tpu.stats.jsonl`` / ``UDA_TPU_STATS_JSONL`` name the
JSON-lines destination (stderr when unset).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Callable, Dict, Optional

from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import PARITY_ALIASES, Metrics
from uda_tpu.utils.metrics import metrics as global_metrics
from uda_tpu.utils.resledger import resledger

__all__ = ["StatsReporter", "telemetry_block", "introspection_snapshot",
           "register_stats_provider", "unregister_stats_provider",
           "percentiles_block", "resledger_block"]

# (rate key, source counter, scale) — rate = delta(counter)/dt/scale
_RATES = (
    ("fetch_mb_s", "fetch.bytes", 1e6),
    ("emit_mb_s", "emit.bytes", 1e6),
    ("merge_records_s", "merge.records", 1.0),
    ("retry_per_s", "fetch.retries", 1.0),
)


def telemetry_block(m: Optional[Metrics] = None) -> Dict:
    """One comparable snapshot block: counters (with the parity trio),
    gauges, and histogram percentile summaries. Embedded in bench JSON,
    chaos-run telemetry and the reporter's final record so BENCH_*.json
    files across rounds stay directly diffable."""
    m = m or global_metrics
    counters = m.snapshot()
    for alias in PARITY_ALIASES:
        counters.setdefault(alias, 0.0)
    return {"counters": counters, "gauges": m.gauges_snapshot(),
            "histograms": m.histogram_summaries()}


def percentiles_block(m: Optional[Metrics] = None,
                      summaries: Optional[Dict] = None) -> Dict:
    """The Metrics.percentile() projection, one compact block per
    histogram series: ``{name: {"p50","p95","p99"}}`` — the same
    estimator the speculation threshold consumes internally, exposed
    in every interval/final record and over MSG_STATS so remote
    pollers (scripts/udatop.py) read latency tails without shipping
    whole bucket arrays. Pass already-built ``summaries`` (a
    ``histogram_summaries()`` result) to avoid a second walk of every
    series per record/poll."""
    if summaries is None:
        summaries = (m or global_metrics).histogram_summaries()
    return {name: {"p50": s.get("p50", 0.0), "p95": s.get("p95", 0.0),
                   "p99": s.get("p99", 0.0)}
            for name, s in summaries.items()
            if s.get("count")}


def resledger_block() -> Dict:
    """The ResourceLedger obligation summary: open obligations grouped
    by pair (count + amount), plus the lifetime leak-report count.
    Stacks deliberately stay OFF the wire — they are the dump/log
    diagnostic; the summary is the scrape surface."""
    by_pair: Dict[str, Dict[str, float]] = {}
    outstanding = resledger.outstanding() if resledger.enabled else []
    for rec in outstanding:
        agg = by_pair.setdefault(rec["pair"], {"count": 0, "amount": 0.0})
        agg["count"] += 1
        agg["amount"] += rec["amount"]
    return {"armed": resledger.enabled,
            "outstanding": len(outstanding),
            "by_pair": by_pair,
            "leak_reports": len(resledger.leak_reports)}


# -- introspection providers (the MSG_STATS scrape surface) -------------------

# name -> zero-arg callable returning a JSON-able dict. Components with
# process-local state the metrics hub cannot see (a MergeManager's
# PenaltyBox/RecoveryLedger, a ShuffleServer's conn table) register
# here for the life of the component; introspection_snapshot() folds
# every provider into the remote-readable record. Providers must be
# cheap and non-blocking — they run on a server dispatcher thread per
# MSG_STATS poll.
_PROVIDERS: Dict[str, Callable[[], Dict]] = {}
_PROVIDERS_LOCK = threading.Lock()


def register_stats_provider(name: str, fn: Callable[[], Dict]) -> None:
    with _PROVIDERS_LOCK:
        _PROVIDERS[name] = fn


def unregister_stats_provider(name: str, fn: Optional[Callable] = None
                              ) -> None:
    """Remove ``name``; with ``fn`` given, only when it is still the
    registered callable (a replaced provider must not be yanked by its
    predecessor's teardown)."""
    with _PROVIDERS_LOCK:
        # == not `is`: bound methods are re-materialized per access,
        # but compare equal for the same (function, instance) pair
        if fn is None or _PROVIDERS.get(name) == fn:
            _PROVIDERS.pop(name, None)


def introspection_snapshot(m: Optional[Metrics] = None) -> Dict:
    """The live introspection record served over MSG_STATS (and usable
    locally): counters/gauges/histogram percentiles, the ResourceLedger
    obligation summary, and every registered provider's block
    (PenaltyBox/RecoveryLedger state, evloop conn tables). One
    provider failing must not take the whole snapshot down — its block
    degrades to an error marker."""
    m = m or global_metrics
    snap = telemetry_block(m)
    snap["ts"] = round(time.time(), 3)
    snap["pid"] = os.getpid()
    snap["percentiles"] = percentiles_block(
        summaries=snap["histograms"])
    snap["resledger"] = resledger_block()
    with _PROVIDERS_LOCK:
        providers = dict(_PROVIDERS)
    blocks = {}
    for name, fn in providers.items():
        try:
            blocks[name] = fn()
        except Exception as e:  # noqa: BLE001 - a dying component's
            # provider racing its own teardown is expected; the poll
            # must still answer
            blocks[name] = {"error": type(e).__name__}
    snap["providers"] = blocks
    return snap


def _profile_block() -> Optional[Dict]:
    """The armed sampling profiler's summary, or None (off / import
    failure) — lazy + total so reporting never depends on the
    profiler's health."""
    try:
        from uda_tpu.utils.profiler import profiler

        if not profiler.armed:
            return None
        return profiler.summary()
    except Exception:  # udalint: disable=UDA006 - profiling is
        return None  # additive; a reporter record must still emit


def _time_accounting_block(m: Optional[Metrics]) -> Optional[Dict]:
    """The critpath block over the recorded span tree, or None —
    same additive contract as the profile block."""
    try:
        from uda_tpu.utils.critpath import time_accounting_block

        return time_accounting_block(m)
    except Exception:  # udalint: disable=UDA006 - additive block
        return None


def _slo_block() -> Optional[Dict]:
    """Per-tenant SLO attainment/burn from the armed SLI book, or
    None — same additive contract as the profile block (the lazy
    import keeps stats.py free of a tenant-layer dependency for
    single-tenant runs)."""
    try:
        from uda_tpu.tenant.sli import sli_book

        return sli_book.slo_block()
    except Exception:  # udalint: disable=UDA006 - additive block
        return None


class StatsReporter:
    """Periodic snapshot/delta/rate reporter over a :class:`Metrics`.

    ``clock`` is injectable for tests (defaults to ``time.monotonic``);
    ``out`` is a path (appended, line-buffered), a file-like object, or
    None for stderr. ``report_once()`` is the single-step core the
    background thread loops on — callable directly with a fake clock."""

    def __init__(self, metrics_obj: Optional[Metrics] = None,
                 interval_s: float = 1.0, out=None,
                 clock: Callable[[], float] = time.monotonic,
                 logger_name: str = "uda.stats"):
        self.metrics = metrics_obj or global_metrics
        self.interval_s = max(0.05, float(interval_s))
        self.clock = clock
        self.log = get_logger(logger_name)
        self._out = out
        self._own_file = None
        if isinstance(out, str):
            self._own_file = open(out, "a", buffering=1)
        self._t0 = clock()
        self._last_t = self._t0
        self._last_counters: Dict[str, float] = self.metrics.snapshot()
        self._latest: Dict = {}
        self._stop = threading.Event()
        self._stopped_final = False
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "StatsReporter":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="uda-stats-reporter")
            self._thread.start()
        return self

    def stop(self, final: bool = True) -> None:
        """Stop the loop; with ``final`` emit one last record flagged
        ``"final": true``. Idempotent: a second stop neither emits
        another final record nor writes past the closed JSONL file."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        if final and not self._stopped_final:
            self._stopped_final = True
            self.report_once(final=True)
        if self._own_file is not None:
            self._own_file.close()
            self._own_file = None

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            try:
                self.report_once()
            except Exception as e:  # noqa: BLE001 - reporting must never
                # take down the job it watches
                self.log.warn(f"stats report failed: {e}")

    # -- the report itself --------------------------------------------------

    def report_once(self, final: bool = False) -> Dict:
        """Snapshot, diff against the previous snapshot, emit one JSONL
        record + one progress line. Returns the record (also kept as
        ``latest()`` for the bridge's GET_STATS)."""
        with self._lock:
            now = self.clock()
            dt = max(now - self._last_t, 1e-9)
            counters = self.metrics.snapshot()
            rates = {key: round((counters.get(src, 0.0)
                                 - self._last_counters.get(src, 0.0))
                                / dt / scale, 6)
                     for key, src, scale in _RATES}
            self._last_t = now
            self._last_counters = counters
            record: Dict = {
                "ts": round(time.time(), 3),
                "uptime_s": round(now - self._t0, 3),
                "interval_s": round(dt, 3),
                "counters": counters,
                "gauges": self.metrics.gauges_snapshot(),
                "rates": rates,
                "histograms": self.metrics.histogram_summaries(),
            }
            # the Metrics.percentile() projection (p50/p95/p99 per
            # series) in EVERY record — the tail-latency view the
            # speculation threshold already consumes internally —
            # derived from the summaries just built, not a second walk
            record["percentiles"] = percentiles_block(
                summaries=record["histograms"])
            prof = _profile_block()
            if prof is not None:
                record["profile"] = prof
            if final:
                record["final"] = True
                for alias in PARITY_ALIASES:
                    record["counters"].setdefault(alias, 0.0)
                # the task post-mortem blocks: what the survivable-
                # shuffle layer did (registered recovery.* providers —
                # PenaltyBox state, RecoveryLedger counts) and whether
                # the obligation books closed clean
                with _PROVIDERS_LOCK:
                    providers = dict(_PROVIDERS)
                recovery = {}
                for name, fn in providers.items():
                    if not name.startswith("recovery"):
                        continue
                    try:
                        recovery[name] = fn()
                    except Exception as e:  # noqa: BLE001 - teardown race
                        recovery[name] = {"error": type(e).__name__}
                record["recovery"] = recovery
                record["resledger"] = resledger_block()
                # the time-accounting post-mortem: where the task's
                # wall-clock went, bucketed over the recorded span
                # tree (None when spans were off — the block is
                # additive, never a failure)
                ta = _time_accounting_block(self.metrics)
                if ta is not None:
                    record["time_accounting"] = ta
                # the SLO post-mortem: per-tenant attainment + burn
                # rate over the run (None when the SLI book never
                # armed — additive, never a failure)
                slo = _slo_block()
                if slo is not None:
                    record["slo"] = slo
            self._latest = record
            self._write_jsonl(record)
        self._progress_line(record)
        return record

    def latest(self) -> Dict:
        """Most recent record (computed on demand when none exists yet —
        the GET_STATS pull path)."""
        with self._lock:
            latest = dict(self._latest)
        return latest or self.report_once()

    def _write_jsonl(self, record: Dict) -> None:
        line = json.dumps(record, sort_keys=True)
        out = self._own_file or self._out or sys.stderr
        try:
            out.write(line + "\n")
        except ValueError:  # closed stream (interpreter teardown)
            pass

    def _progress_line(self, record: Dict) -> None:
        r = record["rates"]
        g = record["gauges"]
        c = record["counters"]
        self.log.info(
            f"shuffle stats: fetch {r['fetch_mb_s']:.2f} MB/s, emit "
            f"{r['emit_mb_s']:.2f} MB/s, merge {r['merge_records_s']:.0f} "
            f"rec/s, retries {r['retry_per_s']:.2f}/s "
            f"(total {c.get('fetch.retries', 0):.0f}), on-air "
            f"{g.get('fetch.on_air', 0):.0f}")


def reporter_output_from_env(cfg_path: str = "") -> Optional[str]:
    """Resolve the JSONL destination: explicit config path wins, then
    UDA_TPU_STATS_JSONL, else None (stderr)."""
    return cfg_path or os.environ.get("UDA_TPU_STATS_JSONL") or None
