"""Live stats reporting over the metrics hub.

The reference exposed its per-task aggregates only post-mortem (the
counter trio logged at reduce teardown, reference StreamRW.cc:555-569);
there was no way to watch a running shuffle. :class:`StatsReporter` is
the missing live channel: a background thread that snapshots counters
and gauges every interval, computes deltas and rates (fetch MB/s, merge
records/s, retry rate), and emits

- one **JSON-lines record** per interval (machine-readable stream —
  schema below), and
- one **human one-liner** through the dedicated ``uda.stats`` logger
  (silence it independently with
  ``get_logger("uda.stats").set_level(0)``).

The final record (``"final": true``, emitted by ``stop()`` or the
bridge's ``reduce_exit``) carries the reference-parity per-task trio
``total_wait_mem_time`` / ``total_fetch_time`` / ``total_merge_time``
plus histogram p50/p95/p99 summaries — the same block ``bench.py``
embeds in its JSON output (``telemetry_block``).

JSON-lines schema (one object per line)::

    {"ts": <unix seconds>, "uptime_s": ..., "interval_s": ...,
     "counters": {<name or name{label=v}>: <total>, ...},
     "gauges": {...},
     "rates": {"fetch_mb_s": ..., "merge_records_s": ...,
               "retry_per_s": ..., "emit_mb_s": ...},
     "histograms": {<name>: {"count","sum","min","max","p50","p95","p99"}},
     "final": true}            # last record only

Configuration: ``uda.tpu.stats.enable`` / ``UDA_TPU_STATS=1`` switch the
whole observability layer on; ``uda.tpu.stats.interval.ms`` paces the
reporter; ``uda.tpu.stats.jsonl`` / ``UDA_TPU_STATS_JSONL`` name the
JSON-lines destination (stderr when unset).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Callable, Dict, Optional

from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import PARITY_ALIASES, Metrics
from uda_tpu.utils.metrics import metrics as global_metrics

__all__ = ["StatsReporter", "telemetry_block"]

# (rate key, source counter, scale) — rate = delta(counter)/dt/scale
_RATES = (
    ("fetch_mb_s", "fetch.bytes", 1e6),
    ("emit_mb_s", "emit.bytes", 1e6),
    ("merge_records_s", "merge.records", 1.0),
    ("retry_per_s", "fetch.retries", 1.0),
)


def telemetry_block(m: Optional[Metrics] = None) -> Dict:
    """One comparable snapshot block: counters (with the parity trio),
    gauges, and histogram percentile summaries. Embedded in bench JSON,
    chaos-run telemetry and the reporter's final record so BENCH_*.json
    files across rounds stay directly diffable."""
    m = m or global_metrics
    counters = m.snapshot()
    for alias in PARITY_ALIASES:
        counters.setdefault(alias, 0.0)
    return {"counters": counters, "gauges": m.gauges_snapshot(),
            "histograms": m.histogram_summaries()}


class StatsReporter:
    """Periodic snapshot/delta/rate reporter over a :class:`Metrics`.

    ``clock`` is injectable for tests (defaults to ``time.monotonic``);
    ``out`` is a path (appended, line-buffered), a file-like object, or
    None for stderr. ``report_once()`` is the single-step core the
    background thread loops on — callable directly with a fake clock."""

    def __init__(self, metrics_obj: Optional[Metrics] = None,
                 interval_s: float = 1.0, out=None,
                 clock: Callable[[], float] = time.monotonic,
                 logger_name: str = "uda.stats"):
        self.metrics = metrics_obj or global_metrics
        self.interval_s = max(0.05, float(interval_s))
        self.clock = clock
        self.log = get_logger(logger_name)
        self._out = out
        self._own_file = None
        if isinstance(out, str):
            self._own_file = open(out, "a", buffering=1)
        self._t0 = clock()
        self._last_t = self._t0
        self._last_counters: Dict[str, float] = self.metrics.snapshot()
        self._latest: Dict = {}
        self._stop = threading.Event()
        self._stopped_final = False
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "StatsReporter":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="uda-stats-reporter")
            self._thread.start()
        return self

    def stop(self, final: bool = True) -> None:
        """Stop the loop; with ``final`` emit one last record flagged
        ``"final": true``. Idempotent: a second stop neither emits
        another final record nor writes past the closed JSONL file."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        if final and not self._stopped_final:
            self._stopped_final = True
            self.report_once(final=True)
        if self._own_file is not None:
            self._own_file.close()
            self._own_file = None

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            try:
                self.report_once()
            except Exception as e:  # noqa: BLE001 - reporting must never
                # take down the job it watches
                self.log.warn(f"stats report failed: {e}")

    # -- the report itself --------------------------------------------------

    def report_once(self, final: bool = False) -> Dict:
        """Snapshot, diff against the previous snapshot, emit one JSONL
        record + one progress line. Returns the record (also kept as
        ``latest()`` for the bridge's GET_STATS)."""
        with self._lock:
            now = self.clock()
            dt = max(now - self._last_t, 1e-9)
            counters = self.metrics.snapshot()
            rates = {key: round((counters.get(src, 0.0)
                                 - self._last_counters.get(src, 0.0))
                                / dt / scale, 6)
                     for key, src, scale in _RATES}
            self._last_t = now
            self._last_counters = counters
            record: Dict = {
                "ts": round(time.time(), 3),
                "uptime_s": round(now - self._t0, 3),
                "interval_s": round(dt, 3),
                "counters": counters,
                "gauges": self.metrics.gauges_snapshot(),
                "rates": rates,
                "histograms": self.metrics.histogram_summaries(),
            }
            if final:
                record["final"] = True
                for alias in PARITY_ALIASES:
                    record["counters"].setdefault(alias, 0.0)
            self._latest = record
            self._write_jsonl(record)
        self._progress_line(record)
        return record

    def latest(self) -> Dict:
        """Most recent record (computed on demand when none exists yet —
        the GET_STATS pull path)."""
        with self._lock:
            latest = dict(self._latest)
        return latest or self.report_once()

    def _write_jsonl(self, record: Dict) -> None:
        line = json.dumps(record, sort_keys=True)
        out = self._own_file or self._out or sys.stderr
        try:
            out.write(line + "\n")
        except ValueError:  # closed stream (interpreter teardown)
            pass

    def _progress_line(self, record: Dict) -> None:
        r = record["rates"]
        g = record["gauges"]
        c = record["counters"]
        self.log.info(
            f"shuffle stats: fetch {r['fetch_mb_s']:.2f} MB/s, emit "
            f"{r['emit_mb_s']:.2f} MB/s, merge {r['merge_records_s']:.0f} "
            f"rec/s, retries {r['retry_per_s']:.2f}/s "
            f"(total {c.get('fetch.retries', 0):.0f}), on-air "
            f"{g.get('fetch.on_air', 0):.0f}")


def reporter_output_from_env(cfg_path: str = "") -> Optional[str]:
    """Resolve the JSONL destination: explicit config path wins, then
    UDA_TPU_STATS_JSONL, else None (stderr)."""
    return cfg_path or os.environ.get("UDA_TPU_STATS_JSONL") or None
