"""In-process time-series rollups over the metrics hub.

The observability plane so far is snapshot-shaped: ``MSG_STATS`` and
the StatsReporter answer "what is the state NOW"; nothing can answer
"what happened two minutes ago" while a job is live. :class:`TimeSeries`
is the missing recent-history layer — the analogue of the reference's
periodic ``Cmd.GET_STATS`` pull loop, kept *inside* the process so
every daemon and every reduce task carries its own black-box recorder
for numbers the way the flight recorder does for events:

- one cheap timer (``uda.tpu.ts.interval.s``) snapshots the global
  :class:`~uda_tpu.utils.metrics.Metrics` hub each interval and folds
  the *deltas* — counter increments, gauge levels, and per-interval
  histogram percentiles recomputed from bucket deltas — into a bounded
  ring of ``uda.tpu.ts.window`` rollups (oldest roll off);
- the ring is queryable by window (:meth:`TimeSeries.window`) and by
  single series (:meth:`counter_rate_series` / :meth:`gauge_series` /
  :meth:`percentile_series`) — the feed the online anomaly detectors
  (``utils/anomaly.py``) and the per-tenant SLI book
  (``tenant/sli.py``) run on;
- listeners subscribe for per-rollup callbacks
  (:meth:`add_listener`) so the whole live-telemetry plane rides ONE
  timer thread — the sampler never grows a second clock per consumer.

Per-interval percentiles are exact within the estimator: the hub's
histograms are cumulative fixed-bucket counters, so the interval view
is the bucket-count delta between consecutive snapshots run through
the same interpolation (:func:`~uda_tpu.utils.metrics.
percentile_from_summary`) that live polls use — a p99 inflation in one
interval cannot hide behind a long healthy history the way it does in
the cumulative summary.

The module-level :data:`timeseries` is the process singleton (tests
construct private instances with a fake clock). Arming follows the
stats plane: :func:`arm_observability_plane` wires ring + detectors +
SLI + the optional OpenMetrics exposition from one config read — the
bridge and the shuffle server both call it, idempotently.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import (Metrics, metrics as global_metrics,
                                   percentile_from_summary)

__all__ = ["TimeSeries", "timeseries", "arm_observability_plane",
           "disarm_observability_plane"]

log = get_logger()

# ring defaults (the knob defaults in config.py mirror these)
DEFAULT_INTERVAL_S = 1.0
DEFAULT_WINDOW = 120


def _interval_hist(cur: Dict, prev: Optional[Dict]) -> Dict:
    """The per-interval histogram summary: cumulative bucket counts
    differenced against the previous snapshot (first sight of a series
    = the whole cumulative state). Returns ``{"count": 0}`` for an
    idle interval."""
    count = cur.get("count", 0) - (prev.get("count", 0) if prev else 0)
    if count <= 0:
        return {"count": 0}
    prev_buckets = {le: c for le, c in (prev.get("buckets") or [])} \
        if prev else {}
    buckets = []
    for le, c in cur.get("buckets") or []:
        d = c - prev_buckets.get(le, 0)
        if d > 0:
            buckets.append([le, d])
    return {"count": count,
            "sum": cur.get("sum", 0.0) - (prev.get("sum", 0.0)
                                          if prev else 0.0),
            # min/max are cumulative (the hub does not track them per
            # interval); they only clamp the interpolation
            "min": cur.get("min", 0.0), "max": cur.get("max", 0.0),
            "buckets": buckets}


class TimeSeries:
    """Bounded ring of per-interval metric rollups.

    One rollup per ``interval_s``::

        {"seq": n, "ts": <unix s>, "dt": <interval s>,
         "counters": {name: delta, ...},        # nonzero deltas only
         "gauges": {name: level, ...},
         "percentiles": {series: {"count","p50","p95","p99"}, ...}}

    ``clock`` is injectable (tests drive :meth:`sample` directly with a
    fake clock); the background thread is optional — :meth:`sample` is
    the single-step core either way."""

    def __init__(self, metrics_obj: Optional[Metrics] = None,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 window: int = DEFAULT_WINDOW,
                 clock: Callable[[], float] = time.monotonic):
        self.metrics = metrics_obj or global_metrics
        self.interval_s = max(0.05, float(interval_s))
        self.clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(2, int(window)))
        self._listeners: List[Callable[[Dict], None]] = []
        self._last_counters: Optional[Dict[str, float]] = None
        self._last_hists: Dict[str, Dict] = {}
        self._last_t = 0.0
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- configuration / lifecycle -------------------------------------------

    @property
    def window_len(self) -> int:
        return self._ring.maxlen or 0

    @property
    def running(self) -> bool:
        return self._thread is not None

    def configure(self, interval_s: Optional[float] = None,
                  window: Optional[int] = None) -> "TimeSeries":
        """Re-point the knobs. A window change re-bounds the ring,
        keeping the newest rollups."""
        with self._lock:
            if interval_s is not None:
                self.interval_s = max(0.05, float(interval_s))
            if window is not None and int(window) != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(2, int(window)))
        return self

    def start(self) -> "TimeSeries":
        """Start the sampling thread (idempotent). The first tick lands
        one interval from now; the baseline snapshot is taken here so
        interval #1 carries only post-start deltas."""
        if self._thread is not None:
            return self
        with self._lock:
            self._baseline_locked()
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="uda-timeseries")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def reset(self) -> None:
        """Back to pristine: ring, baselines and listeners cleared
        (conftest hygiene — a test's listener must not see the next
        test's rollups)."""
        self.stop()
        with self._lock:
            self._ring.clear()
            self._listeners.clear()
            self._last_counters = None
            self._last_hists = {}
            self._seq = 0

    def _baseline_locked(self) -> None:
        self._last_counters = self.metrics.snapshot()
        self._last_hists = self.metrics.histogram_summaries()
        self._last_t = self.clock()

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            try:
                self.sample()
            except Exception as e:  # noqa: BLE001 - the recorder must
                # never take down the process it watches
                log.warn(f"timeseries sample failed: {e}")

    # -- the sampler core ----------------------------------------------------

    def sample(self) -> Dict:
        """One rollup step: snapshot, delta, append, notify listeners.
        Callable directly (fake-clock tests, bench harnesses)."""
        m = self.metrics
        counters = m.snapshot()
        gauges = m.gauges_snapshot()
        hists = m.histogram_summaries()
        with self._lock:
            now = self.clock()
            if self._last_counters is None:
                # first sample with no start(): self-baseline, emit an
                # all-zero rollup rather than a giant cumulative one
                self._last_counters = counters
                self._last_hists = hists
                self._last_t = now
                counters = dict(counters)
            # floor above the round(…, 6) quantum below: a same-tick
            # sample must still roll up with a dividable dt (rate
            # queries and detectors divide by it)
            dt = max(now - self._last_t, 1e-6)
            deltas = {}
            for name, v in counters.items():
                d = v - self._last_counters.get(name, 0.0)
                if d:
                    deltas[name] = d
            pcts = {}
            for key, s in hists.items():
                isum = _interval_hist(s, self._last_hists.get(key))
                if isum["count"]:
                    pcts[key] = {
                        "count": isum["count"],
                        "p50": percentile_from_summary(isum, 50),
                        "p95": percentile_from_summary(isum, 95),
                        "p99": percentile_from_summary(isum, 99)}
            self._seq += 1
            roll = {"seq": self._seq, "ts": round(time.time(), 3),
                    "dt": round(dt, 6), "counters": deltas,
                    "gauges": gauges, "percentiles": pcts}
            self._ring.append(roll)
            self._last_counters = counters
            self._last_hists = hists
            self._last_t = now
            listeners = tuple(self._listeners)
        for fn in listeners:
            try:
                fn(roll)
            except Exception as e:  # noqa: BLE001 - one consumer
                # (detector, SLI book) failing must not stop the clock
                # for the others
                global_metrics.add("ts.listener.errors")
                log.warn(f"timeseries listener failed: {e}")
        return roll

    # -- listeners (the one-timer contract) ----------------------------------

    def add_listener(self, fn: Callable[[Dict], None]) -> None:
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[Dict], None]) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    # -- queries -------------------------------------------------------------

    def window(self, seconds: Optional[float] = None,
               count: Optional[int] = None) -> List[Dict]:
        """The newest rollups, oldest first: the last ``count``
        intervals, or every interval within the trailing ``seconds``
        (both unset = the whole ring)."""
        with self._lock:
            rolls = list(self._ring)
        if count is not None:
            rolls = rolls[-max(0, int(count)):]
        if seconds is not None:
            acc = 0.0
            kept: List[Dict] = []
            for roll in reversed(rolls):
                kept.append(roll)
                acc += roll["dt"]
                if acc >= seconds:
                    break
            rolls = list(reversed(kept))
        return rolls

    def counter_rate_series(self, name: str,
                            count: Optional[int] = None) -> List[float]:
        """Per-interval rate (delta/dt) of one counter, oldest first —
        the throughput feed the collapse detector watches."""
        return [r["counters"].get(name, 0.0) / r["dt"]
                for r in self.window(count=count)]

    def gauge_series(self, name: str,
                     count: Optional[int] = None) -> List[float]:
        return [r["gauges"].get(name, 0.0)
                for r in self.window(count=count)]

    def percentile_series(self, name: str, p: str = "p99",
                          count: Optional[int] = None) -> List[float]:
        """Per-interval percentile of one histogram series (intervals
        without samples are skipped — an idle fetch path is not a
        latency regression)."""
        out = []
        for r in self.window(count=count):
            s = r["percentiles"].get(name)
            if s is not None:
                out.append(s[p])
        return out

    # -- export (MSG_STATS / provider blocks) --------------------------------

    def summary(self) -> Dict:
        """The cheap always-on provider block: configuration + ring
        occupancy + the newest rollup's sequence/timestamp."""
        with self._lock:
            n = len(self._ring)
            last = self._ring[-1] if n else None
        return {"running": self.running,
                "interval_s": self.interval_s,
                "window": self.window_len, "samples": n,
                "last_seq": last["seq"] if last else 0,
                "last_ts": last["ts"] if last else 0.0}

    def wire_block(self, seconds: Optional[float] = None) -> Dict:
        """The on-demand MSG_STATS section (CAP_OBS peers only): the
        requested trailing window of rollups plus the summary."""
        block = self.summary()
        block["rollups"] = self.window(seconds=seconds)
        return block


timeseries = TimeSeries()

_ARM_LOCK = threading.Lock()
_ARMED = False


def arm_observability_plane(config) -> bool:
    """Wire the whole live-telemetry plane from config, idempotently:
    the rollup ring (``uda.tpu.ts.*``), the anomaly detectors
    (``uda.tpu.anomaly.*``), the per-tenant SLI book (``uda.tpu.slo.*``)
    and the optional OpenMetrics exposition
    (``uda.tpu.metrics.http.port``). Gated like the StatsReporter on
    the stats plane being on; returns whether the plane is armed.
    Callers: the bridge's ``_start_stats`` and ``ShuffleServer.start``
    — whichever runs first arms it for the process."""
    global _ARMED
    from uda_tpu.utils.metrics import stats_enabled_from_env

    if not (stats_enabled_from_env()
            or config.get("uda.tpu.stats.enable")):
        return False
    if not config.get("uda.tpu.ts.enable"):
        return False
    with _ARM_LOCK:
        timeseries.configure(
            interval_s=float(config.get("uda.tpu.ts.interval.s")),
            window=int(config.get("uda.tpu.ts.window")))
        timeseries.start()
        from uda_tpu.utils.anomaly import anomaly_engine
        anomaly_engine.arm_from_config(config, timeseries)
        from uda_tpu.tenant.sli import sli_book
        sli_book.arm_from_config(config, timeseries)
        port = int(config.get("uda.tpu.metrics.http.port"))
        if port:
            from uda_tpu.utils.openmetrics import metrics_http
            metrics_http.start(port)
        _ARMED = True
    return True


def disarm_observability_plane() -> None:
    """Tear the plane down (conftest hygiene, daemon stop): timer,
    detectors, SLI book and the exposition endpoint. Safe when never
    armed."""
    global _ARMED
    with _ARM_LOCK:
        try:
            from uda_tpu.utils.anomaly import anomaly_engine
            anomaly_engine.reset()
        except Exception:  # udalint: disable=UDA006 - teardown must
            pass  # be total even mid-import-failure
        try:
            from uda_tpu.tenant.sli import sli_book
            sli_book.reset()
        except Exception:  # udalint: disable=UDA006 - teardown total
            pass
        try:
            from uda_tpu.utils.openmetrics import metrics_http
            metrics_http.stop()
        except Exception:  # udalint: disable=UDA006 - teardown total
            pass
        timeseries.reset()
        _ARMED = False
